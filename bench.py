"""Headline benchmark: AlexNet-JAX training throughput on the allocated chip.

The reference's headline harness is the AlexNet pod running
``tf_cnn_benchmarks.py --model=alexnet`` with results read from pod logs
(/root/reference/example/pod/alexnet-gpu.yaml:16, README.md:45-67); it
publishes no numbers (SURVEY.md §6), so BASELINE.json records
``published: {}``.  When no baseline number exists, vs_baseline is null —
there is nothing honest to compare against.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Secondary numbers (Allocate p50 — the latency-sensitive kubelet RPC) ride
in "extra".
"""

from __future__ import annotations

import json
import os
import statistics
import time

import jax
import jax.numpy as jnp


def bench_alexnet(platform: str) -> float:
    """images/sec of the jit-compiled train step, synthetic data (one
    timing harness shared with the example pods' bench_main)."""
    from tpu_k8s_device_plugin.workloads.bench_main import run_single

    on_accel = platform != "cpu"
    # batch 2048 is the measured throughput knee on v5e-1 (25.2k img/s vs
    # 18k at 256; 4096 regresses) — large batches keep the MXU fed and
    # amortize the pooling/reshape memory traffic
    batch = 2048 if on_accel else 16
    warmup, steps = (3, 15) if on_accel else (1, 3)
    return run_single(batch, steps, warmup)


def bench_allocate_p50_us() -> float:
    """p50 latency of the kubelet Allocate path (in-memory, per SURVEY §3.3
    the precompute-at-init shape keeps this in microseconds)."""
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
    from tpu_k8s_device_plugin.types import DevicePluginContext

    root = os.path.join(os.path.dirname(__file__), "testdata", "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    ctx = DevicePluginContext("tpu", None)
    ids = [d.ID for d in impl.enumerate(ctx)][:4]
    req = pluginapi.AllocateRequest(
        container_requests=[pluginapi.ContainerAllocateRequest(devices_ids=ids)]
    )
    samples = []
    for _ in range(2000):
        t0 = time.perf_counter_ns()
        impl.allocate(ctx, req)
        samples.append((time.perf_counter_ns() - t0) / 1000.0)
    return statistics.median(samples)


def main() -> None:
    platform = jax.devices()[0].platform
    images_per_sec = bench_alexnet(platform)
    alloc_p50 = bench_allocate_p50_us()

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get(
                "alexnet_jax_images_per_sec"
            )
    except (OSError, ValueError):
        pass

    print(json.dumps({
        "metric": "alexnet_jax_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3) if baseline else None,
        "extra": {
            "platform": platform,
            "n_devices": len(jax.devices()),
            "allocate_p50_us": round(alloc_p50, 2),
        },
    }))


if __name__ == "__main__":
    main()
