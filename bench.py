"""Headline benchmark: AlexNet-JAX training throughput on the allocated chip.

The reference's headline harness is the AlexNet pod running
``tf_cnn_benchmarks.py --model=alexnet`` with results read from pod logs
(/root/reference/example/pod/alexnet-gpu.yaml:16, README.md:45-67); it
publishes no numbers (SURVEY.md §6), so BASELINE.json records
``published: {}``.  When no baseline number exists, vs_baseline is null —
there is nothing honest to compare against.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Secondary numbers ride in "extra": MFU (XLA-counted FLOPs over the chip's
published bf16 peak) and Allocate p50/p99 — the latency-sensitive kubelet
RPC, sampled heavily enough to be stable across runs (VERDICT r1 flagged a
1.6x swing at 2000 samples).
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time


def _init_watchdog(timeout_s: int | None = None) -> threading.Timer:
    """Emit a structured outage record and exit if backend init hangs:
    when the remote TPU tunnel is down, ``jax.devices()`` blocks
    indefinitely in NATIVE code (observed for hours in rounds 2-3) and
    the round's benchmark artifact would be an empty hang.  A timer
    thread still runs while the main thread is stuck, prints the
    record, and hard-exits.  Fast failures (ImportError, backend
    errors) are NOT masked — they traceback normally in the main
    thread; healthy init just cancels the timer (zero extra cost).
    The threshold is a judgment call between outage and slow-but-alive
    init (healthy axon init is well under a minute; outages last
    hours): BENCH_INIT_TIMEOUT overrides the 180s default when the
    transport is known to be slower."""
    if timeout_s is None:
        timeout_s = int(os.environ.get("BENCH_INIT_TIMEOUT", "180"))

    def fire():
        # a down tunnel is an environment outage, not a benchmark
        # failure: emit a structured skip record (machine-readable
        # "skipped" key, queued work named) and exit 0 so the round's
        # artifact says "not measurable today" instead of "broken"
        # (BENCH r2-r5 all recorded failed runs for what was really
        # the same outage)
        print(json.dumps({
            "metric": "alexnet_jax_images_per_sec_per_chip",
            "value": None,
            "unit": "images/sec",
            "vs_baseline": None,
            "skipped": "tunnel_down",
            "extra": {
                "reason": "accelerator backend init exceeded "
                          f"{timeout_s}s (TPU tunnel down, or raise "
                          "BENCH_INIT_TIMEOUT for a slow transport)",
                "queued_phases": ["probe", "alexnet_batch_sweep",
                                  "fleet_scale_out_2to4"],
                "requeue": "tools/measure_r3.py",
            },
        }), flush=True)
        os._exit(0)

    t = threading.Timer(timeout_s, fire)
    t.daemon = True
    t.start()
    return t


_watchdog = _init_watchdog() if __name__ == "__main__" else None

import jax  # noqa: E402  (under the watchdog by design)

if _watchdog is not None:
    jax.devices()  # the call that hangs when the tunnel is down
    _watchdog.cancel()


def bench_alexnet(platform: str):
    """(images/sec, batch, flops_per_step) of the jit-compiled train
    step, synthetic data (one timing harness shared with the example
    pods' bench_main)."""
    from tpu_k8s_device_plugin.workloads.bench_main import run_single

    on_accel = platform != "cpu"
    # batch 4096 is the measured throughput knee on v5e-1 with the
    # space-to-depth first conv (29.3k img/s vs 27.3k at 2048, 28.0k at
    # 3072, flat 28.2-28.5k through 8192) — large batches keep the MXU
    # fed and amortize the pooling memory traffic
    batch = 4096 if on_accel else 16
    warmup, steps, rounds = (3, 10, 3) if on_accel else (1, 3, 1)
    ips, flops = run_single(
        batch, steps, warmup, want_flops=True, rounds=rounds
    )
    return ips, batch, flops


def bench_allocate_us():
    """p50/p99 latency of the kubelet Allocate path (in-memory, per SURVEY
    §3.3 the precompute-at-init shape keeps this in microseconds)."""
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
    from tpu_k8s_device_plugin.types import DevicePluginContext

    root = os.path.join(os.path.dirname(__file__), "testdata", "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    ctx = DevicePluginContext("tpu", None)
    ids = [d.ID for d in impl.enumerate(ctx)][:4]
    req = pluginapi.AllocateRequest(
        container_requests=[pluginapi.ContainerAllocateRequest(devices_ids=ids)]
    )
    for _ in range(500):  # warm caches/allocator before sampling
        impl.allocate(ctx, req)
    # timeit-style de-noising: sample in rounds and report the best round's
    # percentiles.  A shared host's scheduler jitter inflates whole rounds;
    # the minimum round median is the reproducible steady-state figure
    # (VERDICT r1 flagged a 1.6x swing between runs of a single batch).
    best = None
    for _ in range(5):
        samples = []
        for _ in range(2000):
            t0 = time.perf_counter_ns()
            impl.allocate(ctx, req)
            samples.append((time.perf_counter_ns() - t0) / 1000.0)
        samples.sort()
        round_stats = (
            statistics.median(samples),
            samples[int(len(samples) * 0.99)],
        )
        if best is None or round_stats[0] < best[0]:
            best = round_stats
    return best


def chip_peak_flops() -> float | None:
    """Published bf16 peak of the chip actually under the benchmark."""
    from tpu_k8s_device_plugin.tpu.topology import spec_for_device_kind

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        return None
    spec = spec_for_device_kind(getattr(dev, "device_kind", "") or "")
    return float(spec.peak_bf16_flops) if spec else None


def main() -> None:
    platform = jax.devices()[0].platform
    images_per_sec, batch, flops_per_step = bench_alexnet(platform)
    alloc_p50, alloc_p99 = bench_allocate_us()

    mfu = None
    peak = chip_peak_flops()
    if flops_per_step and peak:
        mfu = (flops_per_step / batch) * images_per_sec / peak

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get(
                "alexnet_jax_images_per_sec"
            )
    except (OSError, ValueError):
        pass

    print(json.dumps({
        "metric": "alexnet_jax_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / baseline, 3) if baseline else None,
        "extra": {
            "platform": platform,
            "n_devices": len(jax.devices()),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "flops_per_image": (
                round(flops_per_step / batch) if flops_per_step else None
            ),
            "batch": batch,
            "allocate_p50_us": round(alloc_p50, 2),
            "allocate_p99_us": round(alloc_p99, 2),
        },
    }))


if __name__ == "__main__":
    main()
