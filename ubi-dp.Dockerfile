# UBI-based device-plugin image (≈ ubi-dp.Dockerfile): Red Hat base for
# OpenShift environments; defaults the health pulse on (-pulse=30 in the
# reference's UBI variant).
FROM registry.access.redhat.com/ubi9/python-311 AS builder
ARG GIT_DESCRIBE=unknown
USER 0
RUN dnf install -y gcc-c++ make && dnf clean all
WORKDIR /src
COPY pyproject.toml README.md ./
COPY tpu_k8s_device_plugin/ tpu_k8s_device_plugin/
COPY native/ native/
RUN make -C native/tpuprobe \
    && pip install --no-cache-dir --prefix=/install . \
    && cp tpu_k8s_device_plugin/hostinfo/libtpuprobe.so \
         /install/lib/python3.11/site-packages/tpu_k8s_device_plugin/hostinfo/ \
    && echo "${GIT_DESCRIBE}" > /install/git-describe

FROM registry.access.redhat.com/ubi9/python-311 AS labeller
COPY --from=builder /install /usr/local
ENV PYTHONPATH=/usr/local/lib/python3.11/site-packages
ENTRYPOINT ["/usr/local/bin/k8s-tpu-node-labeller"]

# plugin image last so it is the default target (≈ ubi-dp.Dockerfile;
# the labeller stage above ≈ the reference's ubi-labeller.Dockerfile)
FROM registry.access.redhat.com/ubi9/python-311 AS dp
COPY --from=builder /install /usr/local
ENV PYTHONPATH=/usr/local/lib/python3.11/site-packages
ENTRYPOINT ["/usr/local/bin/k8s-tpu-device-plugin"]
CMD ["--pulse=30"]
