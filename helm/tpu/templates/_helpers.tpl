{{/* Common labels */}}
{{- define "tpu-device-plugin.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
{{- end }}
