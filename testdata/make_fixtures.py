#!/usr/bin/env python3
"""Generate the fixture sysfs/tpu-env trees under testdata/.

The reference's tests run every discovery/allocator function against captured
sysfs trees from real machines (testdata/topology-parsing*/README.md documents
the `find ... cat` capture recipe).  TPU hosts in this build's CI have no
/sys/class/accel, so the trees are *synthesised* to the same shape a real
v5e / v5p host exposes; this script is the reproducible "capture recipe".

Run from the repo root:  python testdata/make_fixtures.py
"""

import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def w(path, content):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(content if content.endswith("\n") else content + "\n")


def ln(link, target):
    os.makedirs(os.path.dirname(link), exist_ok=True)
    if os.path.islink(link):
        os.remove(link)
    os.symlink(target, link)


def make_host(
    name,
    n_chips,
    device_id,
    tpu_env,
    numa_split=True,
    firmware="2.12.1",
    driver_version="1.8.0",
    with_accel_class=True,
    driver=None,            # bind PCI devs to this driver (vfio-pci / tpu-vf)
    virtfns_per_pf=0,       # SR-IOV VFs hanging off each PF
):
    root = os.path.join(HERE, name)
    if os.path.isdir(root):
        shutil.rmtree(root)
    sys_root = os.path.join(root, "sys")

    for i in range(n_chips):
        addr = f"0000:00:{4 + i:02x}.0"
        pci_dir = os.path.join(sys_root, "devices", "pci0000:00", addr)
        w(os.path.join(pci_dir, "vendor"), "0x1ae0")
        w(os.path.join(pci_dir, "device"), device_id)
        w(os.path.join(pci_dir, "class"), "0x120000")
        numa = (i >= n_chips // 2) if numa_split and n_chips > 1 else 0
        w(os.path.join(pci_dir, "numa_node"), str(int(numa)))
        w(os.path.join(pci_dir, "firmware_version"), firmware)
        # per-chip driver health attrs (the granular state the exporter's
        # probe reads; a wedged chip flips chip_state / bumps the UE count
        # while its chardev still opens fine)
        w(os.path.join(pci_dir, "chip_state"), "alive")
        w(os.path.join(pci_dir, "uncorrectable_errors"), "0")
        # iommu group per chip
        group = str(8 + i)
        w(os.path.join(sys_root, "kernel", "iommu_groups", group, "type"),
          "DMA")
        ln(os.path.join(pci_dir, "iommu_group"),
           f"../../../kernel/iommu_groups/{group}")
        # bus/pci/devices entry
        ln(os.path.join(sys_root, "bus", "pci", "devices", addr),
           f"../../../devices/pci0000:00/{addr}")
        if with_accel_class:
            accel_dir = os.path.join(sys_root, "class", "accel", f"accel{i}")
            w(os.path.join(accel_dir, "dev"), f"236:{i}")
            ln(os.path.join(accel_dir, "device"),
               f"../../../devices/pci0000:00/{addr}")
            # stand-in for the /dev/accelN char device node
            w(os.path.join(root, "dev", f"accel{i}"), "")
        if driver:
            drv_dir = os.path.join(sys_root, "bus", "pci", "drivers", driver)
            os.makedirs(drv_dir, exist_ok=True)
            ln(os.path.join(pci_dir, "driver"),
               f"../../../bus/pci/drivers/{driver}")
            ln(os.path.join(drv_dir, addr), f"../../devices/pci0000:00/{addr}")
        for vf in range(virtfns_per_pf):
            vf_addr = f"0000:01:{4 + i:02x}.{vf + 1}"
            vf_dir = os.path.join(sys_root, "devices", "pci0000:00", addr,
                                  f"virtfn{vf}_dev")
            # real sysfs puts VFs at bus level; model the PF->VF link precisely:
            vf_real = os.path.join(sys_root, "devices", "pci0000:01", vf_addr)
            w(os.path.join(vf_real, "vendor"), "0x1ae0")
            w(os.path.join(vf_real, "device"), device_id)
            vf_group = str(100 + i * 8 + vf)
            w(os.path.join(sys_root, "kernel", "iommu_groups", vf_group,
                           "type"), "DMA")
            ln(os.path.join(vf_real, "iommu_group"),
               f"../../../kernel/iommu_groups/{vf_group}")
            ln(os.path.join(sys_root, "bus", "pci", "devices", vf_addr),
               f"../../../devices/pci0000:01/{vf_addr}")
            ln(os.path.join(pci_dir, f"virtfn{vf}"),
               f"../../pci0000:01/{vf_addr}")
            del vf_dir

    # driver module info
    if driver == "tpu-vf":
        w(os.path.join(sys_root, "module", "tpu_vf", "version"), driver_version)
        w(os.path.join(sys_root, "module", "tpu_vf", "srcversion"),
          "A1B2C3D4E5F60718TPUVF")
    else:
        w(os.path.join(sys_root, "module", "tpu", "version"), driver_version)
        w(os.path.join(sys_root, "module", "tpu", "srcversion"),
          "9F8E7D6C5B4A3921TPU")

    if tpu_env is not None:
        w(os.path.join(root, "run", "tpu", "tpu-env"), tpu_env)
    return root


def main():
    # v5e single host, full 8-chip pod-slice on one machine (2x4 mesh).
    make_host(
        "v5e-8", 8, "0x0062",
        "ACCELERATOR_TYPE: 'v5litepod-8'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,4,1'\n"
        "HOST_BOUNDS: '1,1,1'\n"
        "WORKER_ID: '0'\n",
    )
    # One host (worker 0) of a two-host v5e-16 slice (4x4 global mesh:
    # each host holds a 2x4 sub-grid, hosts side by side on the x axis).
    make_host(
        "v5e-16-host0", 8, "0x0062",
        "ACCELERATOR_TYPE: 'v5litepod-16'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,4,1'\n"
        "HOST_BOUNDS: '2,1,1'\n"
        "WORKER_ID: '0'\n",
    )
    # Worker 1 of the same two-host v5e-16 slice: identical local grid,
    # global coords offset by one host on the x axis.  Exercises the
    # multi-host identity paths from the second worker's perspective
    # (VERDICT r1 #5 — the reference's fixture breadth is its testing
    # backbone, /root/reference/testdata/).
    make_host(
        "v5e-16-host1", 8, "0x0062",
        "ACCELERATOR_TYPE: 'v5litepod-16'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,4,1'\n"
        "HOST_BOUNDS: '2,1,1'\n"
        "WORKER_ID: '1'\n",
    )
    # v5p host: 4 chips (2x2x1), 2 TensorCores each, whole-chip granularity.
    make_host(
        "v5p-8", 4, "0x0063",
        "ACCELERATOR_TYPE: 'v5p-8'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,2,1'\n"
        "HOST_BOUNDS: '1,1,1'\n"
        "WORKER_ID: '0'\n",
    )
    # Same host partitioned per-core (the MI300-CPX analog).
    make_host(
        "v5p-8-core", 4, "0x0063",
        "ACCELERATOR_TYPE: 'v5p-8'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,2,1'\n"
        "HOST_BOUNDS: '1,1,1'\n"
        "WORKER_ID: '0'\n"
        "TPU_PARTITION_MODE: 'core'\n",
    )
    # Heterogeneous: chips 2,3 per-core, chips 0,1 whole-chip (mixed naming).
    make_host(
        "v5p-8-hetero", 4, "0x0063",
        "ACCELERATOR_TYPE: 'v5p-8'\n"
        "CHIPS_PER_HOST_BOUNDS: '2,2,1'\n"
        "HOST_BOUNDS: '1,1,1'\n"
        "WORKER_ID: '0'\n"
        "TPU_PARTITION_MODE_OVERRIDES: '2:core,3:core'\n",
    )
    # No tpu-env metadata at all: discovery must fall back to sysfs only.
    make_host("v5e-4-nometa", 4, "0x0062", None, numa_split=False)
    # PF passthrough host: 4 chips bound to vfio-pci, no accel class.
    make_host(
        "vfio-pf", 4, "0x0063", None,
        with_accel_class=False, driver="vfio-pci",
    )
    # SR-IOV host: 2 PFs on tpu-vf driver, 2 VFs each, no accel class.
    make_host(
        "vfio-vf", 2, "0x0062", None,
        with_accel_class=False, driver="tpu-vf", virtfns_per_pf=2,
    )
    print("fixtures written under", HERE)


if __name__ == "__main__":
    sys.exit(main())
