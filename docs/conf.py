# Sphinx configuration (≈ the reference's docs/conf.py ReadTheDocs setup).
project = "k8s-tpu-device-plugin"
author = "k8s-tpu-device-plugin contributors"
copyright = "2026, " + author

extensions = ["myst_parser"]
source_suffix = {".md": "markdown", ".rst": "restructuredtext"}
master_doc = "index"

html_theme = "sphinx_rtd_theme"
exclude_patterns = ["_build"]
