"""The project-invariant rules (see ``docs/user-guide/
static-analysis.md`` for the catalog with worked examples).

Each rule encodes a convention PRs 1-6 established but nothing
enforced until now:

C1 lock-order        the inter-module lock-acquisition graph is acyclic
C2 blocking-under-lock  no sleeps/subprocess/socket/device-sync calls
                     while any lock is held
C3 thread-lifecycle  every Thread is daemonized or has a join path
R1 resilience-coverage  network/subprocess boundaries route through
                     RetryPolicy/CircuitBreaker/Watchdog/a fault hook
R2 silent-swallow    no ``except Exception`` without a log line, a
                     re-raise, or resilience.suppressed() accounting
O1 metric-definition metric families are built through a Registry with
                     promlint-compatible names and bounded labels
O2 alert-rule-expr   literal alert-rule expressions reference metric
                     families some Registry in the project defines
D1 unseeded-nondeterminism  no bare ``random.*`` / ``time.time()``
                     inside the declared deterministic paths
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import (
    FileContext,
    Finding,
    LockId,
    Project,
    Rule,
    register,
)

# -- shared lock-scope walker ------------------------------------------------


def _walk_lock_scopes(
    ctx: FileContext,
) -> Iterator[Tuple[str, ast.AST, Tuple[LockId, ...],
                    Optional[ast.FunctionDef]]]:
    """Yield ``("acquire", with_item_expr, held_before, func)`` for each
    lock acquisition and ``("call", call_node, held, func)`` for each
    call made while at least one lock is held.  Nested function bodies
    restart with an empty held set (a closure defined under a lock does
    not execute under it)."""

    def visit(node: ast.AST, held: Tuple[LockId, ...],
              func: Optional[ast.FunctionDef]
              ) -> Iterator[Tuple[str, ast.AST, Tuple[LockId, ...],
                                  Optional[ast.FunctionDef]]]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in node.body:
                yield from visit(child, (), node)
            return
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                yield from visit(child, (), func)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[LockId] = []
            for item in node.items:
                lock = ctx.lock_for_with_item(item.context_expr, func)
                if lock is not None:
                    yield ("acquire", item.context_expr,
                           held + tuple(acquired), func)
                    acquired.append(lock)
                else:
                    # the context expression itself may contain calls
                    # made while the already-held locks are held
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call) and held:
                            yield ("call", sub, held, func)
            new_held = held + tuple(acquired)
            for child in node.body:
                yield from visit(child, new_held, func)
            return
        if isinstance(node, ast.Call) and held:
            yield ("call", node, held, func)
        for child in ast.iter_child_nodes(node):
            yield from visit(child, held, func)

    for top in ctx.tree.body:
        yield from visit(top, (), None)


def _held_locks_of(expr_event: Tuple[str, ast.AST, Tuple[LockId, ...],
                                     Optional[ast.FunctionDef]]
                   ) -> Tuple[LockId, ...]:
    return expr_event[2]


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def _callee(call: ast.Call, ctx: FileContext
            ) -> Tuple[Optional[str], str]:
    """(class hint, bare name) of the called function.

    class hint '' = same-module function; a class name = a ``self.``
    method of that class; None = method resolved by name only."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return "", fn.id
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.value.id == "self":
            cls = ctx.enclosing_class(call)
            return (cls.name if cls is not None else None), fn.attr
        return None, fn.attr
    return None, ""


# -- C1: lock-order ----------------------------------------------------------


@register
class LockOrderRule(Rule):
    """Build the project-wide lock-acquisition graph (lock A held while
    lock B is acquired => edge A->B, including one level of
    interprocedural edges through project-local calls) and flag every
    cycle: two threads taking the locks in opposite orders is the
    classic deadlock, and nothing short of a graph check catches it
    across modules."""

    id = "C1"
    name = "lock-order"
    doc = "inter-module lock acquisition graph must be acyclic"

    # method names so generic that by-name resolution would wire
    # unrelated locks together (dict.get, list.append, Queue.put, ...)
    _AMBIGUOUS = {
        "get", "put", "append", "add", "set", "pop", "update", "start",
        "stop", "close", "run", "send", "write", "read", "join",
        "wait", "clear", "items", "values", "keys", "copy",
    }

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        acquired_by_func: Dict[ast.AST, List[LockId]] = {}
        for kind, node, held, func in _walk_lock_scopes(ctx):
            if kind == "acquire":
                lock = ctx.lock_for_with_item(node, func)
                if lock is None:
                    continue
                if func is not None:
                    acquired_by_func.setdefault(func, []).append(lock)
                for h in held:
                    if h == lock:
                        continue  # re-entry is C2/B territory, not order
                    project.lock_edges.setdefault(
                        (h, lock), (ctx.relpath, node.lineno))
            else:
                assert isinstance(node, ast.Call)
                cls_hint, name = _callee(node, ctx)
                if not name or name in self._AMBIGUOUS:
                    continue
                for h in held:
                    project.deferred_calls.append(
                        (h, name, cls_hint, ctx.relpath, node.lineno))
        # the function index the deferred edges resolve against
        for node, locks in acquired_by_func.items():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            cls = ctx.enclosing_class(node)
            project.functions.setdefault(node.name, []).append(
                (ctx.qualname(node),
                 cls.name if cls is not None else None,
                 list(dict.fromkeys(locks))))
        return []

    def finalize(self, project: Project) -> List[Finding]:
        edges: Dict[Tuple[LockId, LockId], Tuple[str, int]] = dict(
            project.lock_edges)
        for held, name, cls_hint, relpath, lineno in \
                project.deferred_calls:
            candidates = project.functions.get(name, [])
            if cls_hint == "":
                matched = [c for c in candidates if c[1] is None]
            elif cls_hint is not None:
                matched = [c for c in candidates if c[1] == cls_hint]
            else:
                matched = candidates
            if not matched or len(matched) > 3:
                continue  # unresolvable or too ambiguous to trust
            for _, _, locks in matched:
                for lock in locks:
                    if lock == held:
                        continue
                    edges.setdefault((held, lock), (relpath, lineno))
        adj: Dict[LockId, List[LockId]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        findings: List[Finding] = []
        for cycle in _find_cycles(adj):
            witness = edges.get((cycle[0], cycle[1])) or next(
                iter(edges.values()))
            path = " -> ".join(l.key for l in cycle + [cycle[0]])
            findings.append(Finding(
                self.id, witness[0], witness[1],
                f"lock-order cycle: {path} (two threads taking these "
                "locks in opposite orders deadlock)"))
        return findings


def _find_cycles(adj: Dict[LockId, List[LockId]]
                 ) -> List[List[LockId]]:
    """Minimal cycle enumeration: one representative cycle per
    strongly-connected component of size > 1 (Tarjan, iterative)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    counter = [0]
    sccs: List[List[LockId]] = []

    def strongconnect(root: LockId) -> None:
        work: List[Tuple[LockId, int]] = [(root, 0)]
        while work:
            v, i = work.pop()
            if i == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            neighbors = adj.get(v, [])
            for j in range(i, len(neighbors)):
                w = neighbors[j]
                if w not in index:
                    work.append((v, j + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc: List[LockId] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(list(reversed(scc)))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])

    for v in sorted(adj, key=lambda l: l.key):
        if v not in index:
            strongconnect(v)
    return sccs


# -- C2: blocking-under-lock -------------------------------------------------


@register
class BlockingUnderLockRule(Rule):
    """A lock held across a sleep, a subprocess, a socket connect, or a
    device sync (``block_until_ready``) serializes every other thread
    behind an operation with unbounded latency — the exact shape the
    PR-5 watchdog exists to contain at runtime; this catches it at
    review time."""

    id = "C2"
    name = "blocking-under-lock"
    doc = "no unbounded blocking calls while a lock is held"

    _DOTTED = {
        "time.sleep",
        "socket.create_connection",
        "subprocess.run", "subprocess.call", "subprocess.check_call",
        "subprocess.check_output", "subprocess.Popen",
        "urllib.request.urlopen",
    }
    _BARE = {"sleep", "urlopen"}
    _ATTRS = {"block_until_ready"}

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for kind, node, held, _func in _walk_lock_scopes(ctx):
            if kind != "call" or not held:
                continue
            assert isinstance(node, ast.Call)
            dotted = _dotted(node.func)
            blocked = None
            if dotted in self._DOTTED:
                blocked = dotted
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in self._BARE \
                    and dotted in self._BARE:
                blocked = dotted
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in self._ATTRS:
                    blocked = f"...{attr}()"
                elif attr in ("wait", "join") and not node.args \
                        and not node.keywords:
                    # no-timeout wait()/join() block forever;
                    # str.join always takes an argument, so a bare
                    # .join() here really is a thread/process join
                    blocked = f"unbounded ...{attr}()"
            if blocked is not None:
                locks = ", ".join(h.key for h in held)
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"blocking call {blocked} while holding "
                    f"{locks}: every thread contending that lock "
                    "stalls behind it"))
        return findings


# -- C3: thread-lifecycle ----------------------------------------------------


@register
class ThreadLifecycleRule(Rule):
    """Every ``threading.Thread`` must be daemonized or reachable from
    an owner's stop()/join() path: a forgotten non-daemon thread turns
    clean shutdown into a hang (the manager's stop() joins _threads
    with a bound for exactly this reason)."""

    id = "C3"
    name = "thread-lifecycle"
    doc = "threads are daemonized or joined"

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        joined, daemonized = self._join_and_daemon_sets(ctx)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func) not in ("threading.Thread", "Thread"):
                continue
            if _dotted(node.func) == "Thread" \
                    and "threading" not in ctx.source:
                continue
            if self._has_daemon_kwarg(node):
                continue
            target = self._creation_target(ctx, node)
            if target is not None and (target in joined
                                       or target in daemonized):
                continue
            where = f" (assigned to {target!r})" if target else ""
            findings.append(Finding(
                self.id, ctx.relpath, node.lineno,
                f"Thread{where} is neither daemon=True nor joined "
                "anywhere in this module: it will outlive its owner's "
                "stop() and can hang interpreter shutdown"))
        return findings

    @staticmethod
    def _has_daemon_kwarg(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon":
                # daemon=<expr> counts: a computed daemon-ness is a
                # deliberate choice, not a forgotten default
                if isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
                return True
        return False

    def _creation_target(self, ctx: FileContext,
                         call: ast.Call) -> Optional[str]:
        """The name the created thread lands in: assignment target,
        the container a list-comprehension fills, or the container of
        an ``X.append(Thread(...))``."""
        node: ast.AST = call
        while True:
            parent = ctx.parents.get(node)
            if parent is None:
                return None
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    name = _target_name(tgt)
                    if name:
                        return name
                return None
            if isinstance(parent, ast.Call) and node in parent.args \
                    and isinstance(parent.func, ast.Attribute) \
                    and parent.func.attr == "append":
                return _target_name(parent.func.value)
            if isinstance(parent, (ast.ListComp, ast.GeneratorExp,
                                   ast.List, ast.Tuple, ast.IfExp)):
                node = parent
                continue
            if isinstance(parent, (ast.FunctionDef, ast.Module,
                                   ast.ClassDef)):
                return None
            node = parent

    @staticmethod
    def _join_and_daemon_sets(ctx: FileContext
                              ) -> Tuple[Set[str], Set[str]]:
        joined: Set[str] = set()
        daemonized: Set[str] = set()
        # for-loop variables mapped to the containers they iterate: a
        # `for t in threads: t.join()` marks `threads` joined.  One
        # variable may drive several loops (warm_threads, then
        # threads), so the map holds ALL containers per variable.
        loop_containers: Dict[str, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name):
                container = _target_name(node.iter)
                if container:
                    loop_containers.setdefault(
                        node.target.id, set()).add(container)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                name = _target_name(node.func.value)
                if name:
                    joined.add(name)
                    joined.update(loop_containers.get(name, ()))
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and node.targets[0].attr == "daemon":
                name = _target_name(node.targets[0].value)
                if name:
                    daemonized.add(name)
        return joined, daemonized


def _target_name(node: ast.AST) -> Optional[str]:
    """'x' for Name x, 'attr' for self.attr/obj.attr (the attribute
    name alone — join sites and creation sites share it)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# -- R1: resilience-coverage -------------------------------------------------


@register
class ResilienceCoverageRule(Rule):
    """PR 5's contract: every network/subprocess boundary routes
    through RetryPolicy/CircuitBreaker/Watchdog or carries a registered
    fault hook, so chaos runs can provoke its failure path.  A naked
    boundary is untested recovery by definition."""

    id = "R1"
    name = "resilience-coverage"
    doc = "network/subprocess call sites route through the resilience layer"

    _BOUNDARIES = {
        "subprocess.run", "subprocess.call", "subprocess.check_call",
        "subprocess.check_output", "subprocess.Popen",
        "urllib.request.urlopen",
        "socket.create_connection",
        "http.client.HTTPConnection", "http.client.HTTPSConnection",
        "grpc.insecure_channel", "grpc.secure_channel",
    }
    _EVIDENCE_NAMES = {
        "RetryPolicy", "CircuitBreaker", "Watchdog", "InjectedFault",
        "suppressed",
    }
    _EVIDENCE_SUBSTR = ("retry", "breaker", "watchdog", "policy",
                        "fault")

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted not in self._BOUNDARIES:
                continue
            func = ctx.enclosing_function(node)
            if func is not None and self._has_evidence(func):
                continue
            cls = ctx.enclosing_class(node)
            if cls is not None and self._has_evidence(cls):
                continue
            if func is None and cls is None \
                    and self._has_evidence(ctx.tree):
                # module-level boundary (import-time probe):
                # module-wide evidence is the best anchor available
                continue
            findings.append(Finding(
                self.id, ctx.relpath, node.lineno,
                f"boundary call {dotted} has no RetryPolicy/"
                "CircuitBreaker/Watchdog/fault-hook in its "
                "enclosing scope: its failure path cannot be "
                "provoked by the chaos harness"))
        return findings

    def _has_evidence(self, scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            ident = None
            if isinstance(node, ast.Name):
                ident = node.id
            elif isinstance(node, ast.Attribute):
                ident = node.attr
            if ident is None:
                continue
            if ident in self._EVIDENCE_NAMES:
                return True
            low = ident.lower()
            if any(s in low for s in self._EVIDENCE_SUBSTR):
                return True
        return False


# -- R2: silent-swallow ------------------------------------------------------


@register
class SilentSwallowRule(Rule):
    """PR 5 fixed ~30 silent ``except Exception: pass`` sites by hand;
    this rule keeps them fixed.  A broad handler must log, re-raise, or
    account through ``resilience.suppressed()`` /
    ``tpu_suppressed_errors_total`` — a fault that vanishes is a fault
    that floods unnoticed."""

    id = "R2"
    name = "silent-swallow"
    doc = "broad except handlers must log, re-raise, or count"

    _LOG_ATTRS = {"debug", "info", "warning", "warn", "error",
                  "exception", "critical", "log", "handle_error",
                  "abort"}

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._handles(node.body):
                continue
            findings.append(Finding(
                self.id, ctx.relpath, node.lineno,
                "broad except handler swallows silently: log it, "
                "re-raise, or route through resilience.suppressed() "
                "so tpu_suppressed_errors_total sees it"))
        return findings

    @staticmethod
    def _is_broad(type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare except
        names: List[str] = []
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        for n in nodes:
            if isinstance(n, ast.Name):
                names.append(n.id)
            elif isinstance(n, ast.Attribute):
                names.append(n.attr)
        return any(n in ("Exception", "BaseException") for n in names)

    def _handles(self, body: Sequence[ast.stmt]) -> bool:
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Raise):
                    return True
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr in self._LOG_ATTRS:
                        return True
                    if fn.attr == "inc":
                        return True  # counter accounting
                    if fn.attr == "suppressed":
                        return True
                elif isinstance(fn, ast.Name):
                    if fn.id in ("suppressed", "print"):
                        return True
        return False


# -- O1: metric-definition ---------------------------------------------------


@register
class MetricDefinitionRule(Rule):
    """Metric families must be built through a Registry (get-or-create
    + one renderer: the invariant PR 3 introduced), with names promlint
    would accept at the DEFINITION site and label sets whose
    cardinality is bounded — a request-id label is a series-per-request
    memory leak on every scrape path."""

    id = "O1"
    name = "metric-definition"
    doc = "families built via Registry, promlint-compatible, bounded labels"

    _CTORS = {"Counter", "Gauge", "Histogram"}
    _METHODS = {"counter", "gauge", "histogram"}
    _HIGH_CARDINALITY = {
        "request_id", "trace_id", "span_id", "rid", "uid", "url",
        "path", "id", "pod", "pod_name", "container_id", "timestamp",
        "le",
        # request-supplied identities (PR 12 review): a caller-chosen
        # value must be BOUNDED before it becomes a label — the SLO
        # layer maps unknown class/tenant names to 'other' for exactly
        # this reason; these raw forms never belong on a family
        "user", "user_id", "session", "session_id", "prompt",
        "tenant_id", "slo_class_raw",
        # continuous profiler / incident bundles (PR 19): stacks and
        # bundle identities are unbounded by construction — they live
        # in the profiler ring and on disk, NEVER as label values (the
        # profiler exports only bounded meta-metrics for this reason)
        "stack", "frame", "func", "function", "thread", "thread_name",
        "bundle", "bundle_id", "incident", "incident_id",
    }
    # tpu_slo_* label values (class/tenant) are only bounded because
    # SLOAccountant maps unknown names to 'other' before they touch a
    # child; defining one of these families anywhere else would let a
    # request-supplied string mint series, so the module is the bound
    _SLO_OWNER = "obs.slo"
    _NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    _LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        in_obs = ".obs." in f".{ctx.module_name}." \
            or ctx.module_name.endswith(".obs")
        imports_obs = self._imports_obs(ctx)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # direct family construction outside the obs package
            if not in_obs and imports_obs:
                ctor = None
                if isinstance(fn, ast.Name) and fn.id in self._CTORS:
                    ctor = fn.id
                elif isinstance(fn, ast.Attribute) \
                        and fn.attr in self._CTORS \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id == "obs":
                    ctor = fn.attr
                if ctor is not None:
                    findings.append(Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"obs.{ctor} constructed directly: build "
                        "families via Registry.counter()/gauge()/"
                        "histogram() so get-or-create dedup and the "
                        "one renderer apply"))
                    continue
            # definition-site lint on registry.counter/gauge/histogram
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in self._METHODS):
                continue
            if not node.args or not isinstance(node.args[0],
                                               ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if not name.startswith("tpu_"):
                # the project namespace; also filters unrelated
                # .counter()-shaped calls on non-registry objects
                continue
            if not self._NAME_RE.match(name):
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"metric name {name!r} is not promlint-valid"))
            if fn.attr == "counter" and not name.endswith("_total"):
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"counter {name!r} must end in '_total' "
                    "(promlint C1 at the definition site)"))
            if name.startswith("tpu_slo_") \
                    and not ctx.module_name.endswith(self._SLO_OWNER):
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"family {name!r} defined outside "
                    f"{self._SLO_OWNER}: tpu_slo_* class/tenant "
                    "label values are only bounded because "
                    "SLOAccountant maps unknown names to 'other' — "
                    "define SLO families through it"))
            for label, lineno in self._labelnames(node):
                if not self._LABEL_RE.match(label):
                    findings.append(Finding(
                        self.id, ctx.relpath, lineno,
                        f"label {label!r} on {name} is not a valid "
                        "Prometheus label name"))
                if label in self._HIGH_CARDINALITY:
                    findings.append(Finding(
                        self.id, ctx.relpath, lineno,
                        f"label {label!r} on {name} is unbounded-"
                        "cardinality (one series per value): carry it "
                        "in an exemplar or the flight recorder, not a "
                        "label"))
        return findings

    @staticmethod
    def _imports_obs(ctx: FileContext) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and "obs" in node.module.split("."):
                return True
            if isinstance(node, ast.ImportFrom) \
                    and any(a.name == "obs" for a in node.names):
                return True
        return False

    @staticmethod
    def _labelnames(call: ast.Call
                    ) -> List[Tuple[str, int]]:
        candidates: List[ast.AST] = []
        if len(call.args) >= 3:
            candidates.append(call.args[2])
        for kw in call.keywords:
            if kw.arg == "labelnames":
                candidates.append(kw.value)
        out: List[Tuple[str, int]] = []
        for cand in candidates:
            if isinstance(cand, (ast.Tuple, ast.List)):
                for elt in cand.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        out.append((elt.value, elt.lineno))
        return out


# -- O2: alert-rule-expr ------------------------------------------------------


@register
class AlertRuleExprRule(Rule):
    """Every literal alert-rule expression must reference a metric
    family some Registry in the project defines — an alert over a
    misspelled family evaluates to "no data" forever and the page it
    was supposed to send never comes.  Expressions built at runtime
    (the burn-rate f-strings) validate at load instead; this rule
    covers the hand-written literals, where a typo survives review."""

    id = "O2"
    name = "alert-rule-expr"
    doc = "literal alert-rule exprs reference Registry-defined families"

    _DEFINERS = {"counter": (), "gauge": (),
                 "histogram": ("_bucket", "_sum", "_count")}
    # the tsdb grammar, statically: fn(name[w]) | hq(q, name[w]) | name
    _EXPR_RES = (
        re.compile(r"^\s*(?:rate|increase|avg_over_time|min_over_time"
                   r"|max_over_time)\s*\(\s*([a-zA-Z_:][a-zA-Z0-9_:]*)"),
        re.compile(r"^\s*histogram_quantile\s*\(\s*[0-9.]+\s*,"
                   r"\s*([a-zA-Z_:][a-zA-Z0-9_:]*)"),
        re.compile(r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(?:\{|$)"),
    )
    _RULE_CTORS = ("AlertCondition", "threshold_rule")

    def finalize(self, project: Project) -> List[Finding]:
        defined: Set[str] = set()
        for ctx in project.contexts:
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._DEFINERS):
                    continue
                if not (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                name = node.args[0].value
                defined.add(name)
                for suffix in self._DEFINERS[node.func.attr]:
                    defined.add(name + suffix)
        findings: List[Finding] = []
        for ctx in project.contexts:
            for call, expr, lineno in self._literal_exprs(ctx):
                metric = self._referenced(expr)
                if metric is None:
                    findings.append(Finding(
                        self.id, ctx.relpath, lineno,
                        f"alert expr {expr!r} is not in the tsdb "
                        "grammar (selector | fn(selector[window]))"))
                    continue
                if metric in defined:
                    continue
                # histogram_quantile may select the base family
                if metric + "_bucket" in defined:
                    continue
                findings.append(Finding(
                    self.id, ctx.relpath, lineno,
                    f"alert expr references {metric!r}, which no "
                    "Registry in the project defines: the rule would "
                    "evaluate to 'no data' forever and never fire"))
        return findings

    def _literal_exprs(self, ctx: FileContext
                       ) -> Iterator[Tuple[ast.Call, str, int]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name not in self._RULE_CTORS:
                continue
            expr_pos = 0 if name == "AlertCondition" else 1
            cand: Optional[ast.AST] = None
            if len(node.args) > expr_pos:
                cand = node.args[expr_pos]
            for kw in node.keywords:
                if kw.arg == "expr":
                    cand = kw.value
            if isinstance(cand, ast.Constant) \
                    and isinstance(cand.value, str):
                yield node, cand.value, cand.lineno

    def _referenced(self, expr: str) -> Optional[str]:
        for pat in self._EXPR_RES:
            m = pat.match(expr)
            if m:
                return m.group(1)
        return None


# -- D1: unseeded-nondeterminism ---------------------------------------------


@register
class UnseededNondeterminismRule(Rule):
    """The engine/scheduler equivalence suites replay byte-identically
    from a seed; one bare ``random.*`` or wall-clock read in those
    paths and "interleave on == interleave off" stops being checkable.
    Applies to the declared deterministic paths (the
    ``# tpulint: deterministic-path`` marker) plus the known suffixes.
    """

    id = "D1"
    name = "unseeded-nondeterminism"
    doc = "no bare random/time.time in deterministic paths"

    _SUFFIXES = (
        "workloads/serving.py",
        "workloads/scheduler.py",
        "slice/state.py",
    )

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        if not (ctx.deterministic
                or any(rel.endswith(s) for s in self._SUFFIXES)):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted.startswith("random.") \
                    and dotted != "random.Random":
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"{dotted} uses the process-global RNG in a "
                    "deterministic path: construct a seeded "
                    "random.Random and thread it through"))
            elif dotted in ("time.time", "time.time_ns"):
                findings.append(Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"{dotted}() is a wall-clock read in a "
                    "deterministic path: inject now= from the caller "
                    "like slice.state does"))
        return findings
