"""tpulint command line.

``python -m tools.tpulint [--strict] [--json] [PATH ...]`` — the CI
``code-lint`` job and the ``tpulint`` console script both land here, so
there is exactly one implementation to trust.  With no paths the
default target set is the shipped package plus ``tools/`` (relative to
the repo root, located by walking up from this file).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from .core import RULES, lint_paths, render_human, render_json
from . import rules as _rules  # noqa: F401  (registers the rule set)

DEFAULT_TARGETS = ("tpu_k8s_device_plugin", "tools")


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="project-invariant static analysis "
                    "(rule catalog: docs/user-guide/static-analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: "
                             f"{' '.join(DEFAULT_TARGETS)} under the "
                             "repo root)")
    parser.add_argument("--strict", action="store_true",
                        help="also fail on unused pragmas (P2)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULES):
            rule = RULES[rule_id]
            print(f"{rule_id}  {rule.name}: {rule.doc}")
        return 0

    root = _repo_root()
    paths: List[str] = list(args.paths)
    if not paths:
        paths = [os.path.join(root, t) for t in DEFAULT_TARGETS]
    findings = lint_paths(paths, strict=args.strict, root=root)
    if args.json:
        print(render_json(findings))
    elif findings:
        print(render_human(findings))
    else:
        print("tpulint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
