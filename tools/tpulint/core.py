"""tpulint core: the dependency-free AST analysis framework.

``tools/promlint.py`` proved the shape — a stdlib-only linter gating CI
catches invariant regressions before runtime.  This module generalizes
it from metric exposition text to the repo's Python source: a rule
registry, one shared per-file analysis pass (qualified names, lock
discovery, pragma collection), suppression pragmas with REQUIRED
justification text, and JSON/human output.  The project-specific rules
themselves live in :mod:`.rules`; see ``docs/user-guide/
static-analysis.md`` for the catalog.

Suppression contract (enforced, not advisory):

- ``# tpulint: disable=C2 -- <why this site is safe>`` on the flagged
  line (or the line directly above it) suppresses that rule there;
- ``# tpulint: disable-file=R1 -- <why>`` anywhere in the file
  suppresses the rule for the whole file;
- a pragma with no ``-- justification`` text is itself a finding (P1),
  as is one naming an unknown rule;
- under ``--strict`` an unused pragma is a finding too (P2): stale
  suppressions must not outlive the code they excused.

A file whose first 30 lines carry ``# tpulint: deterministic-path``
opts into the seeded-determinism rule set (D1) in addition to any
paths the rule matches by name — the invariant is declared next to the
code that holds it.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)="
    r"([A-Za-z0-9_,]+)\s*(?:--\s*(\S.*))?")
DETERMINISTIC_MARK_RE = re.compile(r"#\s*tpulint:\s*deterministic-path\b")
_DETERMINISTIC_MARK_SCAN_LINES = 30

# directory/file names never linted (generated code, fixtures that are
# DELIBERATE violations, caches)
DEFAULT_EXCLUDES = (
    "__pycache__",
    "lint_fixtures",
    "_pb2.py",
    "_pb2_grpc.py",
    ".jax_cache",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "message": self.message}


@dataclasses.dataclass
class Pragma:
    """One parsed suppression comment."""

    line: int
    rules: Tuple[str, ...]
    justification: str
    file_scope: bool
    used: bool = False


class LockId:
    """Canonical identity of one lock object.

    ``module.Class.attr`` for ``self.attr = threading.Lock()``,
    ``module.func.name`` for a local, ``module.name`` for a module
    global.  Identity is structural: every instance of a class shares
    the class's lock id, which is exactly the granularity a
    lock-ORDER discipline is stated at.
    """

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key

    def __hash__(self) -> int:
        return hash(self.key)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, LockId) and other.key == self.key

    def __repr__(self) -> str:
        return self.key


_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` /
    ``threading.Condition()`` (or the bare imported names)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return (isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
                and fn.attr in _LOCK_FACTORIES)
    return isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES


class FileContext:
    """Everything the rules need about one source file, computed once:
    the AST, parent/qualname maps, pragma table, discovered locks."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.module_name = _module_name(relpath)
        self.pragmas: List[Pragma] = []
        self.deterministic = False
        self._collect_pragmas()
        # parent + qualified-name maps (functions and classes)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.qualnames: Dict[ast.AST, str] = {}
        self._map_scopes()
        # lock discovery: (class name or "", attr/var name) -> LockId
        self.class_lock_attrs: Dict[Tuple[str, str], LockId] = {}
        self.local_locks: Dict[Tuple[str, str], LockId] = {}
        self._discover_locks()

    # -- pragmas -------------------------------------------------------------

    def _collect_pragmas(self) -> None:
        # real COMMENT tokens only: a pragma EXAMPLE quoted in a
        # docstring must not register as a live suppression
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            i = tok.start[0]
            m = PRAGMA_RE.search(tok.string)
            if m:
                kind, rules, justification = m.groups()
                self.pragmas.append(Pragma(
                    line=i,
                    rules=tuple(r.strip() for r in rules.split(",")
                                if r.strip()),
                    justification=(justification or "").strip(),
                    file_scope=(kind == "disable-file"),
                ))
            if (i <= _DETERMINISTIC_MARK_SCAN_LINES
                    and DETERMINISTIC_MARK_RE.search(tok.string)):
                self.deterministic = True

    # -- scope maps ----------------------------------------------------------

    def _map_scopes(self) -> None:
        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    self.qualnames[child] = qual
                    walk(child, qual)
                else:
                    walk(child, prefix)

        walk(self.tree, "")

    def enclosing_function(self, node: ast.AST
                           ) -> Optional[ast.FunctionDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def qualname(self, node: ast.AST) -> str:
        base = self.qualnames.get(node, "")
        return f"{self.module_name}.{base}" if base else self.module_name

    # -- lock discovery ------------------------------------------------------

    def _discover_locks(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if value is None or not _is_lock_ctor(value):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    cls = self.enclosing_class(node)
                    cls_name = cls.name if cls is not None else ""
                    key = (cls_name, tgt.attr)
                    self.class_lock_attrs[key] = LockId(
                        f"{self.module_name}.{cls_name}.{tgt.attr}")
                elif isinstance(tgt, ast.Name):
                    fn = self.enclosing_function(node)
                    scope = fn.name if fn is not None else ""
                    self.local_locks[(scope, tgt.id)] = LockId(
                        f"{self.module_name}.{scope}.{tgt.id}"
                        if scope else f"{self.module_name}.{tgt.id}")

    def lock_for_with_item(self, expr: ast.AST,
                           func: Optional[ast.FunctionDef]
                           ) -> Optional[LockId]:
        """Resolve ``with <expr>:`` to a lock identity, or None when the
        expression is not lock-shaped.  Known locks (discovered
        assignments) resolve exactly; otherwise an attribute/name whose
        name contains ``lock`` or ``cond`` resolves structurally so
        locks assigned in another file still participate."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            cls = self.enclosing_class(expr)
            cls_name = cls.name if cls is not None else ""
            known = self.class_lock_attrs.get((cls_name, expr.attr))
            if known is not None:
                return known
            if _lockish_name(expr.attr):
                return LockId(
                    f"{self.module_name}.{cls_name}.{expr.attr}")
            return None
        if isinstance(expr, ast.Name):
            scope = func.name if func is not None else ""
            known = (self.local_locks.get((scope, expr.id))
                     or self.local_locks.get(("", expr.id)))
            if known is not None:
                return known
            if _lockish_name(expr.id):
                return LockId(f"{self.module_name}.{scope}.{expr.id}"
                              if scope else
                              f"{self.module_name}.{expr.id}")
        return None


def _lockish_name(name: str) -> bool:
    low = name.lower()
    return "lock" in low or low.endswith("_cond") or low == "cond"


def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class Project:
    """The whole-run container: every FileContext plus the cross-file
    state project rules accumulate (the lock-acquisition graph)."""

    def __init__(self, contexts: Sequence[FileContext]):
        self.contexts = list(contexts)
        # C1 state, filled by the lock-order rule during check_file:
        # direct edges (held -> acquired) and deferred call edges
        # resolved against the project-wide function index in finalize.
        self.lock_edges: Dict[Tuple[LockId, LockId],
                              Tuple[str, int]] = {}
        self.deferred_calls: List[Tuple[LockId, str, Optional[str],
                                        str, int]] = []
        # function index: bare name -> [(qualname, class name or None,
        # [LockId acquired anywhere in the function])]
        self.functions: Dict[str, List[Tuple[str, Optional[str],
                                             List[LockId]]]] = {}


class Rule:
    """Base class: one invariant.  ``check_file`` runs per file;
    ``finalize`` runs once after every file (for cross-file rules)."""

    id = "X0"
    name = "unnamed"
    doc = ""

    def check_file(self, ctx: FileContext,
                   project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []


RULES: Dict[str, Rule] = {}


def register(rule_cls: type) -> type:
    inst = rule_cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return rule_cls


# -- the driver --------------------------------------------------------------

def iter_python_files(paths: Iterable[str],
                      excludes: Sequence[str] = DEFAULT_EXCLUDES
                      ) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not _excluded(path, excludes):
                out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if not _excluded(d, excludes))
            for f in sorted(files):
                full = os.path.join(root, f)
                if f.endswith(".py") and not _excluded(full, excludes):
                    out.append(full)
    return out


def _excluded(path: str, excludes: Sequence[str]) -> bool:
    return any(pat in path for pat in excludes)


def lint_paths(paths: Iterable[str],
               strict: bool = False,
               root: Optional[str] = None,
               excludes: Sequence[str] = DEFAULT_EXCLUDES
               ) -> List[Finding]:
    """Lint every Python file under *paths*; returns findings after
    pragma suppression (plus the pragma-hygiene findings themselves).
    *root* anchors the relative paths in messages (default: cwd)."""
    root = root or os.getcwd()
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for path in iter_python_files(paths, excludes):
        rel = os.path.relpath(path, root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            contexts.append(FileContext(path, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(Finding(
                "E0", rel, getattr(e, "lineno", 0) or 0,
                f"cannot parse: {e}"))
    project = Project(contexts)
    raw: List[Finding] = []
    for ctx in contexts:
        for rule in RULES.values():
            raw.extend(rule.check_file(ctx, project))
    for rule in RULES.values():
        raw.extend(rule.finalize(project))
    by_rel = {ctx.relpath: ctx for ctx in contexts}
    findings.extend(_apply_pragmas(raw, by_rel, strict=strict))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _apply_pragmas(raw: List[Finding],
                   contexts: Dict[str, FileContext],
                   strict: bool) -> List[Finding]:
    """Filter findings through the pragma tables, then emit the
    pragma-hygiene findings (P1 always, P2 unused under strict)."""
    kept: List[Finding] = []
    for finding in raw:
        ctx = contexts.get(finding.path)
        if ctx is None:
            kept.append(finding)
            continue
        suppressed = False
        for pragma in ctx.pragmas:
            if finding.rule not in pragma.rules:
                continue
            if pragma.file_scope or pragma.line in (finding.line,
                                                    finding.line - 1):
                pragma.used = True
                # a pragma with no justification never suppresses: the
                # P1 finding below AND the original finding both stand
                if pragma.justification:
                    suppressed = True
        if not suppressed:
            kept.append(finding)
    for ctx in contexts.values():
        for pragma in ctx.pragmas:
            for rule_id in pragma.rules:
                if rule_id not in RULES and not rule_id.startswith("E"):
                    kept.append(Finding(
                        "P1", ctx.relpath, pragma.line,
                        f"pragma names unknown rule {rule_id!r}"))
            if not pragma.justification:
                kept.append(Finding(
                    "P1", ctx.relpath, pragma.line,
                    "pragma without justification: write "
                    "'# tpulint: disable=RULE -- <why this site is "
                    "safe>'"))
            elif strict and not pragma.used:
                kept.append(Finding(
                    "P2", ctx.relpath, pragma.line,
                    f"unused pragma (rules {','.join(pragma.rules)}): "
                    "the code it excused is gone; delete it"))
    return kept


def render_human(findings: Sequence[Finding]) -> str:
    lines = [f.format() for f in findings]
    lines.append(f"tpulint: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in findings],
         "count": len(findings)},
        indent=1, sort_keys=True)
