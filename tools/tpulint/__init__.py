"""tpulint: project-invariant static analysis for this repo.

``tools/promlint.py`` lints what the metrics renderers EMIT;  tpulint
lints what the code IS — the conventions PRs 1-6 introduced (lock
discipline, resilience coverage at every boundary, no silent exception
swallows, registry-only metric families, seeded determinism in the
engine paths) become checked invariants instead of review folklore.

Dependency-free (ast + tokenize), like promlint.  Entry points:

- ``python -m tools.tpulint --strict`` (what CI's ``code-lint`` runs)
- the ``tpulint`` console script (same ``cli.main``)
- ``from tools.tpulint import lint_paths`` for the test suite

Rule catalog and pragma grammar: ``docs/user-guide/
static-analysis.md``.
"""

from .core import (
    DEFAULT_EXCLUDES,
    FileContext,
    Finding,
    Pragma,
    Project,
    RULES,
    Rule,
    lint_paths,
    register,
    render_human,
    render_json,
)
from . import rules  # noqa: F401  (importing registers the rule set)

__all__ = [
    "DEFAULT_EXCLUDES",
    "FileContext",
    "Finding",
    "Pragma",
    "Project",
    "RULES",
    "Rule",
    "lint_paths",
    "register",
    "render_human",
    "render_json",
    "rules",
]
