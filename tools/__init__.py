# Namespace package marker so `python -m tools.tpulint` and the
# `tpulint` console entry point resolve the same code (pyproject ships
# `tools*`).  The standalone scripts in this directory (promlint,
# chaos_soak, trace_smoke, measure_r3) stay runnable as plain files.
