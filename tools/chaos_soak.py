#!/usr/bin/env python
"""Chaos soak: provoke every recovery path, assert reconvergence.

Drives a REAL 2-host slice (coordinator + two plugin managers, each
with its own fake kubelet over real gRPC sockets) plus a real serving
engine through a seeded sweep of injected fault episodes:

  1. kubelet.register drop   — every Register RPC lost; the retry
                               policy burns its budget, then recovery
                               re-registers on the next socket event
  2. slice.join error        — join polls fail transiently; the
                               jittered-backoff loop still forms
  3. slice.heartbeat error   — total heartbeat loss; the breaker opens
                               (fail-fast pulses), then closes on the
                               half-open probe after the faults lift
  4. probe hang              — the sysfs/libtpu probe wedges; the
                               watchdog abandons it, devices demote
                               within one pulse, recovery re-promotes
  5. serve.step error        — the serving scheduler thread crashes;
                               in-flight requests get 503, the
                               supervisor restarts the loop, and the
                               next request answers 200
  6. serve.schedule hang     — an iteration wedges mid-interleave; the
                               schedule watchdog abandons it, in-flight
                               requests drain with 503, the supervisor
                               restarts the loop, and traffic
                               reconverges (the abandoned worker bails
                               on the supersession check instead of
                               racing the restarted loop)
  7. member loss + reshape   -- a slice member dies mid-traffic; after
                               the staleness timeout the verdict
                               demotes (demote-all while it might
                               return), the reshape grace window
                               expires, the survivor re-forms into a
                               smaller degraded generation and serves
                               Healthy at the reduced shape -- all
                               journal-proven (tpu_slice_reshaped,
                               membership_adopted gen+1, lineage)
  8. member flap in grace    -- the member goes silent past the
                               staleness timeout but returns INSIDE the
                               reshape grace window: no reshape, the
                               original generation holds bit-for-bit
                               (outcome=cancelled counted, no
                               tpu_slice_reshaped event)

After every episode the system must reconverge: all devices
re-advertised Healthy, the slice verdict healthy, serving answering
200s — and the flight-recorder journals must contain the
breaker/watchdog transition events that prove the resilience layer
(not luck) did the recovering.

Deterministic: ``--seed`` feeds the fault injector and every backoff
jitter RNG, so a CI failure reproduces locally with the same seed
(the ENGINE_FUZZ_SEED convention).

Usage::

    python tools/chaos_soak.py --seed 1            # full soak
    python tools/chaos_soak.py --seed 1 --skip-serving   # no jax needed
"""
# tpulint: disable-file=R1 -- chaos DRIVER: these probe requests deliberately hit a faulted server raw; the resilience machinery under test lives on the server side, and wrapping the prober would mask whether recovery actually happened

from __future__ import annotations

import argparse
import logging
import os
import random
import shutil
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))  # fake_kubelet

from tpu_k8s_device_plugin import obs, resilience  # noqa: E402
from tpu_k8s_device_plugin.health.server import probe_chip_states  # noqa: E402
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi  # noqa: E402
from tpu_k8s_device_plugin.manager import PluginManager  # noqa: E402
from tpu_k8s_device_plugin.manager import manager as manager_mod  # noqa: E402
from tpu_k8s_device_plugin.resilience import faults  # noqa: E402
from tpu_k8s_device_plugin.slice import SliceClient, SliceCoordinator  # noqa: E402
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl  # noqa: E402
from tpu_k8s_device_plugin.types import constants  # noqa: E402

from fake_kubelet import FakeKubelet, ListAndWatchConsumer  # noqa: E402

log = logging.getLogger("chaos-soak")

_JAX_PORT = 8476
PROBE_WATCHDOG_S = 0.5
BREAKER_RESET_S = 0.2


class ChaosHost:
    """One slice member: fixture tree, impl (in-process sysfs probe),
    slice client, fake kubelet, manager — all wired to one registry +
    flight recorder so episodes can assert on the journal."""

    def __init__(self, name, fixture, testdata, tmp, rendezvous, seed):
        self.name = name
        root = os.path.join(tmp, name)
        shutil.copytree(os.path.join(testdata, fixture), root,
                        symlinks=True)
        self.sys_root = os.path.join(root, "sys")
        self.dev_root = os.path.join(root, "dev")
        self.registry = obs.Registry()
        self.recorder = obs.FlightRecorder(registry=self.registry)
        self.impl = TpuContainerImpl(
            sysfs_root=self.sys_root,
            dev_root=self.dev_root,
            tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
            health_fn=self._granular,
            probe_watchdog_s=PROBE_WATCHDOG_S,
        )
        self.client = SliceClient(
            rendezvous_address=rendezvous,
            hostname=name,
            coords=(self.impl.topology.worker_id,),
            chip_count=len(self.impl.chips),
            state_path=os.path.join(tmp, f"{name}-membership.json"),
            local_health_fn=self.impl.local_health,
            registry=self.registry,
            recorder=self.recorder,
            join_backoff_initial_s=0.05,
            join_backoff_max_s=0.2,
            breaker_reset_s=BREAKER_RESET_S,
            seed=seed,
        )
        self.impl.set_slice_client(self.client)
        self.kubelet = FakeKubelet(os.path.join(tmp, f"{name}-dp")).start()
        self.manager = PluginManager(
            self.impl,
            pulse_seconds=0,
            kubelet_dir=self.kubelet.dir,
            kubelet_watch_interval_s=0.1,
            slice_client=self.client,
            registry=self.registry,
            recorder=self.recorder,
        )
        self.consumer = None

    def _granular(self):
        states = probe_chip_states(self.sys_root, self.dev_root)
        return {cid: st.health for cid, st in states.items()}

    def pulse(self):
        """One manual pulse round in the manager loop's order."""
        self.client.heartbeat_now()
        with self.manager._plugins_lock:
            plugins = list(self.manager._plugins.values())
        for sp in plugins:
            sp.plugin.beat()

    def open_stream(self):
        stub = self.kubelet.plugin_stub("google.com_tpu")
        self.consumer = ListAndWatchConsumer(stub)
        return self.consumer.next_frame()

    def wait_frame(self, predicate, pulses=10, timeout_s=10.0):
        """Pulse until a ListAndWatch frame satisfies *predicate*."""
        import queue as _q
        deadline = time.time() + timeout_s
        last = None
        for _ in range(pulses):
            self.pulse()
            while time.time() < deadline:
                try:
                    last = self.consumer.frames.get(timeout=1.0)
                except _q.Empty:
                    break
                if predicate(last):
                    return last
            if time.time() >= deadline:
                break
        raise AssertionError(
            f"{self.name}: no matching frame within {timeout_s}s; "
            f"last: {last}")

    def journal(self, name):
        return self.recorder.events(name=name)

    def stop(self):
        self.manager.stop()
        self.client.stop()
        self.kubelet.stop()


def all_healthy(frame):
    return frame.devices and all(
        d.health == constants.HEALTHY for d in frame.devices)


def all_unhealthy(frame):
    return frame.devices and all(
        d.health == constants.UNHEALTHY for d in frame.devices)


def check(cond, msg):
    if not cond:
        raise AssertionError(msg)
    log.info("OK: %s", msg)


def episode_register_drop(hosts, seed):
    """Every Register lost -> retries burn out -> recovery on the next
    kubelet socket event once the faults lift."""
    a = hosts[0]
    inj = faults.install("kubelet.register:drop:1", seed=seed,
                         recorder=a.recorder)
    try:
        a.kubelet.register_event.clear()
        a.kubelet.restart(wipe_dir=False)
        got = a.kubelet.wait_for_registration(timeout=3.0)
        check(not got, "register blackhole: no registration landed")
        check(inj.fired_count("kubelet.register") >= manager_mod._REGISTER_RETRIES,
              f"retry policy burned its {manager_mod._REGISTER_RETRIES}-"
              "attempt budget against the blackhole")
        samples = obs.parse_exposition(a.registry.render())
        retries = [v for n, lab, v in samples
                   if n == "tpu_resilience_retries_total"
                   and lab.get("op") == "kubelet.register"]
        check(retries and retries[0] >= 1,
              "tpu_resilience_retries_total{op=kubelet.register} counted")
    finally:
        faults.uninstall()
    a.kubelet.restart(wipe_dir=False)
    check(a.kubelet.wait_for_registration(timeout=10.0),
          "re-registered after the faults lifted")


def episode_join_error(hosts, coordinator, tmp, seed):
    """A fresh client (worker restart) joins through transient join
    errors via the shared backoff policy."""
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    client = SliceClient(
        rendezvous_address=f"127.0.0.1:{coordinator.port}",
        hostname=hosts[0].name,     # same host restarting: rank kept
        coords=(0,),
        chip_count=len(hosts[0].impl.chips),
        state_path=None,
        registry=registry,
        recorder=recorder,
        join_backoff_initial_s=0.02,
        join_backoff_max_s=0.1,
        seed=seed,
    )
    inj = faults.install("slice.join:error:0.6", seed=seed,
                         recorder=recorder)
    try:
        m = client.join(timeout_s=30.0)
        check(m is not None and m.rank_of(hosts[0].name) == 0,
              "join converged through 60% injected error rate "
              f"({inj.fired_count('slice.join')} faults fired)")
    finally:
        faults.uninstall()
        client.stop()


def episode_heartbeat_loss(hosts, seed):
    """Total heartbeat loss: breakers open (fail-fast pulses, verdict
    frozen), then close via the half-open probe once faults lift."""
    a, b = hosts
    inj = faults.install("slice.heartbeat:error:1", seed=seed,
                         recorder=a.recorder)
    try:
        for _ in range(4):      # > breaker threshold (3)
            a.pulse()
            b.pulse()
        opened = [e for e in a.journal("tpu_breaker_transition")
                  if e["attrs"].get("op") == "slice.heartbeat"
                  and e["attrs"].get("to") == "open"]
        check(opened, "heartbeat breaker opened in the journal")
        check(inj.fired_count("slice.heartbeat") >= 3,
              "injector dropped >= 3 heartbeats")
        overlay = a.client.health_overlay()
        check(overlay is not None and overlay[0],
              "verdict frozen healthy through the outage (no "
              "self-inflicted slice demotion)")
    finally:
        faults.uninstall()
    time.sleep(BREAKER_RESET_S * 1.5)   # let the reset window pass
    for _ in range(2):
        a.pulse()
        b.pulse()
    closed = [e for e in a.journal("tpu_breaker_transition")
              if e["attrs"].get("op") == "slice.heartbeat"
              and e["attrs"].get("to") == "closed"]
    check(closed, "heartbeat breaker closed after recovery")
    a.wait_frame(all_healthy)
    b.wait_frame(all_healthy)
    check(True, "both hosts advertise Healthy after heartbeat recovery")


def episode_probe_hang(hosts, seed):
    """The probe wedges: the watchdog abandons it, the host reports
    itself unhealthy, the slice demotes BOTH members within a pulse
    exchange; recovery re-promotes everything."""
    a, b = hosts
    faults.install(f"probe:hang:{PROBE_WATCHDOG_S * 4}", seed=seed,
                   recorder=a.recorder)
    try:
        t0 = time.monotonic()
        a.pulse()               # watchdog trips inside this pulse
        pulse_dt = time.monotonic() - t0
        check(pulse_dt < PROBE_WATCHDOG_S * 4,
              f"pulse returned in {pulse_dt:.1f}s — the watchdog "
              "failed the hung probe instead of riding it out")
        trips = [e for e in a.journal("tpu_watchdog_trip")
                 if e["attrs"].get("op") == "probe"]
        check(trips, "watchdog trip journaled for the probe")
        b.pulse()               # B learns the slice verdict
        b.wait_frame(all_unhealthy)
        check(True, "peer demoted all devices after the probe hang")
    finally:
        faults.uninstall()
    a.wait_frame(all_healthy)
    b.wait_frame(all_healthy)
    check(True, "both hosts re-advertise Healthy after probe recovery")


def episode_scheduler_crash(seed):
    """The serving scheduler crashes mid-decode: in-flight requests
    get 503 (not a hang), the supervisor restarts the loop, and the
    next request answers 200."""
    import http.client
    import json

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4)
    srv.start(host="127.0.0.1", port=0)

    def post(payload, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/generate", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def get(path):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    try:
        status, _ = post({"tokens": [3, 14, 15], "max_new_tokens": 4,
                          "stream": False})
        check(status == 200, "serving baseline request answered 200")
        faults.install("serve.step:error:1", seed=seed,
                       recorder=srv.recorder)
        try:
            status, body = post({"tokens": [9, 9, 8],
                                 "max_new_tokens": 4, "stream": False})
            check(status == 503,
                  f"in-flight request got a real 503 on scheduler "
                  f"crash (got {status}: {body[:80]!r})")
        finally:
            faults.uninstall()
        crashes = srv.recorder.events(name="tpu_serve_scheduler_crash")
        check(crashes, "scheduler crash journaled")
        deadline = time.time() + 10.0
        while (time.time() < deadline
               and srv._m_sched_restarts.value < 1):
            time.sleep(0.05)
        check(srv._m_sched_restarts.value >= 1,
              "supervisor restarted the scheduler")
        status, _ = get("/healthz")
        check(status == 200, "healthz back to 200 after restart")
        status, body = post({"tokens": [2, 71, 82],
                             "max_new_tokens": 4, "stream": False})
        check(status == 200,
              f"serving answers 200 again after the crash "
              f"(got {status}: {body[:80]!r})")
    finally:
        srv.stop()


def episode_scheduler_hang(seed):
    """An iteration hangs mid-interleave: the schedule watchdog trips
    (WatchdogTimeout -> crash supervisor), in-flight requests drain
    with 503 instead of hanging, the loop restarts, and the next
    request answers 200.  The abandoned worker must NOT race the
    restarted loop — the supersession check has it bail before any
    engine work."""
    import http.client
    import json

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4,
                       schedule_watchdog_s=0.5)
    srv.start(host="127.0.0.1", port=0)

    def post(payload, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/generate", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    try:
        status, _ = post({"tokens": [3, 14, 15], "max_new_tokens": 4,
                          "stream": False})
        check(status == 200, "serving baseline request answered 200")
        faults.install("serve.schedule:hang:5", seed=seed,
                       recorder=srv.recorder)
        try:
            status, body = post({"tokens": [9, 9, 8],
                                 "max_new_tokens": 4, "stream": False})
            check(status == 503,
                  f"hung iteration drained the in-flight request with "
                  f"a real 503 (got {status}: {body[:80]!r})")
        finally:
            faults.uninstall()
        trips = [e for e in srv.recorder.events(name="tpu_watchdog_trip")
                 if e["attrs"].get("op") == "serve.schedule"]
        check(trips, "schedule-watchdog trip journaled")
        deadline = time.time() + 10.0
        while (time.time() < deadline
               and srv._m_sched_restarts.value < 1):
            time.sleep(0.05)
        check(srv._m_sched_restarts.value >= 1,
              "supervisor restarted the scheduler after the trip")
        samples = obs.parse_exposition(srv.render_metrics())
        wd = [v for n, lab, v in samples
              if n == "tpu_watchdog_trips_total"
              and lab.get("op") == "serve.schedule"]
        check(wd and wd[0] >= 1,
              "tpu_watchdog_trips_total{op=serve.schedule} counted")
        status, body = post({"tokens": [2, 71, 82],
                             "max_new_tokens": 4, "stream": False})
        check(status == 200,
              f"traffic reconverged after the hang "
              f"(got {status}: {body[:80]!r})")
    finally:
        srv.stop()


def episode_tenant_burst_page_pressure(seed):
    """Episode 9: a low-priority batch tenant saturates a small paged
    KV pool, then an interactive tenant bursts.  QoS must hold: the
    interactive requests admit via preemption-by-page-eviction (the
    batch slot checkpoints its pages to host and re-queues), their
    latency stays bounded instead of queueing behind the whole batch
    stream, the PREEMPTED request still completes with its full token
    count after re-admission, and an over-quota tenant 429s — all
    journal/metric-proven."""
    import http.client
    import json
    import threading

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import (
        EngineServer,
        parse_tenant_quotas,
    )
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    # 8 pages of 8 rows: ONE long request owns most of the pool, so
    # the interactive burst can only land through eviction
    eng = ServingEngine(model, params, n_slots=2, chunk=8,
                        kv_paging=True, kv_pages=8)
    srv = EngineServer(
        eng, max_new_tokens=8, window=2,
        tenant_quotas=parse_tenant_quotas(
            ["interactive=0:0:4", "batch=0:0:1", "greedy=1:20"]))
    srv.start(host="127.0.0.1", port=0)

    def post(payload, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/generate", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, body
        finally:
            conn.close()

    try:
        results = {}
        times = {}

        def fire(key, payload):
            t0 = time.time()
            results[key] = post(payload)
            times[key] = time.time() - t0

        lo = threading.Thread(target=fire, args=("batch", {
            "tokens": list(range(1, 31)), "max_new_tokens": 8,
            "priority": 0, "tenant": "batch", "stream": False}))
        lo.start()
        time.sleep(0.5)  # the batch stream is decoding on the pool
        burst = [threading.Thread(target=fire, args=(f"i{k}", {
            "tokens": list(range(40 + k, 70 + k)), "max_new_tokens": 8,
            "priority": 5, "tenant": "interactive", "stream": False}))
            for k in range(2)]
        for t in burst:
            t.start()
        for t in burst:
            t.join(timeout=120)
        t_interactive = max(times[f"i{k}"] for k in range(2))
        lo.join(timeout=120)
        for k in range(2):
            st, body = results[f"i{k}"]
            check(st == 200, f"interactive request {k} served 200 "
                             f"under page pressure (got {st})")
        st, body = results["batch"]
        check(st == 200, f"preempted batch request completed after "
                         f"re-admission (got {st})")
        done = json.loads(body.decode().strip().splitlines()[-1])
        check(len(done.get("tokens", [])) == 8,
              "preempted request kept its FULL 8-token stream across "
              "checkpoint/resume")
        check(t_interactive <= times["batch"],
              f"interactive p99 bounded: burst finished in "
              f"{t_interactive:.2f}s, not behind the whole batch "
              f"stream ({times['batch']:.2f}s)")
        samples = obs.parse_exposition(srv.render_metrics())
        preempts = [v for n, lab, v in samples
                    if n == "tpu_serve_kv_preemptions_total"]
        check(preempts and preempts[0] >= 1,
              "tpu_serve_kv_preemptions_total counted the eviction")
        names = [e["name"] for e in srv.recorder.events()]
        check("tpu_serve_kv_preempt" in names,
              "page eviction journaled")
        check("tpu_serve_kv_resume" in names,
              "checkpoint resume journaled")
        # over-quota tenant: 429 is per-tenant policy
        st, _ = post({"tokens": list(range(1, 20)),
                      "max_new_tokens": 8, "tenant": "greedy",
                      "stream": False})
        st2, _ = post({"tokens": list(range(1, 20)),
                       "max_new_tokens": 8, "tenant": "greedy",
                       "stream": False})
        check(429 in (st, st2),
              f"over-quota tenant throttled with 429 (got {st}/{st2})")
        samples = obs.parse_exposition(srv.render_metrics())
        quota_sheds = [v for n, lab, v in samples
                       if n == "tpu_serve_shed_total"
                       and lab.get("reason") == "quota"]
        check(quota_sheds and quota_sheds[0] >= 1,
              "tpu_serve_shed_total{reason=quota} counted")
        eng._pool.check()
    finally:
        srv.stop()


def episode_router_replica_kill(seed):
    """Episode 10: a serving replica is SIGKILLed under burst behind
    the router tier.  The surviving replica absorbs the load: only the
    requests mid-stream ON THE DEAD REPLICA error — each with a
    well-formed in-band error frame and a clean chunked terminator,
    never a silent truncation — every post-kill request lands 200 on
    the survivor (pre-stream failover / routing-around), the victim's
    circuit breaker opens, and when the replica comes back under the
    same identity the breaker closes and affinity traffic returns to
    it.  All journal/metric-proven on the router's own surfaces."""
    import http.client
    import json
    import os
    import subprocess
    import sys
    import threading

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.bench_serving import (
        _free_port,
        _wait_http_ok,
    )
    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.router import (
        RouterServer,
        affinity_key,
    )
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    rt = RouterServer(statz_interval_s=0.5, replica_ttl_s=5.0,
                      breaker_reset_s=0.5, seed=seed)
    rt.start(host="127.0.0.1", port=0)

    # survivor: in-process tiny engine registered as replica-a
    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=256, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    survivor = EngineServer(eng, max_new_tokens=200, window=4)
    survivor.start(host="127.0.0.1", port=0)
    survivor.start_registration(
        f"http://127.0.0.1:{rt.port}", replica_id="replica-a",
        model="chaos-tiny", interval_s=0.3)

    # victim: a REAL replica subprocess (the CLI a pod runs), so the
    # kill is a kill — no graceful drain, sockets die mid-chunk
    victim_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    # victim max_len 2048: the burst streams need SECONDS of decode
    # left when the SIGKILL lands (a short stream fits entirely in
    # socket buffers before the kill and aborts nothing)
    victim = subprocess.Popen(
        [sys.executable, "-m",
         "tpu_k8s_device_plugin.workloads.server",
         "--config", "tiny", "--n-slots", "2", "--max-len", "2048",
         "--max-new-tokens", "2000", "--window", "4",
         "--host", "127.0.0.1", "--port", str(victim_port),
         "--register-with", f"http://127.0.0.1:{rt.port}",
         "--replica-id", "replica-b", "--register-interval", "0.3"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    revived = None
    try:
        _wait_http_ok(victim_port, "/healthz", 600)
        _wait_http_ok(
            rt.port, "/replicas", 30,
            lambda b: sum(r["healthy"] for r in b["replicas"]) >= 2)
        check(True, "router sees both replicas healthy")

        # deterministic prompts pinned to each replica via the ring
        import random
        rng = random.Random(seed)

        def prompt_for(rid):
            while True:
                cand = [rng.randrange(1, 128) for _ in range(32)]
                if rt.affinity_target(
                        affinity_key({"tokens": cand}, 32)) == rid:
                    return cand

        p_victim = prompt_for("replica-b")
        p_surv = prompt_for("replica-a")

        def stream(prompt, budget):
            """One streaming request through the router; returns
            (status, X-Replica, event lines, first-line event)."""
            conn = http.client.HTTPConnection("127.0.0.1", rt.port,
                                              timeout=120)
            conn.request("POST", "/generate", json.dumps(
                {"tokens": prompt, "max_new_tokens": budget,
                 "ignore_eos": True}),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            replica = resp.headers.get("X-Replica")
            lines = []
            first = threading.Event()
            try:
                for line in resp:
                    if line.strip():
                        lines.append(line.strip())
                        first.set()
            finally:
                conn.close()
            return resp.status, replica, lines

        # baseline: affinity routes each prompt to its ring target
        st, rep, lines = stream(p_victim, 8)
        check(st == 200 and rep == "replica-b",
              f"affinity routed the victim-bound prompt to replica-b "
              f"(got {st} via {rep})")
        st, rep, lines = stream(p_surv, 8)
        check(st == 200 and rep == "replica-a",
              f"affinity routed the survivor-bound prompt to "
              f"replica-a (got {st} via {rep})")

        # -- burst + kill ---------------------------------------------
        results = {}
        started = threading.Event()

        def burst_one(key, prompt, budget):
            conn = http.client.HTTPConnection("127.0.0.1", rt.port,
                                              timeout=120)
            try:
                conn.request("POST", "/generate", json.dumps(
                    {"tokens": prompt, "max_new_tokens": budget,
                     "ignore_eos": True}),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                replica = resp.headers.get("X-Replica")
                lines = []
                for line in resp:
                    if line.strip():
                        lines.append(line.strip())
                        if replica == "replica-b":
                            started.set()
                results[key] = (resp.status, replica, lines, None)
            # tpulint: disable=R2 -- not a swallow: the exception is captured into results and asserted on by the episode (a truncated stream must FAIL it)
            except Exception as e:
                results[key] = (-1, None, [], e)
            finally:
                conn.close()

        burst = (
            [threading.Thread(target=burst_one,
                              args=(f"v{i}", p_victim, 1500))
             for i in range(2)]
            + [threading.Thread(target=burst_one,
                                args=(f"s{i}", p_surv, 24))
               for i in range(2)])
        for t in burst:
            t.start()
        check(started.wait(timeout=60),
              "victim streams flowing before the kill")
        victim.kill()          # SIGKILL: no drain, sockets die
        victim.wait(timeout=30)
        t_kill = time.monotonic()
        for t in burst:
            t.join(timeout=120)

        aborted = completed = 0
        for key, (st, rep, lines, exc) in sorted(results.items()):
            check(exc is None,
                  f"burst request {key} ended with a parseable "
                  f"stream, not a transport error ({exc})")
            check(st == 200 and lines,
                  f"burst request {key} got headers + frames")
            last = json.loads(lines[-1])
            if "done" in last:
                completed += 1
            else:
                # the well-formed in-band error frame: structured
                # JSON naming the dead replica, code 502
                check("error" in last and last.get("code") == 502
                      and rep == "replica-b",
                      f"aborted stream {key} ended with a well-formed "
                      f"502 error frame on the dead replica ({last})")
                aborted += 1
        check(aborted >= 1,
              f"at least one in-flight stream on the dead replica "
              f"aborted mid-stream ({aborted} did)")
        check(completed >= 2,
              f"streams off the dead replica completed normally "
              f"({completed} did)")

        # post-kill: every new request lands on the survivor, 200
        for i in range(4):
            st, rep, lines = stream(p_victim, 8)
            check(st == 200 and rep == "replica-a",
                  f"post-kill request {i} failed over to the "
                  f"survivor (got {st} via {rep})")
            check(json.loads(lines[-1]).get("done") is True,
                  f"post-kill request {i} completed")
        reconverge_s = time.monotonic() - t_kill
        check(reconverge_s < 60.0,
              f"post-kill traffic reconverged in {reconverge_s:.1f}s")

        # journal + metric proof
        names = [e["name"] for e in rt.recorder.events()]
        check("tpu_router_stream_abort" in names,
              "mid-stream abort journaled")
        opened = [e for e in rt.recorder.events(
            name="tpu_breaker_transition")
            if e["attrs"].get("op") == "router.replica.replica-b"
            and e["attrs"].get("to") == "open"]
        check(opened, "victim breaker opened in the journal")
        samples = obs.parse_exposition(rt.registry.render())
        aborts = [v for n, lab, v in samples
                  if n == "tpu_router_requests_total"
                  and lab.get("replica") == "replica-b"
                  and lab.get("outcome") == "stream_abort"]
        check(aborts and aborts[0] >= 1,
              "tpu_router_requests_total{replica-b,stream_abort} "
              "counted")
        healthy = {lab.get("replica"): v for n, lab, v in samples
                   if n == "tpu_router_replica_healthy"}
        check(healthy.get("replica-a") == 1,
              "tpu_router_replica_healthy{replica-a} = 1")
        check(healthy.get("replica-b", 0) == 0,
              "tpu_router_replica_healthy{replica-b} = 0 after kill")

        # -- revival: same identity, breaker closes, affinity returns -
        eng2 = ServingEngine(model, params, n_slots=2)
        revived = EngineServer(eng2, max_new_tokens=200, window=4)
        revived.start(host="127.0.0.1", port=victim_port)
        revived.start_registration(
            f"http://127.0.0.1:{rt.port}", replica_id="replica-b",
            advertise=f"127.0.0.1:{victim_port}",
            model="chaos-tiny", interval_s=0.3)
        _wait_http_ok(
            rt.port, "/replicas", 30,
            lambda b: sum(r["healthy"] for r in b["replicas"]) >= 2)
        closed = [e for e in rt.recorder.events(
            name="tpu_breaker_transition")
            if e["attrs"].get("op") == "router.replica.replica-b"
            and e["attrs"].get("to") == "closed"]
        check(closed, "victim breaker closed after revival")
        st, rep, lines = stream(p_victim, 8)
        check(st == 200 and rep == "replica-b",
              f"affinity traffic returned to the revived replica "
              f"(got {st} via {rep})")
    finally:
        if revived is not None:
            revived.stop()
        survivor.stop()
        rt.stop()
        victim.kill()
        try:
            victim.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


def episode_packed_prefill_kill(seed):
    """Episode 11: the scheduler is killed (injected hang → schedule
    watchdog → crash supervisor) while RAGGED PACKED PREFILL is in
    flight — several concurrent multi-chunk admissions batching
    through admit_step_packed behind an open decode window.  The
    invariant: every packed request either COMPLETES (a {"done"}
    terminal event) or gets a WELL-FORMED error frame (the
    supervisor's 503 drain) — never a hang, never a truncated stream
    — and after the supervised restart fresh traffic answers 200
    through the re-warmed packed path."""
    import http.client
    import json
    import threading

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    # chunk 4 so a 24-token prompt is 6 chunks; prefill_chunks=1
    # spreads each admission's prefill over many windows — the packed
    # sessions are still mid-flight when the hang lands
    eng = ServingEngine(model, params, n_slots=4, chunk=4,
                        auto_prefix=False)
    srv = EngineServer(eng, max_new_tokens=24, window=4,
                       prefill_chunks=1, schedule_watchdog_s=0.5)
    # pre-compile scan windows + packed shapes like the CLI does: the
    # 0.5s watchdog is sized for steady state, not first-compile
    srv.warm_scheduler()
    srv.start(host="127.0.0.1", port=0)

    def post(payload, out=None, timeout=120):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/generate", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            events = []
            for line in resp:
                s = line.strip()
                if s:
                    events.append(json.loads(s))
            result = (resp.status, events)
        except (OSError, ValueError) as e:
            result = (-1, [{"error": f"transport: {e}", "code": -1}])
        finally:
            conn.close()
        if out is not None:
            out.append(result)
        return result

    try:
        status, _ = post({"tokens": [3, 14, 15], "max_new_tokens": 4,
                          "stream": False})
        check(status == 200, "serving baseline request answered 200")
        # a long-running decode keeps the engine active, so the wave
        # below rides the mid-window admission path; its 40-token
        # (10-chunk) prompts at prefill_chunks=1 keep the packed
        # sessions pending across MANY windows — the hang lands while
        # they are still mid-flight
        results: list = []
        anchor = threading.Thread(target=post, args=(
            {"tokens": [2, 71, 82], "max_new_tokens": 40}, results))
        anchor.start()
        time.sleep(0.02)
        rng = random.Random(seed)
        packed = []
        for i in range(3):
            prompt = [rng.randrange(1, 128) for _ in range(40)]
            th = threading.Thread(target=post, args=(
                {"tokens": prompt, "max_new_tokens": 4}, results))
            th.start()
            packed.append(th)
        time.sleep(0.03)  # tickets pulled, packed rounds under way
        faults.install("serve.schedule:hang:5", seed=seed,
                       recorder=srv.recorder)
        try:
            anchor.join(timeout=60)
            for th in packed:
                th.join(timeout=60)
            check(len(results) == 4,
                  "every request terminated (no hung streams)")
        finally:
            faults.uninstall()
        done = err = 0
        for status, events in results:
            terminal = events[-1] if events else {}
            if status == 200 and terminal.get("done") is True:
                done += 1
            elif "error" in terminal and terminal.get("code") == 503:
                err += 1    # the supervisor's well-formed drain frame
            else:
                check(False,
                      f"request ended without a done/503 terminal "
                      f"event: status={status} last={terminal}")
        check(done + err == 4,
              f"all packed-era requests completed or got well-formed "
              f"503s (done={done} err={err})")
        check(err >= 1, "the hang actually aborted in-flight work")
        check(eng.stats()["packed_prefill_extends"] >= 1,
              "packed prefill dispatches ran before the kill")
        trips = [e for e in srv.recorder.events(name="tpu_watchdog_trip")
                 if e["attrs"].get("op") == "serve.schedule"]
        check(trips, "schedule-watchdog trip journaled")
        deadline = time.time() + 10.0
        while (time.time() < deadline
               and srv._m_sched_restarts.value < 1):
            time.sleep(0.05)
        check(srv._m_sched_restarts.value >= 1,
              "supervisor restarted the scheduler after the trip")
        # reconvergence: the hang may have tripped the watchdog more
        # than once before the uninstall landed (each trip drains
        # 503s), so give the restarted loop a bounded window to serve
        # clean again — the invariant is recovery, not trip count
        status, events = -1, []
        deadline = time.time() + 15.0
        while time.time() < deadline:
            status, events = post({"tokens": [9, 9, 8, 7, 1, 2, 3, 4],
                                   "max_new_tokens": 4,
                                   "stream": False})
            if status == 200 and events and events[0].get("done"):
                break
            time.sleep(0.25)
        check(status == 200 and events and events[0].get("done"),
              f"traffic reconverged after the packed-prefill kill "
              f"(got {status})")
    finally:
        srv.stop()


def episode_prefill_kill_mid_migration(seed):
    """Episode 12: the PREFILL-class replica is SIGKILLed while
    disagg-routed requests are mid-prefill/mid-migration behind the
    phase-aware router.  Every in-flight request must either complete
    on a surviving replica (the router's disagg fallbacks all fire
    BEFORE any client byte, so the request re-routes whole — the
    decode-class survivor serves it normally) or end in a WELL-FORMED
    502/503 frame; post-kill traffic lands 200 on the survivor, the
    victim's breaker opens, and the router's migration counters +
    journal carry the proof."""
    import http.client
    import json
    import subprocess
    import threading

    from tpu_k8s_device_plugin.workloads.bench_serving import (
        _free_port,
        _wait_http_ok,
        build_model_and_params,
    )
    from tpu_k8s_device_plugin.workloads.router import RouterServer
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    rt = RouterServer(statz_interval_s=0.5, replica_ttl_s=5.0,
                      breaker_reset_s=0.5, seed=seed,
                      prefill_threshold=64)
    rt.start(host="127.0.0.1", port=0)

    # survivor: in-process DECODE-class replica with the SAME model
    # the victim CLI builds (checkpoints only resume onto matching
    # shapes/dtypes — the builder's deterministic seed makes the two
    # processes' weights identical, so migrated decode is exact)
    _cfg, model, params = build_model_and_params("tiny", 512, False)
    eng = ServingEngine(model, params, n_slots=4,
                        eos_id=getattr(_cfg, "eos_id", None),
                        kv_paging=True)
    survivor = EngineServer(eng, max_new_tokens=64, window=4,
                            replica_role="decode")
    survivor.start(host="127.0.0.1", port=0)
    survivor.start_registration(
        f"http://127.0.0.1:{rt.port}", replica_id="disagg-decode",
        model="chaos-tiny", interval_s=0.3)

    # victim: a REAL prefill-class replica subprocess — SIGKILL means
    # sockets die mid-prefill/mid-export, no drain
    victim_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    victim = subprocess.Popen(
        [sys.executable, "-m",
         "tpu_k8s_device_plugin.workloads.server",
         "--config", "tiny", "--n-slots", "4", "--max-len", "512",
         "--max-new-tokens", "64", "--window", "4", "--kv-paging",
         "--replica-role", "prefill",
         "--host", "127.0.0.1", "--port", str(victim_port),
         "--register-with", f"http://127.0.0.1:{rt.port}",
         "--replica-id", "disagg-prefill",
         "--register-interval", "0.3"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    try:
        _wait_http_ok(victim_port, "/healthz", 600)
        _wait_http_ok(
            rt.port, "/replicas", 30,
            lambda b: sum(r["healthy"] for r in b["replicas"]) >= 2)
        check(True, "router sees prefill + decode replicas healthy")

        rng = random.Random(seed)

        def long_prompt():
            return [rng.randrange(1, 128) for _ in range(320)]

        def unary(prompt, budget=24):
            """One long-prefill unary request through the router;
            returns (status, X-Replica, parsed body or None, exc)."""
            conn = http.client.HTTPConnection("127.0.0.1", rt.port,
                                              timeout=120)
            try:
                conn.request("POST", "/generate", json.dumps(
                    {"tokens": prompt, "max_new_tokens": budget,
                     "stream": False, "ignore_eos": True}),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
                return (resp.status,
                        resp.headers.get("X-Replica"),
                        json.loads(body), None)
            # tpulint: disable=R2 -- not a swallow: the exception is captured into the result tuple and asserted on by the episode (a torn response must FAIL it)
            except Exception as e:
                return (-1, None, None, e)
            finally:
                conn.close()

        # steady state: migration actually engages
        st, rep, body, exc = unary(long_prompt())
        check(exc is None and st == 200 and "done" in (body or {}),
              f"disagg-routed request completed ({st} via {rep})")
        check(rep == "disagg-decode",
              "pre-kill request streamed from the decode replica")
        samples = obs.parse_exposition(rt.registry.render())
        ok_migs = [v for n, lab, v in samples
                   if n == "tpu_router_migrations_total"
                   and lab.get("outcome") == "ok"]
        check(ok_migs and ok_migs[0] >= 1,
              "tpu_router_migrations_total{outcome=ok} counted "
              "before the kill")

        # -- burst + kill mid-migration --------------------------------
        results = {}
        started = threading.Event()

        def burst_one(key):
            started.wait(timeout=30)
            results[key] = unary(long_prompt())

        burst = [threading.Thread(target=burst_one, args=(f"r{i}",))
                 for i in range(6)]
        for t in burst:
            t.start()
        started.set()
        time.sleep(0.2)     # let prefills land on the victim
        victim.kill()
        victim.wait(timeout=30)
        t_kill = time.monotonic()
        for t in burst:
            t.join(timeout=180)

        completed = well_formed_errors = 0
        for key, (st, rep, body, exc) in sorted(results.items()):
            check(exc is None,
                  f"burst request {key} got a parseable response, "
                  f"not a transport error ({exc})")
            if st == 200 and body is not None and "done" in body:
                completed += 1
            else:
                # the acceptance contract: a request that could not
                # complete must end in a STRUCTURED 502/503, never a
                # torn body
                check(st in (502, 503) and body is not None
                      and "error" in body,
                      f"burst request {key} ended in a well-formed "
                      f"502/503 frame (got {st}: {body})")
                well_formed_errors += 1
        check(completed >= 1,
              f"requests completed on the surviving replica "
              f"({completed} of {len(results)} did, "
              f"{well_formed_errors} well-formed errors)")

        # post-kill: disagg stands down (one class left) and every
        # new request lands whole on the decode-class survivor
        for i in range(3):
            st, rep, body, exc = unary(long_prompt(), budget=8)
            check(exc is None and st == 200
                  and rep == "disagg-decode",
                  f"post-kill request {i} served by the survivor "
                  f"(got {st} via {rep})")
        reconverge_s = time.monotonic() - t_kill
        check(reconverge_s < 60.0,
              f"post-kill traffic reconverged in {reconverge_s:.1f}s")

        # journal + metric proof
        samples = obs.parse_exposition(rt.registry.render())
        fallbacks = sum(
            v for n, lab, v in samples
            if n == "tpu_router_migrations_total"
            and lab.get("outcome") in ("fallback",
                                       "prefill_unavailable",
                                       "prefill_error"))
        migrated_post = [
            v for n, lab, v in samples
            if n == "tpu_router_migrations_total"
            and lab.get("outcome") == "ok"]
        names = [e["name"] for e in rt.recorder.events()]
        check(fallbacks >= 1 or "tpu_router_migrate_fallback" in names
              or completed == len(results),
              "migration fallback counted or every burst request "
              "completed through a surviving path")
        check("tpu_router_migrated" in names,
              "successful migration journaled")
        opened = [e for e in rt.recorder.events(
            name="tpu_breaker_transition")
            if e["attrs"].get("op")
            == "router.replica.disagg-prefill"
            and e["attrs"].get("to") == "open"]
        stale = [e for e in rt.recorder.events(
            name="tpu_router_replica_evicted")
            if e["attrs"].get("replica") == "disagg-prefill"]
        check(bool(opened or stale),
              "victim breaker opened (or the stale replica was "
              "evicted) in the journal")
        healthy = {lab.get("replica"): v for n, lab, v in samples
                   if n == "tpu_router_replica_healthy"}
        check(healthy.get("disagg-decode") == 1,
              "tpu_router_replica_healthy{disagg-decode} = 1")
        check(healthy.get("disagg-prefill", 0) == 0,
              "tpu_router_replica_healthy{disagg-prefill} = 0 "
              "after the kill")
        check(migrated_post and migrated_post[0] >= 1,
              "migration ledger intact after the kill")
        statz = survivor.statz()
        check(statz["role"] == "decode",
              "survivor /statz advertises role=decode")
    finally:
        survivor.stop()
        rt.stop()
        victim.kill()
        try:
            victim.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


def episode_trace_replay_kill(seed):
    """Episode 13: the goodput gate's exact shape, library-driven — a
    seeded production trace (bursty MMPP arrivals, Zipf prefixes, both
    SLO classes) replayed open-loop through the router against two
    real replica subprocesses, with one replica SIGKILLed mid-burst.
    The replay report must carry the whole chaos story on the router's
    own journal/metric surfaces (breaker opened OR failover counted,
    TTL eviction journaled, recovery probes green) AND the client-side
    join: per-class attainment stays above the floor and recovers in
    the post-kill window."""
    import argparse

    from tpu_k8s_device_plugin import obs
    from tpu_k8s_device_plugin.workloads import replay
    from tpu_k8s_device_plugin.workloads.trafficgen import (
        TraceConfig,
        generate,
    )

    cfg = TraceConfig(
        n_requests=48, base_rate_rps=8.0, burst_rate_rps=40.0,
        p_enter_burst=0.05, p_exit_burst=0.1, prefix_chunk=16,
        n_prefixes=4, max_prefix_chunks=2, prompt_median=24.0,
        prompt_max=48, output_median=24.0, output_max=64,
        vocab=256, unary_frac=0.25, slow_reader_frac=0.0,
        abandon_frac=0.0)
    requests = generate(cfg, seed)
    # kill mid-trace: a third of the arrivals in, burst or not — the
    # tail must outlive the settle window so recovery is measurable
    kill_ms = requests[len(requests) // 3].t_ms
    policies = obs.default_slo_policies()
    metrics = replay.ReplayMetrics(obs.Registry(), policies)
    args = argparse.Namespace(
        replicas=2, config="tiny", slots=2, max_len=512,
        max_new_tokens=128, prefix_chunk=16, seed=seed,
        kill_replica_at_ms=kill_ms, slo=None, time_scale=1.0,
        late_ms=100.0, timeout_s=120.0, top_missed=3)
    report = replay.run_fleet(args, requests, policies, metrics,
                              trace_header={"seed": seed})

    chaos = report["chaos"]
    check(chaos["killed_replica"] == "replay-1",
          "report names the SIGKILLed replica")
    check(chaos["breaker_opened"] or chaos["failovers"] > 0,
          "router journaled the death: breaker opened or a request "
          "failed over off the corpse")
    check(chaos["replica_evicted"],
          "statz sweep evicted the silent replica "
          "(tpu_router_replica_evicted journaled)")
    check(chaos["recovery_probes_ok"] == chaos["recovery_probes"],
          "post-trace probes all served by the survivor")
    for cls in ("interactive", "batch"):
        info = report["classes"][cls]
        check(info["eligible"] > 0,
              f"{cls}: trace landed eligible requests")
        check(info["attainment"] >= 0.5,
              f"{cls}: goodput floor held through the kill "
              f"(attainment {info['attainment']})")
        post = chaos["attainment_windows"][cls]["post_kill"]
        check(post is None or post >= 0.5,
              f"{cls}: post-kill attainment recovered ({post})")
    # the replay's own obs families carried the joined accounting
    samples = obs.parse_exposition(metrics.registry.render())
    total = sum(v for name, _, v in samples
                if name == "tpu_replay_requests_total")
    check(total == len(requests),
          "tpu_replay_requests_total accounts every trace request")


def episode_fleet_degraded_drain(tmp, seed):
    """Episode 14 (PR 16): degraded-slice drain under load.  The fleet
    reconciler (workloads/fleet.py) runs its full gate episode with the
    SIGKILL arm disabled and the degraded-reshape arm live: mid-peak,
    the capacity file's slice generation bumps with ``degraded: true``
    while the open-loop ramp is still streaming.  The controller must
    execute a rolling drain (router ``POST /drain`` first — no new
    streams, in-flight finishes), stop the stale replica, and respawn
    on the NEW generation, all without a single malformed client
    frame.  Evidence is the episode report (journal + tpu_fleet_*
    metrics), not logs."""
    import argparse

    from tpu_k8s_device_plugin.workloads import fleet

    workdir = os.path.join(tmp, f"fleet-ep14-{seed}")
    os.makedirs(workdir, exist_ok=True)
    args = argparse.Namespace(
        mode="episode", seed=seed, max_replicas=2,
        # a shorter ramp than the CI fleet-gate: this episode proves
        # the drain choreography under load, not the scaling curve
        calm_requests=8, peak_requests=28, tail_requests=6,
        calm_rate=2.0, peak_rate=8.0,
        high_watermark=1.0, low_watermark=0.25,
        up_stable_s=0.5, down_stable_s=2.0, cooldown_s=2.0,
        drain_timeout_s=20.0, kill_at_ms=None, degrade_at_ms=None,
        no_kill=True, no_degrade=False, capacity_spec="",
        workdir=workdir, time_scale=1.0, late_ms=100.0,
        timeout_s=120.0, settle_s=20.0, top_missed=3,
        report=None, metrics_out=None, assert_goodput=None,
        assert_fleet=False, fault_spec=None,
        config="tiny", slots=2, max_len=512, max_new_tokens=128,
        prefix_chunk=16, slo=None,
        compile_cache_dir=os.environ.get(
            "TPU_DP_COMPILE_CACHE_DIR",
            os.path.join("tests", ".jax_cache")))
    report, _ = fleet.run_episode(args)

    f, c = report["fleet"], report["chaos"]
    check(f["degraded_drained"],
          "generation bump drained the stale replica "
          "(tpu_fleet_scale_events_total{direction=down,"
          "reason=degraded})")
    check(f["respawned_on_new_generation"],
          "drain was followed by a respawn placed on generation 2")
    check(f["replicas_stopped"] >= 1,
          "the drained replica was actually stopped "
          "(tpu_fleet_replica_stopped journaled)")
    check(c["frame_errors"] == 0,
          f"zero malformed client frames through the drain "
          f"(got {c['frame_errors']})")
    check(f["final_replicas"] >= 1,
          "fleet settled at/above the floor after the reshape")
    for cls in ("interactive", "batch"):
        info = report["classes"][cls]
        check(info["eligible"] > 0,
              f"{cls}: ramp landed eligible requests")
        check(info["attainment"] >= 0.5,
              f"{cls}: goodput floor held through the rolling drain "
              f"(attainment {info['attainment']})")


def episode_fleet_burn_alert(tmp, seed):
    """Episode 15 (PR 18): burn-rate page alert through a replica
    SIGKILL.  The fleet episode runs with the kill arm live: mid-burst
    a replica dies, the survivor drowns, the per-class SLO burn gauges
    the replicas publish through /statz roll up into the router's
    ``tpu_router_fleet_burn_rate`` — and the router's multi-window
    multi-burn-rate evaluator must page.  The reconciler replaces the
    dead replica (reason=failure, or reason=alert if the pre-chewed
    page verdict lands first), and once the fleet recovers and the
    shrunk windows drain, the page alert must traverse to
    ``resolved``.  Every asserted fact comes from the episode report:
    the journaled ``tpu_alert_transition`` state machine and the
    ``tpu_fleet_*`` spawn evidence — never logs."""
    import argparse

    from tpu_k8s_device_plugin.workloads import fleet

    workdir = os.path.join(tmp, f"fleet-ep15-{seed}")
    os.makedirs(workdir, exist_ok=True)
    args = argparse.Namespace(
        mode="episode", seed=seed, max_replicas=2,
        # the CI fleet-gate ramp: big enough that pressure reliably
        # scales the fleet to 2 BEFORE the kill hook's routable-fleet
        # gate releases the SIGKILL (ep14's short drain ramp never
        # crosses the watermark, so the kill would time out unarmed)
        calm_requests=16, peak_requests=72, tail_requests=20,
        calm_rate=2.0, peak_rate=10.0,
        high_watermark=1.0, low_watermark=0.25,
        up_stable_s=0.5, down_stable_s=2.0, cooldown_s=2.0,
        drain_timeout_s=20.0, kill_at_ms=None, degrade_at_ms=None,
        no_kill=False, no_degrade=True, capacity_spec="",
        workdir=workdir, time_scale=1.0, late_ms=100.0,
        timeout_s=120.0, settle_s=30.0, top_missed=3,
        report=None, metrics_out=None, assert_goodput=None,
        assert_fleet=False, fault_spec=None,
        config="tiny", slots=2, max_len=512, max_new_tokens=128,
        prefix_chunk=16,
        # a TTFT budget the fleet meets at calm but cannot meet while
        # a kill leaves the survivor queueing the whole burst — the
        # collapse that must drive the burn gauge past 14.4x
        slo=["interactive=1200", "batch=0:20000"],
        # canonical 5m/1h/6h burn windows scaled to 0.6s/7.2s/43.2s
        # and a 0.25s evaluation tick, so the page rule can observe
        # the distress AND resolve inside the episode's wall clock
        alert_interval=0.25, alert_window_scale=0.002,
        # replicas keep only a 3s rolling SLO window: once the burst
        # drains, their burn gauges fall back to zero fast enough for
        # the firing -> resolved transition to land before harvest
        server_extra_args=("--slo-window", "3"),
        settle_on_alerts=True,
        compile_cache_dir=os.environ.get(
            "TPU_DP_COMPILE_CACHE_DIR",
            os.path.join("tests", ".jax_cache")))
    report, _ = fleet.run_episode(args)

    f, c = report["fleet"], report["chaos"]
    check(c["killed_replica"],
          "chaos arm SIGKILLed a managed replica mid-burst")
    transitions = f["alert_transitions"]
    paged = sorted({t["alert"] for t in transitions
                    if t["severity"] == "page"
                    and t["to"] == "firing"})
    check(paged,
          "a page-severity burn-rate alert reached firing "
          "(tpu_alert_transition journal)")
    # the full state machine for one paging rule, in order: the dwell
    # (inactive->pending), the page (pending->firing), the recovery
    # (firing->resolved) — all journaled by the router's evaluator
    path = [(t["from"], t["to"]) for t in transitions
            if t["alert"] == paged[0]]
    want = [("inactive", "pending"), ("pending", "firing"),
            ("firing", "resolved")]
    it = iter(path)
    check(all(step in it for step in want),
          f"{paged[0]} traversed "
          f"inactive->pending->firing->resolved (got {path})")
    check(f["replaced_after_kill"] or f["alert_scale_up_events"] >= 1,
          "reconciler replaced the dead replica (reason=failure) or "
          "scaled on the page verdict (reason=alert)")
    check(f["final_replicas"] >= 1,
          "fleet settled at/above the floor after recovery")


def episode_fleet_incident_bundle(seed):
    """Episode 16 (PR 19): SIGKILL a replica mid-burst; the page that
    follows must make the router's flight data recorder write ONE
    fleet incident bundle — with the DEAD replica's fragment degraded
    to its ``{"unreachable": true}`` marker (the bundle fan-out must
    not wedge on a corpse), the survivor's fragment real, and the
    incident subscriber still alive afterwards (``/alerts`` answers,
    traffic still proxies 200)."""
    import http.client
    import json
    import os
    import subprocess
    import sys
    import threading
    import urllib.request

    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.bench_serving import (
        _free_port,
        _wait_http_ok,
    )
    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.router import (
        RouterServer,
        affinity_key,
    )
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    incident_dir = tempfile.mkdtemp(prefix=f"tpu-chaos-ep16-{seed}-")
    # class 'bad' can never meet its 1ms deadline: once the post-kill
    # traffic lands on the survivor the fleet burn gauge pages
    policies = {
        "bad": obs.SLOPolicy("bad", deadline_ms=1.0),
        "good": obs.SLOPolicy("good", deadline_ms=60000.0),
    }
    # replica_ttl 30s: the victim's row must STILL be in the table
    # when the bundle fans out, so the fragment fetch proves the
    # unreachable-marker path rather than skipping the dead replica
    rt = RouterServer(statz_interval_s=0.25, replica_ttl_s=30.0,
                      breaker_reset_s=0.5, seed=seed,
                      slo_policies=policies,
                      alert_interval_s=0.25,
                      alert_window_scale=0.0005,
                      incident_dir=incident_dir)
    rt.start(host="127.0.0.1", port=0)

    # survivor: in-process tiny engine registered as replica-a, with
    # the SLO accountant live so its /statz publishes the burn the
    # router rolls up into tpu_router_fleet_burn_rate
    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=256, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    survivor = EngineServer(eng, max_new_tokens=200, window=4,
                            slo_policies=policies, slo_window_s=30.0)
    survivor.start(host="127.0.0.1", port=0)
    survivor.start_registration(
        f"http://127.0.0.1:{rt.port}", replica_id="replica-a",
        model="chaos-tiny", interval_s=0.3)

    # victim: a REAL replica subprocess; max_len 2048 so the burst
    # stream still has seconds of decode left when the SIGKILL lands
    victim_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    victim = subprocess.Popen(
        [sys.executable, "-m",
         "tpu_k8s_device_plugin.workloads.server",
         "--config", "tiny", "--n-slots", "2", "--max-len", "2048",
         "--max-new-tokens", "2000", "--window", "4",
         "--host", "127.0.0.1", "--port", str(victim_port),
         "--register-with", f"http://127.0.0.1:{rt.port}",
         "--replica-id", "replica-b", "--register-interval", "0.3"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    try:
        _wait_http_ok(victim_port, "/healthz", 600)
        _wait_http_ok(
            rt.port, "/replicas", 30,
            lambda b: sum(r["healthy"] for r in b["replicas"]) >= 2)
        check(True, "router sees both replicas healthy")

        def post(body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{rt.port}/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, resp.read()

        # one long stream pinned to the victim via the affinity ring,
        # so the SIGKILL lands mid-burst, not on an idle replica
        rng = random.Random(seed)
        p_victim = None
        while p_victim is None:
            cand = [rng.randrange(1, 128) for _ in range(32)]
            if rt.affinity_target(
                    affinity_key({"tokens": cand}, 32)) == "replica-b":
                p_victim = cand

        streaming = threading.Event()

        def burst():
            conn = http.client.HTTPConnection("127.0.0.1", rt.port,
                                              timeout=120)
            try:
                conn.request("POST", "/generate", json.dumps(
                    {"tokens": p_victim, "max_new_tokens": 1500,
                     "ignore_eos": True, "slo_class": "good"}),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                for line in resp:
                    if line.strip():
                        streaming.set()
            # tpulint: disable=R2 -- the SIGKILL is SUPPOSED to abort this stream mid-chunk; the episode's assertions live in the incident bundle, not in this thread's outcome
            except Exception:
                pass
            finally:
                conn.close()

        t = threading.Thread(target=burst, daemon=True)
        t.start()
        check(streaming.wait(60.0),
              "victim-pinned stream is live before the kill")
        victim.kill()
        victim.wait()
        t.join(timeout=30.0)
        check(not t.is_alive(), "aborted stream drained, not hung")

        # goodput collapse on the survivor: every 'bad' request fails
        # over to replica-a and misses its 1ms deadline
        for _ in range(4):
            st, _ = post({"tokens": [1, 2, 3], "max_new_tokens": 4,
                          "slo_class": "bad"})
            check(st == 200,
                  f"post-kill 'bad' request failed over 200 (got {st})")

        # the fleet bundle materializes (page -> subscriber -> write)
        deadline = time.time() + 45.0
        bundles = []
        while time.time() < deadline and not bundles:
            bundles = [p for p in os.listdir(incident_dir)
                       if p.startswith(obs.BUNDLE_PREFIX)]
            time.sleep(0.2)
        check(len(bundles) == 1,
              f"exactly one fleet incident bundle (got {bundles}, "
              f"dir {os.listdir(incident_dir)})")
        bundle = obs.read_bundle(os.path.join(incident_dir, bundles[0]))
        meta = bundle["meta"]
        check(meta["severity"] == "page"
              and meta["alert"].startswith("slo_burn_page"),
              f"bundle is for the page ({meta['alert']}, "
              f"{meta['severity']})")
        dead = bundle.get("replicas/replica-b/statz.json")
        check(isinstance(dead, dict) and dead.get("unreachable") is True,
              f"dead replica's fragment degraded to the unreachable "
              f"marker (got {dead!r})")
        live = bundle.get("replicas/replica-a/statz.json")
        check(isinstance(live, dict) and "unreachable" not in live,
              "survivor's statz fragment is real")
        check(any("burn_rate" in s["name"] and s["points"]
                  for s in bundle["tsdb.json"]["series"]),
              "bundle's TSDB snapshot retained the burn series")

        # the subscriber is NOT wedged: the evaluator still serves
        # /alerts, the worker thread survives, traffic still proxies
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rt.port}/alerts",
                timeout=30) as resp:
            status = json.loads(resp.read().decode())
        check(any(a["name"] == meta["alert"] and a["state"] == "firing"
                  for a in status["alerts"]),
              "router /alerts still answering after the bundle")
        assert rt._incidents is not None
        check(rt._incidents._worker is not None
              and rt._incidents._worker.is_alive(),
              "incident worker thread alive after the bundle")
        st, _ = post({"tokens": [5, 6, 7], "max_new_tokens": 4,
                      "slo_class": "good"})
        check(st == 200, f"router still proxying 200 (got {st})")
    finally:
        if victim.poll() is None:
            victim.kill()
        survivor.stop()
        rt.stop()
        shutil.rmtree(incident_dir, ignore_errors=True)


def _reshape_slice(tmp, testdata, seed, suffix, grace, hb_timeout):
    """A dedicated 2-host slice with live staleness + reshape grace (the
    main soak coordinator drives heartbeats manually with no timeout, so
    eviction-by-silence needs its own)."""
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    coordinator = SliceCoordinator(
        expected_workers=2,
        bind_address="127.0.0.1:0",
        jax_port=_JAX_PORT,
        state_path=os.path.join(tmp, f"coordinator-{suffix}.json"),
        heartbeat_timeout_s=hb_timeout,
        reshape_grace_s=grace,
        registry=registry,
        recorder=recorder,
    ).start()
    rendezvous = f"127.0.0.1:{coordinator.port}"
    hosts = [
        ChaosHost(f"host-{suffix}0", "v5e-16-host0", testdata, tmp,
                  rendezvous, seed),
        ChaosHost(f"host-{suffix}1", "v5e-16-host1", testdata, tmp,
                  rendezvous, seed),
    ]
    import concurrent.futures
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        for f in [pool.submit(h.client.join, 20.0) for h in hosts]:
            f.result(timeout=30.0)
    for h in hosts:
        h.manager.run(block=False)
        check(h.kubelet.wait_for_registration(timeout=10.0),
              f"{h.name} registered with its kubelet")
        h.open_stream()
        h.pulse()
        h.wait_frame(all_healthy)
    return coordinator, recorder, registry, hosts


def episode_session_spill_crash_resume(seed):
    """Episode 17: session KV tiering through a replica SIGKILL.  A
    conversation idles down the full tier chain (device park -> host
    checkpoint -> crash-safe .kvs spill file) on a REAL replica
    subprocess, the replica is SIGKILLed with the session spilled, and
    the respawned generation — same CLI, same --session-dir — serves
    the returning session's next turn BYTE-IDENTICALLY to an
    uninterrupted control replica that kept the conversation
    device-parked the whole time.  An in-process probe replica runs
    the same chain with its flight recorder visible, proving every
    transition journaled (tpu_kv_park / demote / spill / promote);
    the subprocess legs are proven on their /statz + /metrics
    surfaces (tpu_kv_tier_demotions_total{tier=disk} before the kill,
    tpu_kv_tier_{hits,promotions}_total{tier=disk} after respawn)."""
    import http.client
    import json
    import shutil as ep_shutil
    import subprocess
    import tempfile

    from tpu_k8s_device_plugin.workloads.bench_serving import (
        _free_port,
        _wait_http_ok,
        build_model_and_params,
    )
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    tmp = tempfile.mkdtemp(prefix="chaos-kv-tier-")
    cfg, model, params = build_model_and_params("tiny", 256, False)
    eos = getattr(cfg, "eos_id", None)

    def mk_server(sub, idle_s, host_idle_s):
        eng = ServingEngine(model, params, n_slots=4, eos_id=eos,
                            kv_paging=True)
        return EngineServer(eng, max_new_tokens=64, window=4,
                            session_tier=True,
                            session_dir=os.path.join(tmp, sub),
                            session_idle_s=idle_s,
                            session_host_idle_s=host_idle_s,
                            session_seed=seed)

    # control: generous timers — the conversation never leaves the
    # device tier, so its turn 2 is the uninterrupted oracle
    control = mk_server("ctrl", 3600.0, 3600.0)
    control.start(host="127.0.0.1", port=0)
    # probe: soak-speed timers + a visible flight recorder
    probe = mk_server("probe", 0.3, 0.3)
    probe.start(host="127.0.0.1", port=0)

    # victim: a REAL replica subprocess (the CLI a pod runs) — the
    # SIGKILL is a kill, and only the .kvs files survive it
    victim_port = _free_port()
    victim_dir = os.path.join(tmp, "victim")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")

    def spawn_victim():
        return subprocess.Popen(
            [sys.executable, "-m",
             "tpu_k8s_device_plugin.workloads.server",
             "--config", "tiny", "--n-slots", "4", "--max-len", "256",
             "--max-new-tokens", "64", "--window", "4", "--kv-paging",
             "--session-tier", "--session-dir", victim_dir,
             "--session-idle", "0.3", "--session-host-idle", "0.3",
             "--session-seed", str(seed),
             "--host", "127.0.0.1", "--port", str(victim_port)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    victim = spawn_victim()
    respawn = None

    p1 = [(i * 7) % 255 + 1 for i in range(24)]
    p2 = [9, 8, 7]
    sid = "soak-conv"

    def gen(port, tokens, session=None):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=120)
        try:
            payload = {"tokens": list(tokens), "max_new_tokens": 12,
                       "stream": False, "ignore_eos": True}
            if session is not None:
                payload["session_id"] = session
            conn.request("POST", "/generate", json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            return resp.status, body.get("tokens")
        finally:
            conn.close()

    def statz(port):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=30)
        try:
            conn.request("GET", "/statz")
            return json.loads(conn.getresponse().read())
        finally:
            conn.close()

    def tier_metrics(port):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=30)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
            return obs.parse_exposition(text)
        finally:
            conn.close()

    try:
        # -- uninterrupted control conversation ------------------------
        st, out1 = gen(control.port, p1, sid)
        check(st == 200 and out1, "control turn 1 answered 200")
        chain = p1 + out1 + p2
        st, want = gen(control.port, chain, sid)
        check(st == 200 and want, "control turn 2 answered 200")
        check(statz(control.port)["kv_tiers"]["hits"]["device"] >= 1,
              "control turn 2 was a device-tier warm hit")
        check(bool(control.recorder.events(name="tpu_kv_park")),
              "session park journaled on the control replica")

        # -- journal probe: the full tier chain in one process ---------
        st, out1p = gen(probe.port, p1, sid)
        check(st == 200 and out1p == out1,
              "probe turn 1 matches control bit-for-bit")
        deadline = time.monotonic() + 30.0
        while (time.monotonic() < deadline
               and statz(probe.port)["kv_tiers"]["disk"] < 1):
            time.sleep(0.1)
        check(statz(probe.port)["kv_tiers"]["disk"] >= 1,
              "probe session idled down to the disk tier")
        for name in ("tpu_kv_park", "tpu_kv_demote", "tpu_kv_spill"):
            check(bool(probe.recorder.events(name=name)),
                  f"{name} journaled on the probe replica")
        st, got = gen(probe.port, chain, sid)
        check(st == 200 and got == want,
              "probe disk-tier resume byte-identical to the "
              "uninterrupted control")
        promoted = [e for e in probe.recorder.events(
            name="tpu_kv_promote")
            if e["attrs"].get("tier") == "disk"
            and e["attrs"].get("outcome") == "ok"]
        check(bool(promoted), "disk promotion journaled on the probe")

        # -- victim: spill, SIGKILL, respawn from the same dir ---------
        _wait_http_ok(victim_port, "/healthz", 600)
        st, out1v = gen(victim_port, p1, sid)
        check(st == 200 and out1v == out1,
              "victim turn 1 matches control (deterministic params "
              "across processes)")
        _wait_http_ok(victim_port, "/statz", 60,
                      lambda b: b["kv_tiers"]["disk"] >= 1)
        demoted = [v for n, lab, v in tier_metrics(victim_port)
                   if n == "tpu_kv_tier_demotions_total"
                   and lab.get("tier") == "disk"]
        check(bool(demoted) and sum(demoted) >= 1,
              "tpu_kv_tier_demotions_total{tier=disk} counted on the "
              "victim before the kill")
        victim.kill()          # SIGKILL: no drain, no spill_all
        victim.wait(timeout=30)
        spills = [f for f in os.listdir(victim_dir)
                  if f.endswith(".kvs")]
        check(bool(spills), "spill file survived the SIGKILL")

        respawn = spawn_victim()
        _wait_http_ok(victim_port, "/healthz", 600)
        check(statz(victim_port)["kv_tiers"]["disk"] >= 1,
              "respawned generation inherited the spilled session "
              "from the filenames alone")
        st, got = gen(victim_port, chain, sid)
        check(st == 200, "post-crash turn 2 answered 200")
        check(got == want,
              "post-crash resume byte-identical to uninterrupted "
              "serving")
        samples = tier_metrics(victim_port)
        hits = [v for n, lab, v in samples
                if n == "tpu_kv_tier_hits_total"
                and lab.get("tier") == "disk"]
        check(bool(hits) and sum(hits) >= 1,
              "tpu_kv_tier_hits_total{tier=disk} counted after "
              "respawn")
        promos = [v for n, lab, v in samples
                  if n == "tpu_kv_tier_promotions_total"
                  and lab.get("tier") == "disk"
                  and lab.get("outcome") == "ok"]
        check(bool(promos) and sum(promos) >= 1,
              "tpu_kv_tier_promotions_total{tier=disk,outcome=ok} "
              "counted after respawn")
    finally:
        control.stop()
        probe.stop()
        for proc in (victim, respawn):
            if proc is not None:
                proc.kill()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
        ep_shutil.rmtree(tmp, ignore_errors=True)


def episode_member_loss_reshape(testdata, tmp, seed):
    """(7) Member loss mid-traffic: staleness demotes the slice
    (demote-all while the member might return), the grace window
    expires, the survivor re-forms into a smaller degraded generation
    and serves Healthy at the reduced shape — within one staleness
    timeout + one grace window + a couple of pulses, journal-proven."""
    hb_timeout, grace = 0.4, 0.6
    coordinator, recorder, registry, hosts = _reshape_slice(
        tmp, testdata, seed, "r", grace, hb_timeout)
    survivor, victim = hosts
    try:
        gen1 = survivor.client.membership
        check(gen1 is not None and gen1.num_workers == 2
              and not gen1.degraded,
              "2-host slice formed whole before the loss")
        t_kill = time.monotonic()
        victim.stop()           # the member dies mid-traffic
        # the survivor's own pulses must first deliver the demote-all
        # verdict (the member might still return), then — at grace
        # expiry — the reshaped generation
        survivor.pulse()
        deadline = time.time() + hb_timeout + grace + 8.0
        while time.time() < deadline:
            survivor.pulse()
            m = survivor.client.membership
            if m is not None and m.generation > gen1.generation:
                break
            time.sleep(0.05)
        adopted_after = time.monotonic() - t_kill
        m = survivor.client.membership
        check(m is not None and m.generation == gen1.generation + 1,
              "survivor adopted the next generation "
              f"({adopted_after:.1f}s after the kill)")
        check(m.hostnames == (survivor.name,),
              "reshaped membership is the survivor alone (rank 0)")
        check(m.reshaped_from == (gen1.slice_id,),
              "lineage carries the original slice id")
        check(m.degraded, "reshaped membership marked degraded")
        check(adopted_after <= hb_timeout + grace + 3.0,
              "reshape landed within one staleness timeout + one grace "
              f"window + pulse slack ({adopted_after:.1f}s)")
        # journal evidence on both sides
        reshaped = recorder.events(name="tpu_slice_reshaped")
        check(reshaped and reshaped[-1]["attrs"]["generation"]
              == m.generation,
              "coordinator journaled tpu_slice_reshaped for gen "
              f"{m.generation}")
        check(reshaped[-1]["attrs"]["degraded"] is True,
              "journal marks the reshaped generation degraded")
        adoptions = [e for e in survivor.journal(
            "tpu_slice_membership_adopted")
            if e["attrs"].get("generation") == m.generation]
        check(adoptions, "survivor journaled the gen-2 adoption")
        samples = obs.parse_exposition(registry.render())
        reshapes = [v for n, lab, v in samples
                    if n == "tpu_slice_reshape_total"
                    and lab.get("outcome") == "reshaped"]
        check(reshapes and reshapes[0] >= 1,
              "tpu_slice_reshape_total{outcome=reshaped} counted")
        secs = [v for n, lab, v in samples
                if n == "tpu_slice_reshape_seconds_count" and not lab]
        check(secs and secs[0] >= 1,
              "tpu_slice_reshape_seconds observed the window")
        # the survivor must SERVE at the reduced shape: devices Healthy
        # and the Allocate contract re-emitted for 1 worker
        frame = survivor.wait_frame(all_healthy)
        check(len(frame.devices) == 8,
              "survivor re-advertises all 8 local devices Healthy at "
              "the reduced shape")
        stub = survivor.kubelet.plugin_stub("google.com_tpu")
        resp = stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[pluginapi.ContainerAllocateRequest(
                devices_ids=[d.ID for d in frame.devices])]))
        env = dict(resp.container_responses[0].envs)
        check(env.get(constants.ENV_TPU_WORKER_ID) == "0"
              and env.get(constants.ENV_TPU_WORKER_HOSTNAMES)
              == survivor.name
              and env.get(constants.ENV_JAX_NUM_PROCESSES) == "1"
              and env.get(constants.ENV_TPU_SLICE_GENERATION)
              == str(m.generation),
              "survivor serves the re-emitted identity contract at the "
              "reduced shape")
    finally:
        survivor.stop()
        coordinator.stop()


def episode_member_flap_no_reshape(testdata, tmp, seed):
    """(8) The member goes silent past the staleness timeout (verdict
    demotes, reshape window opens) but flaps BACK inside the grace
    window: no reshape — the original generation holds bit-for-bit."""
    # grace must comfortably exceed the bounded demote-frame wait below
    # plus pulse slack on a loaded CI box: the point of this episode is
    # the member returning INSIDE the window
    hb_timeout, grace = 0.4, 10.0
    coordinator, recorder, registry, hosts = _reshape_slice(
        tmp, testdata, seed, "f", grace, hb_timeout)
    a, b = hosts
    try:
        gen1 = a.client.membership
        # b goes silent past the staleness timeout; a's pulse trips it
        time.sleep(hb_timeout * 2)
        a.pulse()
        overlay = a.client.health_overlay()
        check(overlay is not None and not overlay[0],
              "verdict demoted while the member is silent (demote-all "
              "inside the grace window)")
        a.wait_frame(all_unhealthy, pulses=5, timeout_s=3.0)
        check(True, "survivor demoted all devices during the window")
        # the member flaps back BEFORE the grace expires
        b.pulse()
        a.pulse()
        for h in (a, b):
            h.wait_frame(all_healthy)
        m = a.client.membership
        check(m == gen1,
              "original generation holds bit-for-bit after the flap "
              f"(gen {m.generation}, {len(m.hostnames)} workers)")
        check(not recorder.events(name="tpu_slice_reshaped"),
              "no reshape journaled for an in-grace flap")
        samples = obs.parse_exposition(registry.render())
        cancelled = [v for n, lab, v in samples
                     if n == "tpu_slice_reshape_total"
                     and lab.get("outcome") == "cancelled"]
        check(cancelled and cancelled[0] >= 1,
              "tpu_slice_reshape_total{outcome=cancelled} counted")
        reshaped = [v for n, lab, v in samples
                    if n == "tpu_slice_reshape_total"
                    and lab.get("outcome") == "reshaped"]
        check(not reshaped, "no reshape outcome counted")
    finally:
        for h in hosts:
            h.stop()
        coordinator.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="chaos-soak")
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("ENGINE_FUZZ_SEED", "0")
                               or 0),
                   help="fault + jitter RNG seed (ENGINE_FUZZ_SEED "
                        "env honored)")
    p.add_argument("--testdata",
                   default=os.path.join(_REPO, "testdata"))
    p.add_argument("--skip-serving", action="store_true",
                   help="skip the scheduler-crash episode (no jax "
                        "needed)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    log.info("chaos soak, seed=%d", args.seed)
    manager_mod._REGISTER_RETRY_DELAY_S = 0.05  # soak-speed retries

    tmp = tempfile.mkdtemp(prefix="chaos-soak-")
    coordinator = SliceCoordinator(
        expected_workers=2,
        bind_address="127.0.0.1:0",
        jax_port=_JAX_PORT,
        state_path=os.path.join(tmp, "coordinator-membership.json"),
        heartbeat_timeout_s=0.0,    # pulses are driven explicitly
    ).start()
    rendezvous = f"127.0.0.1:{coordinator.port}"
    hosts = [
        ChaosHost("host-a", "v5e-16-host0", args.testdata, tmp,
                  rendezvous, args.seed),
        ChaosHost("host-b", "v5e-16-host1", args.testdata, tmp,
                  rendezvous, args.seed),
    ]
    try:
        # -- formation + steady state ---------------------------------
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            for f in [pool.submit(h.client.join, 20.0) for h in hosts]:
                f.result(timeout=30.0)
        for h in hosts:
            h.manager.run(block=False)
            check(h.kubelet.wait_for_registration(timeout=10.0),
                  f"{h.name} registered with its kubelet")
            frame = h.open_stream()
            check(len(frame.devices) == 8,
                  f"{h.name} advertises 8 devices")
        for h in hosts:
            h.pulse()
        for h in hosts:
            h.wait_frame(all_healthy)
        log.info("=== episode 1: kubelet register drop ===")
        episode_register_drop(hosts, args.seed)
        log.info("=== episode 2: slice join error ===")
        episode_join_error(hosts, coordinator, tmp, args.seed)
        log.info("=== episode 3: slice heartbeat loss ===")
        episode_heartbeat_loss(hosts, args.seed)
        log.info("=== episode 4: probe hang ===")
        episode_probe_hang(hosts, args.seed)
        if not args.skip_serving:
            log.info("=== episode 5: serving scheduler crash ===")
            episode_scheduler_crash(args.seed)
            log.info("=== episode 6: scheduler hang mid-interleave ===")
            episode_scheduler_hang(args.seed)
        log.info("=== episode 7: member loss -> reshape ===")
        episode_member_loss_reshape(args.testdata, tmp, args.seed)
        log.info("=== episode 8: member flap inside the grace window ===")
        episode_member_flap_no_reshape(args.testdata, tmp, args.seed)
        if not args.skip_serving:
            log.info("=== episode 9: tenant burst under KV page "
                     "pressure ===")
            episode_tenant_burst_page_pressure(args.seed)
            log.info("=== episode 10: replica kill under burst "
                     "through the router ===")
            episode_router_replica_kill(args.seed)
            log.info("=== episode 11: scheduler killed mid-packed-"
                     "prefill ===")
            episode_packed_prefill_kill(args.seed)
            log.info("=== episode 12: prefill replica killed "
                     "mid-migration ===")
            episode_prefill_kill_mid_migration(args.seed)
            log.info("=== episode 13: seeded trace replayed through "
                     "a kill ===")
            episode_trace_replay_kill(args.seed)
            log.info("=== episode 14: degraded-slice drain under "
                     "load ===")
            episode_fleet_degraded_drain(tmp, args.seed)
            log.info("=== episode 15: burn-rate page alert through "
                     "a replica kill ===")
            episode_fleet_burn_alert(tmp, args.seed)
            log.info("=== episode 16: SIGKILL mid-burst writes the "
                     "fleet incident bundle ===")
            episode_fleet_incident_bundle(args.seed)
            log.info("=== episode 17: session spill survives a "
                     "replica SIGKILL ===")
            episode_session_spill_crash_resume(args.seed)
        # -- final convergence sweep ----------------------------------
        for h in hosts:
            h.pulse()
        for h in hosts:
            h.wait_frame(all_healthy)
        m = hosts[0].client.membership
        check(m is not None and m.hostnames == ("host-a", "host-b"),
              "slice still formed with stable ranks")
        transitions = (hosts[0].journal("tpu_breaker_transition")
                       + hosts[0].journal("tpu_watchdog_trip"))
        check(transitions,
              "flight recorder journaled breaker/watchdog transitions")
        log.info("CHAOS SOAK PASS (seed=%d)", args.seed)
        return 0
    finally:
        faults.uninstall()
        for h in hosts:
            h.stop()
        coordinator.stop()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
