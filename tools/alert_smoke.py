#!/usr/bin/env python3
"""alert-smoke: the end-to-end acceptance check for burn-rate alerting.

A REAL serving subprocess gets a synthetic goodput collapse (an SLO
class whose 1ms completion deadline no request can meet), and the
multi-window multi-burn-rate machinery must prove, from its own
surfaces:

  1. the page alert reaches ``firing`` — and the journal + retained
     burn-rate series show it fired within two evaluation ticks of
     the collapse reaching the burn gauge,
  2. ``/metrics`` stays promlint-clean in BOTH exposition modes with
     the ``tpu_alert_*`` and ``tpu_scrape_*`` families present,
  3. after the collapse stops, the alert traverses to ``resolved``,
  4. the flight-recorder journal carries the full state traversal
     (inactive -> pending -> firing -> resolved) as
     ``tpu_alert_transition`` events.

Windows are shrunk with ``alert_window_scale`` so the canonical
5m/1h/6h SRE windows run in seconds — the same knob the chaos soak and
the fleet controller use.  CI runs this in the ``metrics-lint`` job;
also runnable by hand:

    JAX_PLATFORMS=cpu python tools/alert_smoke.py
"""
# tpulint: disable-file=R1 -- smoke DRIVER: single-shot requests against a subprocess it just started; a failure IS the test failing, retries would only blur which layer lost the alert

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.promlint import lint                      # noqa: E402

ALERT_INTERVAL_S = 0.5
WINDOW_SCALE = 0.0005  # 5m/1h/6h -> 0.15s / 1.8s / 10.8s
PAGE_ALERT = "slo_burn_page_bad"

_SERVER_PROG = """
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.server import EngineServer
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                     d_ff=128, max_len=64, dtype=jnp.float32)
tokens = jnp.zeros((1, 8), jnp.int32)
pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
eng = ServingEngine(model, params, n_slots=2)
# class 'bad' can never meet its 1ms deadline: every request misses,
# burn = 1/(1-0.99) = 100x the moment traffic lands on it
policies = {{
    "bad": obs.SLOPolicy("bad", deadline_ms=1.0),
    "good": obs.SLOPolicy("good", deadline_ms=60000.0),
}}
srv = EngineServer(eng, max_new_tokens=4, window=2,
                   slo_policies=policies, slo_window_s=3.0,
                   alert_interval_s={interval!r},
                   alert_window_scale={scale!r})
srv.start(host="127.0.0.1", port=0)
print(json.dumps({{"port": srv.port}}), flush=True)
import threading
threading.Event().wait()
"""


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read().decode())


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return dict(resp.headers), resp.read().decode()


def _alert(status, name):
    for a in status["alerts"]:
        if a["name"] == name:
            return a
    raise AssertionError(f"{name} missing from /alerts: "
                         f"{[a['name'] for a in status['alerts']]}")


def _wait_for_state(port, name, want, timeout_s):
    deadline = time.time() + timeout_s
    state = None
    while time.time() < deadline:
        state = _alert(_get_json(port, "/alerts"), name)["state"]
        if state == want:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"{name} never reached {want!r} (last state {state!r})")


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _SERVER_PROG.format(repo=REPO, interval=ALERT_INTERVAL_S,
                             scale=WINDOW_SCALE)],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        port = json.loads(proc.stdout.readline())["port"]
        print(f"server up on :{port}")

        # boot state: every derived rule present, all inactive
        status = _get_json(port, "/alerts")
        assert _alert(status, PAGE_ALERT)["state"] == "inactive"
        assert _alert(status, "slo_burn_ticket_bad")["severity"] \
            == "ticket"

        # synthetic goodput collapse: every 'bad' request misses its
        # 1ms deadline, so the class burns at 100x from request one
        for i in range(4):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"tokens": [1, 2, 3],
                                 "slo_class": "bad"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                assert resp.status == 200
                resp.read()
        print("1. collapse traffic sent (4 guaranteed SLO misses)")

        _wait_for_state(port, PAGE_ALERT, "firing", timeout_s=20.0)
        firing = _alert(_get_json(port, "/alerts"), PAGE_ALERT)
        assert firing["severity"] == "page"
        # the roll-up every statz consumer (the fleet planner) reads
        statz = _get_json(port, "/statz")
        assert any(f["name"] == PAGE_ALERT
                   for f in statz["alerts"]["firing"])
        print(f"2. page alert firing (value {firing['value']:.1f})")

        # two-evaluation-tick bound, proven from the server's OWN
        # clock domain: the retained burn series says when the breach
        # first became visible to a tick; the journal says when the
        # rule fired.  No client clock involved.
        expr = urllib.parse.quote(
            'tpu_slo_error_budget_burn_rate{class="bad"}', safe="")
        q = _get_json(port, f"/debug/query?expr={expr}&range=60s")
        breach_ts = [t for t, v in q["series"][0]["points"]
                     if v >= 14.4]
        assert breach_ts, f"no breach sample retained: {q}"
        events = _get_json(port, "/debug/events")["events"]
        journal = [e for e in events
                   if e["name"] == "tpu_alert_transition"
                   and e["attrs"].get("alert") == PAGE_ALERT]
        fired_at = next(e["attrs"]["at"] for e in journal
                        if e["attrs"]["state_to"] == "firing")
        lag = fired_at - breach_ts[0]
        assert lag <= 2 * ALERT_INTERVAL_S + 0.25, (
            f"firing lagged first visible breach by {lag:.2f}s "
            f"(> 2 ticks of {ALERT_INTERVAL_S}s)")
        print(f"3. fired {lag:.2f}s after first retained breach "
              f"(<= 2 ticks) OK")

        # promlint-clean in both modes, alert + scrape families present
        _, plain = _get(port, "/metrics")
        _, om = _get(port, "/metrics", headers={
            "Accept": "application/openmetrics-text"})
        for mode, body in (("text", plain), ("openmetrics", om)):
            errs = lint(body)
            assert not errs, f"{mode} fails promlint: {errs[:5]}"
            for fam in ("tpu_alert_state{", "tpu_alert_transitions_total{",
                        "tpu_alert_evaluations_total",
                        "tpu_scrape_duration_seconds_bucket",
                        "tpu_scrape_series{", "tpu_scrape_size_bytes{"):
                assert fam in body, f"{fam} absent from {mode} scrape"
        print("4. both exposition modes promlint-clean with "
              "tpu_alert_*/tpu_scrape_* OK")

        # recovery: the SLO window drains (3s), burn returns to 0, the
        # page windows (0.15s/1.8s) clear, the alert must resolve
        _wait_for_state(port, PAGE_ALERT, "resolved", timeout_s=30.0)
        print("5. page alert resolved after recovery")

        # the journal proves the FULL traversal, in order
        events = _get_json(port, "/debug/events")["events"]
        path = [(e["attrs"]["state_from"], e["attrs"]["state_to"])
                for e in events
                if e["name"] == "tpu_alert_transition"
                and e["attrs"].get("alert") == PAGE_ALERT]
        assert path[:3] == [("inactive", "pending"),
                            ("pending", "firing"),
                            ("firing", "resolved")], path
        assert all(e["attrs"]["severity"] == "page" for e in events
                   if e["name"] == "tpu_alert_transition"
                   and e["attrs"].get("alert") == PAGE_ALERT)
        print(f"6. journal traversal OK ({path})")
        print("alert-smoke: PASS")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
