#!/usr/bin/env python3
"""promlint: validate Prometheus text-exposition output.  Stdlib only.

The CI ``metrics-lint`` step scrapes every /metrics surface in this
repo IN-PROCESS (see tests/test_metrics_lint.py) and runs this linter
over the bodies, so a renderer regression — a counter without
``_total``, a family missing ``# HELP``, a histogram without its
``+Inf`` bucket — fails the build instead of silently breaking every
dashboard query downstream.

Rules (the promlint subset that bit this repo before PR 3, plus
format-validity basics):

  N1  metric names match [a-zA-Z_:][a-zA-Z0-9_:]*
  N2  label names match [a-zA-Z_][a-zA-Z0-9_]* and don't start '__'
  T1  every sample's family has a '# TYPE' declared before samples
  H1  every sample's family has a non-empty '# HELP' before samples
  T2  TYPE is one of counter|gauge|histogram|summary|untyped
  T3  no duplicate TYPE/HELP for one family
  C1  counter names end in '_total'
  C2  '_total'-suffixed series are declared counter (no type drift)
  V1  sample values parse as floats (+Inf/-Inf/NaN allowed)
  D1  no duplicate series (same name + label set twice)
  B1  histogram families expose _bucket/_sum/_count
  B2  every _bucket carries 'le' and the '+Inf' bucket exists
  B3  bucket cumulative counts are non-decreasing, +Inf == _count

Exemplar rules (PR 4; the OpenMetrics renderer carries trace-id
exemplars, and this linter gates them in CI):

  X1  no exemplars in the plain text exposition (they are an
      OpenMetrics-only construct; plain scrapers would choke)
  X2  exemplars only on histogram '_bucket' or counter '_total' lines
  X3  exemplar label set (the text inside '{...}') <= 128 chars
  X4  exemplar values parse ('# {labels} value [timestamp]')

Exposition mode: ``lint(text, openmetrics=None)`` auto-detects by the
trailing ``# EOF`` terminator (required in OpenMetrics, absent in the
plain format); pass True/False to pin it.

Usage:
  python tools/promlint.py [--openmetrics] FILE [...]   # '-' = stdin
  from tools.promlint import lint             # -> list of error strings
"""

from __future__ import annotations

import math
import re
import sys
from typing import Dict, List, Tuple

_METRIC_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(raw: str, line_no: int, errors: List[str]
                  ) -> Tuple[Dict[str, str], int, bool]:
    """Parse '{a="b",c="d"}' (escapes included); returns
    (labels, chars consumed, ok)."""
    labels: Dict[str, str] = {}
    i = 1
    while True:
        while i < len(raw) and raw[i] in ", ":
            i += 1
        if i < len(raw) and raw[i] == "}":
            return labels, i + 1, True
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', raw[i:])
        if not m:
            errors.append(f"line {line_no}: malformed label block {raw!r}")
            return labels, i, False
        name = m.group(1)
        if name.startswith("__"):
            errors.append(
                f"line {line_no}: reserved label name {name!r} (N2)")
        i += m.end()
        buf = []
        while i < len(raw):
            c = raw[i]
            if c == "\\":
                nxt = raw[i + 1:i + 2]
                if nxt not in ("\\", '"', "n"):
                    errors.append(
                        f"line {line_no}: bad escape '\\{nxt}' in label "
                        f"value")
                buf.append({"\\": "\\", '"': '"', "n": "\n"}.get(
                    nxt, nxt))
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                buf.append(c)
                i += 1
        else:
            errors.append(f"line {line_no}: unterminated label value")
            return labels, i, False
        if name in labels:
            errors.append(
                f"line {line_no}: duplicate label {name!r} in one series")
        labels[name] = "".join(buf)


def _base_family(name: str, types: Dict[str, str]) -> str:
    """Histogram/summary samples declare TYPE under the base name."""
    for suffix in _HIST_SUFFIXES + ("_created",):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if base in types:
                return base
    return name


_EXEMPLAR_MAX_LABEL_CHARS = 128


def _lint_exemplar(name: str, raw: str, line_no: int,
                   errors: List[str]) -> None:
    """Validate one exemplar tail (the text after ' # ') against the
    X-rules; *name* is the sample's metric name."""
    if not (name.endswith("_bucket") or name.endswith("_total")):
        errors.append(
            f"line {line_no}: exemplar on {name!r} (only _bucket/"
            "_total lines may carry exemplars) (X2)")
    m = re.match(r"^\{(.*)\}\s+(\S+)(?:\s+(\S+))?\s*$", raw)
    if not m:
        errors.append(
            f"line {line_no}: malformed exemplar {raw!r} (X4)")
        return
    labelset, value, ts = m.groups()
    # OpenMetrics: total exemplar label characters (names + values)
    # bounded at 128 so scrape buffers stay predictable
    if len(labelset) > _EXEMPLAR_MAX_LABEL_CHARS:
        errors.append(
            f"line {line_no}: exemplar label set is {len(labelset)} "
            f"chars, over the {_EXEMPLAR_MAX_LABEL_CHARS} bound (X3)")
    for raw_num, what in ((value, "value"), (ts, "timestamp")):
        if raw_num is None:
            continue
        try:
            float(raw_num)
        except ValueError:
            errors.append(
                f"line {line_no}: unparseable exemplar {what} "
                f"{raw_num!r} (X4)")


def lint(text: str, openmetrics=None) -> List[str]:
    """Lint one exposition body; returns a list of error strings
    (empty = clean).  *openmetrics* None auto-detects the mode from
    the trailing ``# EOF`` terminator."""
    if openmetrics is None:
        openmetrics = text.rstrip("\n").endswith("# EOF")
    errors: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    seen_series: set = set()
    # family -> {label-key-minus-le -> [(le, value)]}, plus _sum/_count
    hist_parts: Dict[str, Dict[str, set]] = {}
    hist_buckets: Dict[Tuple[str, Tuple], List[Tuple[float, float]]] = {}
    hist_counts: Dict[Tuple[str, Tuple], float] = {}

    for line_no, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4 or not parts[3].strip():
                errors.append(f"line {line_no}: empty HELP text (H1)")
                continue
            name = parts[2]
            if name in helps:
                errors.append(
                    f"line {line_no}: duplicate HELP for {name} (T3)")
            helps[name] = parts[3]
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append(f"line {line_no}: malformed TYPE line")
                continue
            _, _, name, kind = parts
            if kind not in _TYPES:
                errors.append(
                    f"line {line_no}: unknown TYPE {kind!r} (T2)")
            if name in types:
                errors.append(
                    f"line {line_no}: duplicate TYPE for {name} (T3)")
            types[name] = kind
            if kind == "counter" and not name.endswith("_total"):
                errors.append(
                    f"line {line_no}: counter {name!r} must end in "
                    "'_total' (C1)")
            continue
        if line.startswith("#"):
            continue  # arbitrary comments are legal
        # -- sample line ---------------------------------------------------
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
        if not m:
            errors.append(f"line {line_no}: malformed sample {line!r} (N1)")
            continue
        name = m.group(1)
        rest = line[m.end():]
        labels: Dict[str, str] = {}
        if rest.startswith("{"):
            labels, consumed, ok = _parse_labels(rest, line_no, errors)
            if not ok:
                continue
            rest = rest[consumed:]
        exemplar = None
        if " # " in rest:
            # OpenMetrics exemplar tail: '<value> [ts] # {labels} v [ts]'
            rest, exemplar = rest.split(" # ", 1)
            if not openmetrics:
                errors.append(
                    f"line {line_no}: exemplar in plain-text "
                    "exposition (OpenMetrics only) (X1)")
            _lint_exemplar(name, exemplar.strip(), line_no, errors)
        value_parts = rest.split()
        if not value_parts:
            errors.append(f"line {line_no}: sample has no value (V1)")
            continue
        raw_val = value_parts[0]
        try:
            value = (math.inf if raw_val == "+Inf"
                     else -math.inf if raw_val == "-Inf"
                     else float(raw_val))
        except ValueError:
            errors.append(
                f"line {line_no}: unparseable value {raw_val!r} (V1)")
            continue
        family = _base_family(name, types)
        if family not in types:
            errors.append(
                f"line {line_no}: sample {name} has no # TYPE (T1)")
        if family not in helps:
            errors.append(
                f"line {line_no}: sample {name} has no # HELP (H1)")
        kind = types.get(family)
        if name.endswith("_total") and kind not in (None, "counter"):
            errors.append(
                f"line {line_no}: {name} ends in _total but family "
                f"{family} is {kind} (C2)")
        series_key = (name, tuple(sorted(labels.items())))
        if series_key in seen_series:
            errors.append(
                f"line {line_no}: duplicate series {name}"
                f"{dict(labels)} (D1)")
        seen_series.add(series_key)
        if kind == "histogram":
            hist_parts.setdefault(family, {"_bucket": set(),
                                           "_sum": set(), "_count": set()})
            for suffix in _HIST_SUFFIXES:
                if name == family + suffix:
                    child = tuple(sorted(
                        (k, v) for k, v in labels.items() if k != "le"))
                    hist_parts[family][suffix].add(child)
                    if suffix == "_bucket":
                        if "le" not in labels:
                            errors.append(
                                f"line {line_no}: {name} without "
                                "'le' (B2)")
                        else:
                            le = (math.inf if labels["le"] == "+Inf"
                                  else float(labels["le"]))
                            hist_buckets.setdefault(
                                (family, child), []).append((le, value))
                    elif suffix == "_count":
                        hist_counts[(family, child)] = value
                    break
            else:
                if name == family:
                    errors.append(
                        f"line {line_no}: bare sample {name} on a "
                        "histogram family (B1)")

    for family, parts in hist_parts.items():
        for suffix in _HIST_SUFFIXES:
            if not parts[suffix]:
                errors.append(f"{family}: missing {family}{suffix} (B1)")
        for child in parts["_bucket"]:
            buckets = sorted(hist_buckets.get((family, child), []))
            if not buckets:
                continue
            if buckets[-1][0] != math.inf:
                errors.append(
                    f"{family}{dict(child)}: no '+Inf' bucket (B2)")
                continue
            cum = [v for _, v in buckets]
            if any(b > a for a, b in zip(cum[1:], cum)):
                errors.append(
                    f"{family}{dict(child)}: bucket counts decrease (B3)")
            count = hist_counts.get((family, child))
            if count is not None and count != buckets[-1][1]:
                errors.append(
                    f"{family}{dict(child)}: _count {count} != +Inf "
                    f"bucket {buckets[-1][1]} (B3)")
    return errors


def main(argv: List[str]) -> int:
    openmetrics = None
    if argv and argv[0] == "--openmetrics":
        openmetrics = True
        argv = argv[1:]
    paths = argv or ["-"]
    failed = False
    for path in paths:
        if path == "-":
            text, label = sys.stdin.read(), "<stdin>"
        else:
            with open(path, "r", encoding="utf-8") as f:
                text, label = f.read(), path
        errors = lint(text, openmetrics=openmetrics)
        for e in errors:
            print(f"{label}: {e}")
        failed = failed or bool(errors)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
