"""Round-3 on-chip measurement backlog, runnable as ONE command.

The TPU tunnel has been down for most of rounds 2-3; every queued
measurement (VERDICT r2 #1/#2/#7 + BASELINE.md backlog) is encoded here
so a brief tunnel window captures all of it:

    python tools/measure_r3.py            # everything, ~15-25 min
    python tools/measure_r3.py --phase pool_ab   # one phase

Each phase runs in a SUBPROCESS (weights for the 8B configs must be
freed between phases — jax holds device buffers for the life of the
process) with its own timeout; failures are recorded per phase and the
rest continue.  Results land in MEASURE_r03.json, ready to be copied
into BASELINE.md and to drive the default flips (AlexNet pool impl —
VERDICT r2 asks for xla vs pallas vs fused with the winner as default).

Sync discipline: all timing helpers here sync by VALUE TRANSFER
(float of one element), never block_until_ready — the axon tunnel can
report buffers ready early and inflate numbers ~70x (verify skill
gotchas).

NOT here: zigzag-vs-contiguous ring on ICI (VERDICT r2 #7) — rings
need >= 2 devices and the tunnel exposes ONE chip; recorded as
hardware-blocked in BASELINE.md.
"""
# tpulint: disable-file=R1 -- measurement runner: each phase subprocess already has a timeout and its failure is recorded as the phase result; retrying would double-count warmup effects

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# `python tools/measure_r3.py` puts tools/ (not the repo root) on
# sys.path[0]; without this bootstrap every phase's
# tpu_k8s_device_plugin import fails the moment a chip is attached
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "MEASURE_r03.json")

# (name, timeout seconds); order: cheap headline stuff first so a short
# window still produces the most important numbers
PHASES = [
    ("probe", 180),
    ("alexnet_pool_xla", 900),
    ("alexnet_pool_pallas", 900),
    ("alexnet_pool_fused", 900),
    ("flash_attention", 900),
    ("pool_kernel", 600),
    ("serving_int8_b1", 1200),
    ("serving_int8_b8", 1200),
    ("serving_int8_b8_engine", 1200),
    ("serving_int4_b1", 1200),
    ("serving_int8_b32", 1200),
    ("int4_bytes", 900),
    # round-4 additions: speculative-round economics on the 8B int8
    # target with the Llama-3.2-1B-shaped draft (random weights, so
    # the OUTPUT is round latency + the implied tok/s curve over
    # accept rate + break-even accept — see bench_serving._spec_throughput)
    ("serving_spec_g4_b1", 1500),
    ("serving_spec_g8_b1", 1500),
    # round-5 additions: the HTTP front door under concurrent load on
    # the 8B int8 target (req/s + TTFT/TPOT percentiles vs the direct
    # engine — VERDICT r4 #5 asked for exactly this number), and the
    # per-step cost of grammar-constrained decoding's [S, V] row
    # gather at a real vocab width
    ("serving_http_b8", 1800),
    ("grammar_overhead_b8", 1800),
    # round-6 additions: the iteration scheduler (continuous batching
    # with chunked/interleaved prefill + engine-level chunk-aligned
    # APC) A/B on real hardware — the CPU-proxied http-smoke ratio
    # (0.85 gate) needs an on-chip counterpart before the serving perf
    # story can stop saying "CPU-proxied".  Same invocation either
    # way; only the scheduler's interleave flips.
    ("serving_sched_interleave_b8", 1800),
    ("serving_sched_no_interleave_b8", 1800),
    # round-7 addition: elastic-slice availability — kill one member of
    # a formed (in-process, loopback-gRPC) slice during alexnet
    # training and measure the checkpoint-resume gap: member death ->
    # reshape detected + final checkpoint, restore + first step under
    # the survivor's new identity, and the whole serving gap.  The
    # CPU-proxied chaos episode 7 proves the mechanism; this phase puts
    # an on-chip number on it.
    ("reshape_under_load", 900),
    # round-9 addition: the paged-vs-contiguous KV A/B on real HBM.
    # The CPU equivalence suite proves tokens identical; what only the
    # chip can answer is the gather-formulation's decode cost (the
    # pool view is an XLA gather per layer, not a Pallas kernel yet)
    # and the pool's real HBM headroom under the shared-prefix load.
    # Pair with serving_sched_interleave_b8 (identical invocation,
    # contiguous) and compare tokens_per_sec_http + kv_pool_occupancy
    # / kv_shared_page_ratio.
    ("serving_paged_kv_b8", 1800),
    # the same paged load with int8 pool storage: decode is
    # KV-bandwidth-bound at depth, so the ~53% byte cut should read as
    # tok/s — and the output drift vs the exact pool needs eyeballing
    # before anyone serves it (lossy mode: NOT covered by the
    # equivalence gate)
    ("serving_paged_kv_int8_b8", 1800),
    # round-10 addition: the router-tier A/B on real chips.  CPU
    # router-smoke proves the mechanism (scaling gate, failover); what
    # only hardware can answer is whether 2 single-chip replicas
    # behind the router actually deliver ~2x the single-replica HBM-
    # bound tok/s (they decode independently — the router adds one
    # socket hop), and what the hop costs TTFT at real decode rates.
    # Compare tokens_per_sec_router_{1,n} + affinity_hit_rate.
    ("serving_router_2rep_b8", 2400),
    # round-11 additions: (1) ragged packed prefill + dispatch-ahead
    # overlap on real MXUs — CPU shows ~1.2x on the prefill-heavy
    # shape, but the packed extend's whole thesis is hardware (K
    # chunk-extends share one kernel's MXU pass instead of K dispatch
    # round-trips over the tunnel), so the on-chip A/B vs
    # serving_sched_interleave_b8 is the number that matters; (2)
    # replica cold-start with a persistent compile cache — warm-boot
    # first-completion vs cold is the constant that decides whether
    # router-driven scale-up is real capacity or a warmup storm
    # (CPU proxy: 14s cold -> 4s warm on tiny).
    ("serving_ragged_prefill_b8", 1800),
    ("replica_cold_start", 2400),
    # round-13 addition: disaggregated prefill/decode on real chips.
    # CPU router-smoke proves the mechanism (byte-identical migration,
    # the decode-tail-latency gate); what only hardware can answer is
    # the real economics — checkpoint ship time vs prefill time at 8B
    # KV sizes (the payload is MBs per request on TPU, bytes on tiny),
    # and whether the decode replica's TPOT p99 win survives when
    # prefill is MXU-bound instead of host-bound.  Compare
    # decode_tpot_p99_ms_{homog,disagg} + migrate_mean_ms.
    ("serving_disagg_2rep_b8", 2400),
    # round-16 addition: the fleet reconciler's scale-out delivery
    # time on real chips.  The CPU fleet gate proves the control loop
    # (ramp -> 1..N -> idle); what only hardware can answer is how
    # fast 2 extra warmed replicas become routable capacity — spawn
    # through the persistent compile cache, register, first healthy
    # statz — i.e. whether scale-out is seconds (real elasticity) or
    # a compile storm.  Reports time-to-2 and 2->4 separately: the
    # second pair boots entirely warm.
    ("fleet_scale_out_2to4", 2400),
    # round-17 addition: the fused decode loop on real chips.  The CPU
    # proxy proves byte-identity and shows the harvest-path win
    # (~1.9x per window) but is host-forward-bound, so the end-to-end
    # claim — sampled windows overlapping dispatch + the boundary
    # carry staying on-device instead of a host re-scan per column —
    # only means something where the forward pass runs on MXUs.
    # Compare tokens_per_sec_http_{off,on}, tpot_ms_p99_{off,on},
    # and harvest_ms_per_window_{off,on}.
    ("serving_fused_decode_b8", 2400),
    # round-18 addition: session KV tiering's resume economics on real
    # chips.  The CPU gate proves byte-identity across all three tiers
    # and warm-beats-cold on the proxy; what only hardware can answer
    # is the tier ladder's actual latency shape at 8B KV sizes — a
    # device-parked resume is a splice (~0 prefill), a host hit pays a
    # HBM upload, a disk hit pays codec decode + upload — vs the
    # re-prefill each one replaces (the payload is MBs per session on
    # TPU, KBs on tiny).  Compare ttft_warm_{device,host,disk}_ms vs
    # ttft_cold_ms.
    ("serving_session_resume_b8", 2400),
]


def _sync(x) -> float:
    """Value-transfer sync (see module docstring): slice ONE element
    on device and transfer only that — np.asarray of a whole gradient
    tree would ship GBs through the tunnel inside the timed region."""
    import jax

    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(leaf.ravel()[0])


# -- phases (run inside the subprocess) ---------------------------------------

def phase_probe():
    import jax

    devs = jax.devices()
    return {
        "platform": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", "?"),
        "n_devices": len(devs),
    }


def _alexnet(pool: str):
    import jax

    from tpu_k8s_device_plugin.workloads.bench_main import run_single

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("no accelerator")
    ips, flops = run_single(4096, 10, 3, want_flops=True, rounds=3,
                            pool=pool)
    mfu = None
    from tpu_k8s_device_plugin.tpu.topology import spec_for_device_kind

    spec = spec_for_device_kind(
        getattr(jax.devices()[0], "device_kind", "") or "")
    if flops and spec:
        mfu = (flops / 4096) * ips / float(spec.peak_bf16_flops)
    return {"images_per_sec": round(ips, 1), "pool": pool,
            "mfu": round(mfu, 4) if mfu else None}


def phase_alexnet_pool_xla():
    return _alexnet("xla")


def phase_alexnet_pool_pallas():
    return _alexnet("pallas")


def phase_alexnet_pool_fused():
    return _alexnet("fused")


def phase_flash_attention():
    """flash vs einsum attention, fwd and fwd+bwd, bf16 (the r2 claims
    were 2.8x fwd / 1.98x fwd+bwd pre-outage)."""
    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.flash_attention import (
        flash_causal_attention,
    )
    from tpu_k8s_device_plugin.workloads.ring_attention import (
        full_attention,
    )

    B, T, H, D = 2, 2048, 8, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
               for kk in ks)

    def timed(fn, *args, reps=20):
        f = jax.jit(fn)
        _sync(f(*args))  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        _sync(out)
        return (time.perf_counter() - t0) / reps * 1e3

    res = {}
    res["fwd_flash_ms"] = timed(
        lambda q, k, v: flash_causal_attention(q, k, v), q, k, v)
    res["fwd_einsum_ms"] = timed(
        lambda q, k, v: full_attention(q, k, v, causal=True), q, k, v)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v)
                       .astype(jnp.float32) ** 2)

    def loss_einsum(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True)
                       .astype(jnp.float32) ** 2)

    res["fwdbwd_flash_ms"] = timed(
        jax.grad(loss_flash, argnums=(0, 1, 2)), q, k, v, reps=10)
    res["fwdbwd_einsum_ms"] = timed(
        jax.grad(loss_einsum, argnums=(0, 1, 2)), q, k, v, reps=10)
    res["fwd_speedup"] = round(
        res["fwd_einsum_ms"] / res["fwd_flash_ms"], 2)
    res["fwdbwd_speedup"] = round(
        res["fwdbwd_einsum_ms"] / res["fwdbwd_flash_ms"], 2)
    res["shape"] = [B, T, H, D]
    return res


def phase_pool_kernel():
    """Pallas argmax-index pool vs XLA reduce_window/select_and_scatter
    fwd+bwd at the AlexNet seg1 shape (the BASELINE.md backlog item)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.pool import max_pool

    x = jax.random.normal(
        jax.random.PRNGKey(0), (4096, 56, 56, 64), jnp.bfloat16)

    def timed_grad(fn, reps=10):
        g = jax.jit(jax.grad(
            lambda a: jnp.sum(fn(a).astype(jnp.float32) ** 2)))
        _sync(g(x))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = g(x)
        _sync(out)
        return (time.perf_counter() - t0) / reps * 1e3

    return {
        "xla_fwdbwd_ms": timed_grad(
            lambda a: nn.max_pool(a, (3, 3), (2, 2))),
        "pallas_fwdbwd_ms": timed_grad(lambda a: max_pool(a, 3, 2)),
        "shape": [4096, 56, 56, 64],
    }


def _serving(quantized, batch, steps, max_len, engine=False):
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", quantized, batch, steps,
               prompt_len=128, max_len=max_len, engine=engine)


def phase_serving_int8_b1():
    return _serving(True, 1, 128, 512)


def phase_serving_int8_b8():
    return _serving(True, 8, 128, 512)


def phase_serving_int8_b8_engine():
    return _serving(True, 8, 64, 512, engine=True)


def phase_serving_int8_b32():
    # 10.4 GB weights + ~4.3 GB cache at max_len 256: tight on a 16 GB
    # v5e — an OOM here is a finding, not a harness bug
    return _serving(True, 32, 64, 256)


def phase_serving_int4_b1():
    return _serving("int4", 1, 128, 512)


def phase_serving_spec_g4_b1():
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    # budget: 2*64 + 4*(4+1) = 148 decode rows + 128 prompt <= 512
    return run("llama3-8b", True, 1, 64,
               prompt_len=128, max_len=512, spec=4)


def phase_serving_spec_g8_b1():
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", True, 1, 64,
               prompt_len=128, max_len=512, spec=8)


def phase_serving_http_b8():
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", True, 8, 64, prompt_len=128, max_len=512,
               http_clients=16, http_requests=32)


def phase_serving_sched_interleave_b8():
    """Iteration scheduler ON (PR 6 default): chunked prefill
    interleaved with open decode windows, mid-window admission,
    adaptive windows, full-prompt APC fast path.  Compare
    http_over_engine_ratio and the prefill/decode split against the
    no-interleave phase below."""
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", True, 8, 64, prompt_len=128, max_len=512,
               http_clients=8, http_requests=32, interleave=True)


def phase_serving_sched_no_interleave_b8():
    """Same load with interleaving OFF (admissions run fully between
    windows — the r6 cadence): the delta is the scheduler's on-chip
    win, with bit-identical outputs either way."""
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", True, 8, 64, prompt_len=128, max_len=512,
               http_clients=8, http_requests=32, interleave=False)


def phase_serving_paged_kv_b8():
    """Paged KV pool under the serving_sched_interleave_b8 load (the
    contiguous A side): same clients, same prompts, storage behind a
    block-table gather.  Watch http_over_engine_ratio vs the A side
    plus the pool telemetry the bench scrapes off /metrics."""
    from tpu_k8s_device_plugin.workloads.bench_serving import run

    return run("llama3-8b", True, 8, 64, prompt_len=128, max_len=512,
               http_clients=8, http_requests=32, interleave=True,
               kv_paging=True, tenants=2)


def phase_serving_paged_kv_int8_b8():
    """Paged pool with int8 KV storage (per-row scales): the
    bandwidth rung below bf16 pages.  Lossy — compare outputs by hand
    before believing the tok/s."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        build_model_and_params,
    )
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    cfg, model, params = build_model_and_params(
        "llama3-8b", True, 512)
    eng = ServingEngine(model, params, n_slots=8,
                        kv_paging=True, kv_dtype="int8")
    import time as _t

    prompt = list(range(1, 129))
    slots = [eng.admit(prompt[:64 + i]) for i in range(8)]
    eng.run_scan(8)  # warm/compile
    t0 = _t.perf_counter()
    for _ in range(4):
        eng.run_scan(16)
    dt = _t.perf_counter() - t0
    st = eng.stats()
    return {
        "tokens_per_sec": 8 * 64 / dt,
        "kv_pages_free": st["kv_pages_free"],
        "kv_pages_shared": st["kv_pages_shared"],
        "sample_output_head": eng.output(slots[0])[:8],
    }


def phase_serving_router_2rep_b8():
    """Router-tier A/B on hardware: 2 single-chip 8B-int8 replica
    subprocesses (each pinned to its own TPU via TPU_VISIBLE_DEVICES
    when >= 2 chips are granted) behind the in-process router, vs the
    same load through the router at 1 replica.  run_router reports
    both aggregates + the affinity hit rate; scaling below ~1.8x on
    independent chips means the hop (or the affinity split) is the
    bottleneck, not the engines."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        run_router,
    )

    return run_router("llama3-8b", True, n_replicas=2, clients=8,
                      n_requests=32, slots=8, steps=64,
                      prompt_len=128, max_len=512, kill=False,
                      seed=1)


def phase_serving_ragged_prefill_b8():
    """Ragged packed prefill + dispatch-ahead overlap on the 8B int8
    target under the PREFILL-HEAVY shape (long distinct prompts,
    short outputs): ON vs OFF in one phase (run_prefill_heavy runs
    both arms).  Compare prefill_tokens_per_sec_{on,off} and
    req_per_sec_speedup_x against the CPU proxy (~1.2x), and the ON
    arm's http_over_engine_ratio against serving_sched_interleave_b8
    — on hardware the packed extend shares one kernel's MXU pass
    where CPU only saves host dispatches."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        run_prefill_heavy,
    )

    return run_prefill_heavy("llama3-8b", True, clients=8,
                             n_requests=32, slots=8, steps=8,
                             prompt_len=384, max_len=512)


def phase_serving_disagg_2rep_b8():
    """Disaggregated prefill/decode A/B on the 8B int8 target: mixed
    long-prefill-unary + short-streaming-decode traffic against 2
    mixed replicas vs a prefill+decode pair (phase routing + KV
    migration over /migrate), each replica pinned to its own chip.
    The CPU gate shows decode TPOT p99 improving when long prefills
    leave the decode replica; on hardware the question is whether
    that survives MXU-bound prefill AND what the checkpoint ship
    costs at real KV sizes (migrate_mean_ms vs the prefill it
    saves)."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        run_disagg,
    )

    return run_disagg("llama3-8b", True, clients=8, n_requests=32,
                      slots=8, steps=64, prompt_len=96, max_len=512,
                      seed=1)


def phase_serving_fused_decode_b8():
    """Fused decode loop A/B on the 8B int8 target under the DECODE-
    HEAVY shape (short distinct prompts, long seeded-sampled outputs
    with top-4 logprobs): OFF vs ON in one phase (run_decode_heavy
    runs both arms best-of-2).  The CPU proxy gates the harvest-path
    win (>= 1.10x per window); on hardware the headline is
    tokens_per_sec_http_on/off — sampled windows dispatch ahead and
    the boundary carry never round-trips to host — plus what the
    vectorized harvest does to tpot_ms_p99."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        run_decode_heavy,
    )

    # budget: 64 * (1 + 3) = 256 decode rows + 32 prompt <= 512
    return run_decode_heavy("llama3-8b", True, clients=8,
                            n_requests=32, slots=8, steps=64,
                            prompt_len=32, max_len=512)


def phase_serving_session_resume_b8():
    """Session-tier resume ladder on the 8B int8 target: TTFT of a
    returning conversation's turn 2 when its KV comes back from each
    tier (device splice / host upload / disk codec-load) vs the cold
    re-prefill of a chain-shaped prompt — plus the replica's own
    tier accounting.  One conversation per tier; the tier is staged
    by letting the park age past the seeded-jitter idle deadlines
    (0.5s -> host, +2s -> disk) and PROVEN from /statz before the
    timed turn, so each number is labelled by where the bytes
    actually came from."""
    import http.client
    import json as _json
    import shutil
    import tempfile
    import time

    import numpy as np

    from tpu_k8s_device_plugin.workloads import loadclient
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        build_model_and_params,
    )
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    cfg, model, params = build_model_and_params("llama3-8b", 512, True)
    tmp = tempfile.mkdtemp(prefix="measure-kvs-")
    eng = ServingEngine(model, params, n_slots=8,
                        eos_id=getattr(cfg, "eos_id", None),
                        kv_paging=True)
    srv = EngineServer(eng, max_new_tokens=64, window=4,
                       session_tier=True, session_dir=tmp,
                       session_idle_s=0.5, session_host_idle_s=2.0,
                       session_seed=0)
    srv.start(host="127.0.0.1", port=0)
    rng = np.random.default_rng(0)
    prompt_len, turn2_len, gen = 96, 8, 16

    def unary(body):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=600)
        try:
            conn.request("POST", "/generate", _json.dumps(body),
                         {"Content-Type": "application/json"})
            return _json.loads(conn.getresponse().read())
        finally:
            conn.close()

    def statz():
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=60)
        try:
            conn.request("GET", "/statz")
            return _json.loads(conn.getresponse().read())
        finally:
            conn.close()

    def ttft_ms(tokens, sid):
        body = {"tokens": tokens, "max_new_tokens": gen,
                "ignore_eos": True}
        if sid is not None:
            body["session_id"] = sid
        out = loadclient.stream_request(
            "127.0.0.1", srv.port, body, timeout_s=600.0)
        assert out.outcome == loadclient.OUTCOME_OK, out
        return round(out.ttft_s * 1000.0, 2)

    def wait_tiers(pred, deadline_s=60.0):
        end = time.time() + deadline_s
        while time.time() < end:
            tiers = statz()["kv_tiers"]
            if pred(tiers):
                return
            time.sleep(0.1)
        raise RuntimeError(f"tier staging stalled: {tiers}")

    try:
        # cold control FIRST, UNsessioned (nothing parks, nothing to
        # match): a chain-shaped random prompt pays the full prefill
        # a tier miss would
        chain_len = prompt_len + gen + turn2_len
        cold = [ttft_ms(list(map(int, rng.integers(
            1, model.vocab, chain_len))), None) for _ in range(3)]

        res = {}
        # one conversation per tier, staged and MEASURED in an order
        # whose statz predicates attribute the tier unambiguously:
        # conv-disk is the only session when disk goes nonzero;
        # conv-host is in host once NO session remains device-parked
        # (its own spill deadline is 2s further out); conv-device is
        # asked back well inside the 0.5s idle window
        stage_pred = {
            "disk": lambda t: t["disk"] >= 1,
            "host": lambda t: t["device"] == 0 and t["host"] >= 1,
            "device": None,
        }
        for tier in ("disk", "host", "device"):
            p1 = list(map(int, rng.integers(1, model.vocab,
                                            prompt_len)))
            out1 = unary({"tokens": p1, "max_new_tokens": gen,
                          "ignore_eos": True, "stream": False,
                          "session_id": f"conv-{tier}"})["tokens"]
            if stage_pred[tier] is not None:
                wait_tiers(stage_pred[tier])
            p2 = list(map(int, rng.integers(1, model.vocab,
                                            turn2_len)))
            res[f"ttft_warm_{tier}_ms"] = ttft_ms(
                p1 + out1 + p2, f"conv-{tier}")
        tiers = statz()["kv_tiers"]
        for tier in ("device", "host", "disk"):
            assert tiers["hits"][tier] >= 1, tiers
        cold_ms = round(float(np.median(cold)), 2)
        res.update(
            ttft_cold_ms=cold_ms,
            ttft_cold_all_ms=cold,
            speedup_device_x=round(
                cold_ms / res["ttft_warm_device_ms"], 2),
            speedup_host_x=round(
                cold_ms / res["ttft_warm_host_ms"], 2),
            speedup_disk_x=round(
                cold_ms / res["ttft_warm_disk_ms"], 2),
            tier_hits=tiers["hits"], promotions=tiers["promotions"],
            spill_bytes_disk=tiers["disk_bytes"],
        )
        return res
    finally:
        srv.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def phase_replica_cold_start():
    """Replica cold-start economics on real chips: the server CLI
    booted twice against one --compile-cache-dir (cold fill, warm
    load), spawn -> first-completion timed each way.  On TPU the
    compile set is minutes, not seconds — warm_speedup_x here is the
    constant that decides whether the router tier's scale-up story
    (ROADMAP fleet-controller item) delivers capacity in seconds."""
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        run_cold_start,
    )

    return run_cold_start("llama3-8b", True, slots=8, steps=16,
                          prompt_len=64, max_len=512)


def phase_grammar_overhead_b8():
    """Per-step overhead of grammar-constrained decoding on the 8B
    int8 engine: the [S, V] table-row gather + derived mask vs the
    plain scan, at the real 128k vocab width.  The token byte table is
    synthetic (no tokenizer download in this image) — overhead depends
    only on the [N, V] table shape, not on which bytes map where."""
    import time

    import numpy as np

    from tpu_k8s_device_plugin.workloads.bench_serving import (
        build_model_and_params,
    )
    from tpu_k8s_device_plugin.workloads.grammar import (
        json_value_regex,
        regex_to_dfa,
        token_dfa,
    )
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    cfg, model, params = build_model_and_params("llama3-8b", 384, True)
    rng = np.random.default_rng(0)
    alpha = b'abcdefghijklmnopqrstuvwxyz0123456789"{}[]:,. -'
    tb = [b""] + [
        rng.choice(list(alpha), int(rng.integers(1, 9)))
        .astype(np.uint8).tobytes()
        for _ in range(model.vocab - 1)
    ]
    eos = 0  # bench posture: random weights, ids-only; any id works
    t0 = time.time()
    tdfa = token_dfa(regex_to_dfa(json_value_regex(2)), tb,
                     eos_id=eos)
    compile_s = round(time.time() - t0, 1)
    n_states = int(tdfa.table.shape[0])

    prompts = rng.integers(1, model.vocab, (8, 128))

    def timed_scan(grammar_on):
        eng = ServingEngine(model, params, n_slots=8,
                            eos_id=eos, grammar=tdfa)
        for b in range(8):
            eng.admit(prompts[b].tolist(), grammar=grammar_on,
                      ignore_eos=True)
        eng.run_scan(16)  # warm/compile
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            eng.run_scan(16)
            dt = (time.perf_counter() - t0) / 16
            best = dt if best is None or dt < best else best
        return best

    t_plain = timed_scan(False)
    t_gram = timed_scan(True)
    return {
        "grammar_states": n_states,
        "table_mb": round(n_states * model.vocab * 4 / 2**20, 1),
        "token_dfa_compile_s": compile_s,
        "step_ms_plain": round(t_plain * 1e3, 3),
        "step_ms_grammar": round(t_gram * 1e3, 3),
        "overhead_pct": round(100 * (t_gram / t_plain - 1), 2),
    }


def phase_int4_bytes():
    """Is the int4 nibble-unpack fused into the matmul, or does XLA
    materialize the bf16 kernel?  (ADVICE r2: the int4 bandwidth win is
    a fusion property.)  Compare XLA-reported bytes accessed for one
    decode-shaped matmul, int8 vs int4."""
    import jax
    import jax.numpy as jnp

    from tpu_k8s_device_plugin.workloads.inference import (
        Quant4Dense,
        QuantDense,
    )

    D, F, B = 4096, 14336, 8
    x = jnp.zeros((B, 1, D), jnp.bfloat16)
    out = {}
    for name, mod in (("int8", QuantDense(features=F, use_bias=False,
                                          dtype=jnp.bfloat16)),
                      ("int4", Quant4Dense(features=F, use_bias=False,
                                           dtype=jnp.bfloat16))):
        params = mod.init(jax.random.PRNGKey(0), x)

        def f(p, x):
            return mod.apply(p, x)

        compiled = jax.jit(f).lower(params, x).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        out[f"{name}_bytes_accessed"] = ca.get("bytes accessed")
    if out.get("int8_bytes_accessed") and out.get("int4_bytes_accessed"):
        out["int4_over_int8"] = round(
            out["int4_bytes_accessed"] / out["int8_bytes_accessed"], 3)
    return out


def phase_reshape_under_load():
    """Checkpoint-resume gap of an elastic-slice reshape under training
    load (ROADMAP: the availability story needs an on-chip number the
    day the tunnel returns).

    A 2-member in-process slice forms (real coordinator + clients over
    loopback gRPC, the production code path); alexnet trains with the
    elastic loop; mid-run one member is killed.  Measured, on whatever
    chip is attached: kill -> reshaped generation adopted (detect_s),
    the final checkpoint save (checkpoint_s), restore + first step back
    under the survivor identity (resume_s), and the whole serving gap
    (gap_s = last step before the kill -> first step after resume)."""
    import shutil
    import tempfile
    import threading

    from tpu_k8s_device_plugin.slice import SliceClient, SliceCoordinator
    from tpu_k8s_device_plugin.workloads import bench_main, checkpoint

    tmp = tempfile.mkdtemp(prefix="reshape-r3-")
    coordinator = SliceCoordinator(
        expected_workers=2, bind_address="127.0.0.1:0", jax_port=8476,
        state_path=os.path.join(tmp, "coordinator.json"),
        heartbeat_timeout_s=0.5, reshape_grace_s=1.0,
    ).start()
    addr = f"127.0.0.1:{coordinator.port}"
    clients = [
        SliceClient(rendezvous_address=addr, hostname=f"host-{i}",
                    coords=(i,), chip_count=1,
                    state_path=os.path.join(tmp, f"host-{i}.json"))
        for i in range(2)
    ]
    try:
        threads = [threading.Thread(target=c.join, args=(30.0,))
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=40.0)
        survivor, victim = clients
        gen1 = survivor.membership.generation
        signal = checkpoint.ReshapeSignal(
            os.path.join(tmp, "host-0.json"), generation=gen1)
        # both members heartbeat in the background; the "kill" is the
        # victim's heartbeats stopping
        survivor.start(period_s=0.2)
        victim.start(period_s=0.2)

        ckpt_dir = os.path.join(tmp, "ckpts")
        # warm start: a few steps + checkpoint so the resume is honest
        rc = bench_main.run_elastic(
            batch=64, steps=5, checkpoint_dir=ckpt_dir,
            checkpoint_every=0, slice_state="", signal=signal)
        assert rc == 0, f"warmup train failed rc={rc}"
        t_kill = time.time()
        victim.stop()           # the member dies under load
        rc = bench_main.run_elastic(
            batch=64, steps=10_000, checkpoint_dir=ckpt_dir,
            checkpoint_every=0, slice_state="", signal=signal)
        t_ckpt_done = time.time()
        assert rc == checkpoint.RESHAPE_EXIT_CODE, (
            f"elastic loop should exit {checkpoint.RESHAPE_EXIT_CODE} "
            f"on reshape, got {rc}")
        detect_s = None
        m = signal.check()
        if m is not None:
            detect_s = round(t_ckpt_done - t_kill, 3)
        # the restart: restore + run one step under the new identity
        t0 = time.time()
        rc = bench_main.run_elastic(
            batch=64, steps=checkpoint.latest_step(ckpt_dir) + 1,
            checkpoint_dir=ckpt_dir, checkpoint_every=0,
            slice_state="",
            signal=checkpoint.ReshapeSignal(
                os.path.join(tmp, "host-0.json"),
                generation=m.generation if m else gen1))
        resume_s = round(time.time() - t0, 3)
        assert rc == 0, f"resume failed rc={rc}"
        return {
            "detect_and_checkpoint_s": detect_s,
            "resume_s": resume_s,
            "gap_s": round(time.time() - t_kill, 3),
            "reshaped_generation": m.generation if m else None,
        }
    finally:
        for c in clients:
            c.stop()
        coordinator.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def phase_fleet_scale_out_2to4():
    """The reconciler's capacity-delivery constant on real chips: a
    floor of 4 llama3-8b-int8 replicas brought up by the fleet
    controller through one persistent compile cache, timing router-
    confirmed healthy counts at 2 and at 4.  scale_2to4_s is the
    number the ROADMAP's elasticity story rests on — the second pair
    boots entirely warm, so it is the marginal cost of a scale-out
    decision, not of a cold fleet."""
    import shutil
    import tempfile

    from tpu_k8s_device_plugin.workloads import fleet, loadclient
    from tpu_k8s_device_plugin.workloads.router import RouterServer

    tmp = tempfile.mkdtemp(prefix="fleet-r3-")
    rt = RouterServer(statz_interval_s=0.5, replica_ttl_s=10.0,
                      seed=0)
    rt.start(host="127.0.0.1", port=0)
    cap = os.path.join(tmp, "capacity.json")
    with open(cap, "w") as f:
        json.dump({"slices": [{"slice_id": "r3", "generation": 1,
                               "workers": 4}]}, f)
    controller = fleet.FleetController(
        f"http://127.0.0.1:{rt.port}",
        config=fleet.PlannerConfig(min_replicas=4, max_replicas=4,
                                   start_grace_s=3600.0),
        server=fleet.ServerSpec(
            config="llama3-8b", slots=8, max_len=512,
            max_new_tokens=64,
            compile_cache_dir=os.path.join(tmp, "compile-cache")),
        capacity_spec=cap, interval_s=1.0, seed=0)
    import threading as _th

    loop = _th.Thread(target=controller.run, daemon=True)
    t0 = time.time()
    t2 = t4 = None
    try:
        loop.start()
        deadline = t0 + 2100
        while time.time() < deadline and t4 is None:
            try:
                body = loadclient.fetch_json(rt.port, "/replicas",
                                             timeout_s=10.0)
            except (OSError, ValueError):
                time.sleep(1.0)
                continue
            healthy = sum(1 for r in body.get("replicas", [])
                          if isinstance(r, dict) and r.get("healthy"))
            if t2 is None and healthy >= 2:
                t2 = time.time() - t0
            if healthy >= 4:
                t4 = time.time() - t0
            time.sleep(1.0)
        if t4 is None:
            raise RuntimeError(
                f"never reached 4 healthy replicas (t2={t2})")
        return {
            "time_to_2_healthy_s": round(t2, 1),
            "time_to_4_healthy_s": round(t4, 1),
            "scale_2to4_s": round(t4 - t2, 1),
        }
    finally:
        controller.shutdown()
        rt.stop()
        shutil.rmtree(tmp, ignore_errors=True)


# -- orchestration ------------------------------------------------------------

def run_phase_subprocess(name: str, timeout: int) -> dict:
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase", name],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    dt = round(time.time() - t0, 1)
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-2000:], "seconds": dt}
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            d = json.loads(line)
            d["seconds"] = dt
            return d
    return {"error": f"no JSON in output: {proc.stdout[-500:]}",
            "seconds": dt}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--phase", default=None,
                   help="run one phase in-process and print its JSON")
    args = p.parse_args()
    if args.phase:
        result = globals()[f"phase_{args.phase}"]()
        print(json.dumps(result))
        return 0

    results = {}
    for name, timeout in PHASES:
        print(f"== {name} (limit {timeout}s)", flush=True)
        results[name] = run_phase_subprocess(name, timeout)
        print(json.dumps({name: results[name]}), flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
        if name == "probe" and "error" in results[name]:
            # a dead tunnel is an environment outage, not a failed
            # measurement: record a structured skip that names the
            # queued phases and exit 0, so the round's artifact reads
            # "run me when the tunnel returns" instead of "broken"
            # (rounds 2-5 recorded the same outage as failures)
            results[name] = {
                "skipped": "tunnel_down",
                "detail": results[name].get("error"),
                "seconds": results[name].get("seconds"),
            }
            results["queued_phases"] = [n for n, _ in PHASES[1:]]
            with open(OUT, "w") as f:
                json.dump(results, f, indent=1)
            print(json.dumps(
                {"skipped": "tunnel_down",
                 "queued_phases": results["queued_phases"]}),
                flush=True)
            return 0
    print(f"wrote {OUT}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
