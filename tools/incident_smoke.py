#!/usr/bin/env python3
"""incident-smoke: end-to-end acceptance check for incident bundles.

The alert-smoke scenario (a REAL serving subprocess, an SLO class
whose 1ms deadline no request can meet, shrunken burn-rate windows)
extended through the PR-19 flight data recorder: when the page fires,
the server must write exactly ONE schema-complete incident bundle to
``--incident-dir``, with no human in the loop —

  1. the page alert reaches ``firing`` and exactly one
     ``incident-<alert>-*`` directory materializes (atomically: no
     ``.incident-tmp-*`` litter, meta.json present),
  2. the bundle is self-contained: alert transition history, full
     flight-recorder journal, TSDB snapshot with the burn-rate series,
     a continuous-profile slice, and stitched spans for at least one
     SLO-missed request,
  3. the profile proves the recorder was ALREADY running when the
     incident started: at least one profile sample is timestamped
     before the firing transition,
  4. ``tools/obs_query.py --incident DIR`` renders the bundle offline
     and exits 0.

CI runs this in the ``metrics-lint`` job; also runnable by hand:

    JAX_PLATFORMS=cpu python tools/incident_smoke.py
"""
# tpulint: disable-file=R1 -- smoke DRIVER: single-shot requests against a subprocess it just started; a failure IS the test failing, retries would only blur which layer lost the bundle

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_k8s_device_plugin import obs                # noqa: E402

ALERT_INTERVAL_S = 0.5
WINDOW_SCALE = 0.0005  # 5m/1h/6h -> 0.15s / 1.8s / 10.8s
PAGE_ALERT = "slo_burn_page_bad"

_SERVER_PROG = """
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.server import EngineServer
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                     d_ff=128, max_len=64, dtype=jnp.float32)
tokens = jnp.zeros((1, 8), jnp.int32)
pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
eng = ServingEngine(model, params, n_slots=2)
# class 'bad' can never meet its 1ms deadline: every request misses,
# burn = 1/(1-0.99) = 100x the moment traffic lands on it
policies = {{
    "bad": obs.SLOPolicy("bad", deadline_ms=1.0),
    "good": obs.SLOPolicy("good", deadline_ms=60000.0),
}}
srv = EngineServer(eng, max_new_tokens=4, window=2,
                   slo_policies=policies, slo_window_s=3.0,
                   alert_interval_s={interval!r},
                   alert_window_scale={scale!r},
                   incident_dir={incident_dir!r})
srv.start(host="127.0.0.1", port=0)
print(json.dumps({{"port": srv.port}}), flush=True)
import threading
threading.Event().wait()
"""


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read().decode())


def _alert(status, name):
    for a in status["alerts"]:
        if a["name"] == name:
            return a
    raise AssertionError(f"{name} missing from /alerts: "
                         f"{[a['name'] for a in status['alerts']]}")


def _wait_for_state(port, name, want, timeout_s):
    deadline = time.time() + timeout_s
    state = None
    while time.time() < deadline:
        state = _alert(_get_json(port, "/alerts"), name)["state"]
        if state == want:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"{name} never reached {want!r} (last state {state!r})")


def _wait_for_bundle(incident_dir, timeout_s):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        bundles = [p for p in os.listdir(incident_dir)
                   if p.startswith(obs.BUNDLE_PREFIX)]
        if bundles:
            return bundles
        time.sleep(0.1)
    raise AssertionError(
        f"no incident bundle materialized in {incident_dir} "
        f"({os.listdir(incident_dir)})")


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    incident_dir = tempfile.mkdtemp(prefix="tpu-incident-smoke-")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _SERVER_PROG.format(repo=REPO, interval=ALERT_INTERVAL_S,
                             scale=WINDOW_SCALE,
                             incident_dir=incident_dir)],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        port = json.loads(proc.stdout.readline())["port"]
        print(f"server up on :{port}, incident dir {incident_dir}")

        # the continuous profiler is live BEFORE any trouble: its
        # /debug/pprof surface already serves the schema
        prof = _get_json(port, "/debug/pprof?format=json")
        assert prof["schema"] == "tpu-profile/v1", prof["schema"]

        # synthetic goodput collapse: every 'bad' request misses its
        # 1ms deadline, so the class burns at 100x from request one
        for _ in range(4):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"tokens": [1, 2, 3],
                                 "slo_class": "bad"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                assert resp.status == 200
                resp.read()
        print("1. collapse traffic sent (4 guaranteed SLO misses)")

        _wait_for_state(port, PAGE_ALERT, "firing", timeout_s=20.0)
        print("2. page alert firing")

        # exactly ONE bundle, atomically placed (no tmp litter, and
        # read_bundle validates meta.json + schema below)
        bundles = _wait_for_bundle(incident_dir, timeout_s=15.0)
        assert len(bundles) == 1, bundles
        assert not [p for p in os.listdir(incident_dir)
                    if p.startswith(".incident-tmp-")]
        bundle_dir = os.path.join(incident_dir, bundles[0])
        bundle = obs.read_bundle(bundle_dir)
        meta = bundle["meta"]
        assert meta["alert"] == PAGE_ALERT
        assert meta["severity"] == "page"
        assert meta["errors"] == {}, meta["errors"]
        for rel in ("alert.json", "journal.jsonl", "tsdb.json",
                    "profile.folded", "profile.json", "statz.json",
                    "traces.json"):
            assert rel in meta["files"], (rel, meta["files"])
        print(f"3. one schema-complete bundle: {bundles[0]}")

        # the bundle carries the firing transition in its own history
        firing = [t for t in bundle["alert.json"]["transitions"]
                  if t["attrs"].get("alert") == PAGE_ALERT
                  and t["attrs"].get("state_to") == "firing"]
        assert firing, bundle["alert.json"]["transitions"]
        fired_at = firing[0]["attrs"]["at"]

        # TSDB snapshot retained the burn series that paged
        burn = [s for s in bundle["tsdb.json"]["series"]
                if "burn_rate" in s["name"] and s["points"]]
        assert burn, [s["name"] for s in bundle["tsdb.json"]["series"]]

        # the flight data recorder was already running: at least one
        # profile sample predates the firing transition
        prof = bundle["profile.json"]
        assert prof["samples"] > 0, prof
        early = [sec for sec, n in prof["timeline"]
                 if n > 0 and sec < fired_at]
        assert early, (prof["timeline"], fired_at)
        print(f"4. profile has samples from {fired_at - early[0]:.1f}s "
              f"before the firing transition")

        # stitched spans for at least one SLO-missed request
        misses = bundle["traces.json"]["misses"]
        assert misses and misses[0]["events"], misses
        tree = obs.stitch(misses[0]["events"])
        assert tree, misses[0]
        print(f"5. {len(misses)} SLO-missed trace(s) with spans")

        # offline render: the on-call's first command must just work
        rc = subprocess.call(
            [sys.executable, os.path.join(REPO, "tools/obs_query.py"),
             "--incident", bundle_dir])
        assert rc == 0, f"obs_query --incident exited {rc}"
        print("6. obs_query --incident rendered the bundle, exit 0")
        print("incident-smoke: PASS")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
