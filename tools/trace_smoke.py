#!/usr/bin/env python3
"""trace-smoke: the end-to-end acceptance check for request tracing.

One trace-id issued at the serving front door must be observable in
every layer the tracing PR wired:

  1. the response headers (X-Trace-Id / traceparent echo),
  2. the span breadcrumbs in /debug/traces (admission, queue wait,
     run_scan windows, stream writes, terminal request span),
  3. an OpenMetrics exemplar on the serve histograms,
  4. the plain-text /metrics staying exemplar-free AND promlint-clean,
  5. the flight-record dump written when the server gets SIGTERM.

The server runs as a REAL subprocess (random weights, tiny decoder, CPU)
so the SIGTERM path is the production path, not a test double.  CI runs
this in the ``trace-smoke`` job on every push; it is also runnable by
hand:

    JAX_PLATFORMS=cpu python tools/trace_smoke.py
"""
# tpulint: disable-file=R1 -- smoke DRIVER: single-shot requests against a subprocess it just started; a failure IS the test failing, retries would only blur which layer dropped the trace

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.promlint import lint                      # noqa: E402
from tpu_k8s_device_plugin import obs                # noqa: E402

_SERVER_PROG = """
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.server import EngineServer
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                     d_ff=128, max_len=64, dtype=jnp.float32)
tokens = jnp.zeros((1, 8), jnp.int32)
pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
eng = ServingEngine(model, params, n_slots=2)
srv = EngineServer(eng, max_new_tokens=4, window=2,
                   flight_record_dir={dump_dir!r})
# the CLI installs the SIGTERM dump chain; do the same here so the
# smoke exercises the production shutdown path
srv.recorder.install_dump_handlers({dump_dir!r})
srv.start(host="127.0.0.1", port=0)
print(json.dumps({{"port": srv.port}}), flush=True)
import threading
threading.Event().wait()
"""


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return dict(resp.headers), resp.read().decode()


def main() -> int:
    dump_dir = tempfile.mkdtemp(prefix="trace-smoke-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _SERVER_PROG.format(repo=REPO, dump_dir=dump_dir)],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        port = json.loads(proc.stdout.readline())["port"]
        print(f"server up on :{port}")

        root = obs.new_trace()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"tokens": [1, 2, 3]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": root.to_traceparent()})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["X-Trace-Id"] == root.trace_id, \
                "response header does not echo the trace id"
            resp.read()
        print(f"1. header echo OK ({root.trace_id})")

        _, body = _get(port, f"/debug/traces?trace_id={root.trace_id}")
        names = {e["name"] for e in json.loads(body)["events"]}
        for want in ("tpu_serve_queue_wait", "tpu_serve_admit",
                     "tpu_serve_ttft", "tpu_serve_window",
                     "tpu_serve_stream_write", "tpu_serve_request"):
            assert want in names, f"missing {want} in {sorted(names)}"
        print(f"2. /debug/traces spans OK ({sorted(names)})")

        headers, om = _get(
            port, "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert "openmetrics" in headers["Content-Type"]
        assert f'trace_id="{root.trace_id}"' in om, \
            "trace id absent from OpenMetrics exemplars"
        errs = lint(om)
        assert not errs, f"OpenMetrics body fails promlint: {errs[:5]}"
        print("3. OpenMetrics exemplar OK")

        headers, plain = _get(port, "/metrics")
        assert headers["Content-Type"].startswith("text/plain")
        assert "# {" not in plain, \
            "exemplar leaked into the plain-text exposition"
        errs = lint(plain)
        assert not errs, f"plain /metrics fails promlint: {errs[:5]}"
        print("4. plain exposition exemplar-free + promlint-clean OK")

        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
        dumps = [p for p in os.listdir(dump_dir)
                 if p.startswith("flight-") and p.endswith(".jsonl")]
        assert dumps, f"no flight-record dump in {dump_dir}"
        with open(os.path.join(dump_dir, dumps[0]),
                  encoding="utf-8") as f:
            lines = [json.loads(line) for line in f]
        assert lines[0].get("flight_record") is True
        assert any(rec.get("trace_id") == root.trace_id
                   for rec in lines[1:]), \
            "trace id absent from the SIGTERM flight-record dump"
        print(f"5. SIGTERM dump OK ({dumps[0]}, "
              f"{lines[0]['events']} events)")
        print("trace-smoke: PASS")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
