"""obs_query: query live /debug endpoints AND flight-recorder dumps.

The fleet's post-mortem companion: one trace-id (or time range) in,
one merged view out — whether the processes that produced the events
are still alive (live ``/debug/traces`` / ``/debug/events`` endpoints
on routers, replicas, and device plugins) or already dead (their
``--flight-record-dir`` JSON-lines dumps).  Events from every source
are merged, deduplicated, and — in trace-id mode — re-linked into the
same span tree the router's stitched ``/debug/traces`` serves, via the
``parent_id`` each hop's traceparent stamped.

Examples::

    # a live fleet: router + 2 replicas
    python tools/obs_query.py --trace-id 4bf9... \
        --endpoint http://router:8100 \
        --endpoint http://rep0:8000 --endpoint http://rep1:8000

    # the same trace after a replica died: its dump has its half
    python tools/obs_query.py --trace-id 4bf9... \
        --endpoint http://router:8100 \
        --dump /var/lib/tpu-flight-records/

    # what happened in the last minute before the crash?
    python tools/obs_query.py --dump flight-43-1754300612.jsonl \
        --since 1754300550 --until 1754300612

    # replay -> post-mortem in one command: the slowest SLO-missed
    # requests of a workloads.replay report, span trees and all
    python tools/obs_query.py --replay-report replay-report.json --top 3

    # live dashboard: sparklines from the server's in-process TSDB
    # (GET /debug/query) + the firing-alert table (GET /alerts)
    python tools/obs_query.py --watch --endpoint http://rep0:8000

    # render an alert-triggered incident bundle offline: alert
    # timeline, burn sparkline, top profile stacks by phase, and the
    # stitched span trees of the slowest SLO-missed requests
    python tools/obs_query.py --incident \
        /var/lib/tpu-incidents/incident-slo_burn_page_chat-1754300612000

Dependency-free (stdlib + the stdlib-only ``obs`` package), like
every tool in this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from typing import Callable, Dict, List, Optional
from urllib.parse import quote
from urllib.request import urlopen

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/obs_query.py` from anywhere
    sys.path.insert(0, _REPO_ROOT)

from tpu_k8s_device_plugin import obs  # noqa: E402


def _fetch_json(url: str, timeout_s: float) -> Optional[dict]:
    try:
        with urlopen(url, timeout=timeout_s) as resp:
            out = json.loads(resp.read())
        return out if isinstance(out, dict) else None
    except (OSError, ValueError) as e:
        print(f"obs_query: {url}: {e}", file=sys.stderr)
        return None


def fetch_endpoint(base: str, trace_id: Optional[str],
                   since: float, timeout_s: float
                   ) -> List[Dict[str, object]]:
    """One live endpoint's events: /debug/traces?trace_id= in trace
    mode, /debug/events?since= in time-range mode."""
    base = base.rstrip("/")
    if trace_id:
        url = (f"{base}/debug/traces"
               f"?trace_id={quote(trace_id, safe='')}")
    else:
        url = f"{base}/debug/events?since={since}"
    out = _fetch_json(url, timeout_s)
    if out is None:
        return []
    events = out.get("events")
    if not isinstance(events, list):
        # the router's stitched shape: flatten its tree back to events
        tree = out.get("tree")
        if isinstance(tree, list):
            return [dict(e, _origin=base)
                    for e in obs.flatten(tree)]
        return []
    return [dict(e, _origin=base) for e in events
            if isinstance(e, dict)]


def read_dump(path: str) -> List[Dict[str, object]]:
    """One flight-recorder dump file (JSON-lines; header line skipped),
    or every flight-*.jsonl in a directory."""
    if os.path.isdir(path):
        out: List[Dict[str, object]] = []
        for name in sorted(os.listdir(path)):
            if name.startswith("flight-") and name.endswith(".jsonl"):
                out.extend(read_dump(os.path.join(path, name)))
        return out
    events: List[Dict[str, object]] = []
    origin = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # truncated tail of a crash-time dump
                if not isinstance(ev, dict) or "name" not in ev:
                    continue  # the header line, or foreign JSON
                ev["_origin"] = origin
                events.append(ev)
    except OSError as e:
        print(f"obs_query: {path}: {e}", file=sys.stderr)
    return events


def _f(v: object) -> float:
    return float(v) if isinstance(v, (int, float)) else 0.0


def collect(trace_id: Optional[str], endpoints: List[str],
            dumps: List[str], since: float, until: float,
            name: Optional[str], timeout_s: float
            ) -> List[Dict[str, object]]:
    """Gather + filter + dedup events from every source, oldest
    first.  Dedup key: (name, trace span, wall time) — a live
    endpoint and that process's dump report the same event once."""
    events: List[Dict[str, object]] = []
    for ep in endpoints:
        events.extend(fetch_endpoint(ep, trace_id, since, timeout_s))
    for d in dumps:
        events.extend(read_dump(d))
    seen = set()
    out: List[Dict[str, object]] = []
    for ev in events:
        if trace_id and ev.get("trace_id") != trace_id:
            continue
        t = _f(ev.get("t_wall"))
        if since and t <= since:
            continue
        if until and t > until:
            continue
        if name and ev.get("name") != name:
            continue
        key = (ev.get("name"), ev.get("trace_id"), ev.get("span_id"),
               round(t, 6))
        if key in seen:
            continue
        seen.add(key)
        out.append(ev)
    out.sort(key=lambda e: _f(e.get("t_wall")))
    return out


def render_replay_report(path: str, top: int,
                         as_json: bool) -> int:
    """The slowest *top* SLO-missed requests of a
    ``tpu-replay-report/v1`` file (workloads.replay --report),
    attribution plus — where the report embedded the raw spans — the
    stitched tree, re-stitched right here so the post-mortem needs no
    live endpoint.  Exit 0 when the report has no misses at all."""
    with open(path, "r", encoding="utf-8") as f:
        report = json.loads(f.read())
    if not isinstance(report, dict) \
            or report.get("schema") != "tpu-replay-report/v1":
        print(f"obs_query: {path} is not a tpu-replay-report/v1 "
              f"file (schema={report.get('schema')!r})"
              if isinstance(report, dict)
              else f"obs_query: {path}: not a JSON object",
              file=sys.stderr)
        return 2
    missed = report.get("slo_missed")
    rows = [r for r in missed if isinstance(r, dict)] \
        if isinstance(missed, list) else []
    rows = rows[:max(0, top)]
    if as_json:
        out = []
        for row in rows:
            events = row.get("events")
            tree = obs.stitch([e for e in events
                               if isinstance(e, dict)]) \
                if isinstance(events, list) else []
            out.append(dict(row, tree=tree))
        print(json.dumps({"report": path, "slo_missed": out},
                         indent=2))
        return 0
    classes = report.get("classes")
    if isinstance(classes, dict):
        for name in sorted(classes):
            info = classes[name]
            if isinstance(info, dict):
                print(f"class {name}: attainment "
                      f"{info.get('attainment')} "
                      f"({info.get('met')}/{info.get('eligible')} "
                      f"eligible, {info.get('total')} total)")
    if not rows:
        print("no SLO-missed requests in the report")
        return 0
    for row in rows:
        print(f"\n-- {row.get('rid')} class={row.get('class')} "
              f"outcome={row.get('outcome')} "
              f"total={row.get('total_ms')}ms "
              f"ttft={row.get('ttft_ms')}ms "
              f"replica={row.get('replica')} "
              f"trace={str(row.get('trace_id'))[:16]}")
        attribution = row.get("attribution")
        if isinstance(attribution, dict):
            print("   where it went: " + "  ".join(
                f"{k.removesuffix('_ms')}={v:.1f}ms"
                for k, v in attribution.items()
                if isinstance(v, (int, float)) and v > 0))
        events = row.get("events")
        if isinstance(events, list) and events:
            tree = obs.stitch([e for e in events
                               if isinstance(e, dict)])
            print(obs.render_tree(tree))
        else:
            print("   (no spans embedded for this request — raise "
                  "--top-missed on the replay run)")
    return 0


# -- watch mode (PR 18): live TSDB sparklines + firing alerts ---------------

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"

# severity colors for TTY watch frames (PR 19): page red, ticket
# yellow — everything else stays uncolored; NO_COLOR opts out
_SEV_COLOR = {"page": "\x1b[31m", "ticket": "\x1b[33m"}
_RESET = "\x1b[0m"


def _colorize(text: str, severity: str, color: bool) -> str:
    code = _SEV_COLOR.get(severity) if color else None
    return f"{code}{text}{_RESET}" if code else text

# the serving surface's vital signs; families a surface lacks just
# render "(no data)", so the same default set works against the
# router and the exporter too
WATCH_EXPRS = (
    "tpu_slo_goodput_ratio",
    "tpu_slo_error_budget_burn_rate",
    "tpu_serving_pending_requests",
    "tpu_serving_kv_pages_free",
)


def sparkline(values: List[float], width: int = 48) -> str:
    """Unicode block sparkline of the last *width* values, annotated
    with the min/last/max.  NaNs are dropped; empty -> '(no data)'."""
    vals = [float(v) for v in values
            if isinstance(v, (int, float)) and v == v]
    if not vals:
        return "(no data)"
    vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        bar = SPARK_BLOCKS[0] * len(vals)
    else:
        top = len(SPARK_BLOCKS) - 1
        bar = "".join(
            SPARK_BLOCKS[int(round((v - lo) / span * top))]
            for v in vals)
    return f"{bar}  min={lo:g} last={vals[-1]:g} max={hi:g}"


def _series_label(labels: Dict[str, object]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v
                          in sorted(labels.items())) + "}"


def render_watch_frame(queries: List[Dict[str, object]],
                       alerts: Optional[dict],
                       width: Optional[int] = None,
                       color: bool = False) -> str:
    """One watch frame as text: per-expr sparklines over the
    /debug/query payloads, then the alert table (every rule NOT
    inactive, severity first).  Pure — the watch test feeds it
    captured payloads and pins the rendering; *width* sizes the
    sparklines (None keeps the historical 48) and *color* wraps
    page/ticket alert rows in ANSI red/yellow (both default off so
    the pinned rendering is unchanged)."""
    spark_w = 48 if width is None else max(8, width - 54)
    lines: List[str] = []
    for q in queries:
        expr = str(q.get("expr", ""))
        series = q.get("series")
        series = series if isinstance(series, list) else []
        lines.append(expr)
        if not series:
            lines.append("  (no data)")
        for s in series:
            if not isinstance(s, dict):
                continue
            pts = s.get("points")
            pts = pts if isinstance(pts, list) else []
            values = [p[1] for p in pts
                      if isinstance(p, (list, tuple)) and len(p) == 2
                      and isinstance(p[1], (int, float))]
            labels = s.get("labels")
            labels = labels if isinstance(labels, dict) else {}
            lines.append(f"  {_series_label(labels) or '(all)':24s} "
                         f"{sparkline(values, width=spark_w)}")
    rows = []
    if isinstance(alerts, dict):
        for a in alerts.get("alerts") or []:
            if isinstance(a, dict) and a.get("state") != "inactive":
                rows.append(a)
    lines.append("")
    if rows:
        sev_rank = {"page": 0, "ticket": 1, "info": 2}
        rows.sort(key=lambda a: (
            sev_rank.get(str(a.get("severity")), 9),
            str(a.get("name"))))
        lines.append(f"{'ALERT':32s} {'SEVERITY':8s} {'STATE':8s} "
                     f"{'VALUE':>10s}  SINCE")
        now = time.time()
        for a in rows:
            since = a.get("since")
            age = f"{now - float(since):.0f}s ago" \
                if isinstance(since, (int, float)) and since else "-"
            value = a.get("value")
            vtxt = f"{value:.4g}" \
                if isinstance(value, (int, float)) else "-"
            row = (f"{str(a.get('name', '')):32s} "
                   f"{str(a.get('severity', '')):8s} "
                   f"{str(a.get('state', '')):8s} {vtxt:>10s}  {age}")
            lines.append(_colorize(row, str(a.get("severity", "")),
                                   color))
    else:
        lines.append("no pending or firing alerts")
    return "\n".join(lines)


def watch(endpoint: str, exprs: List[str], range_s: float,
          interval_s: float, iterations: int,
          timeout_s: float = 3.0,
          out: Callable[[str], None] = print) -> int:
    """Poll one endpoint's /debug/query + /alerts and render frames
    until *iterations* run out (0 = forever).  Exit 0 once at least
    one frame rendered real data (a series or an alert payload).

    On a TTY the sparklines stretch to the terminal width and
    page/ticket alert rows go red/yellow; piped output keeps the
    fixed-width, colorless rendering (and NO_COLOR disables color
    even on a TTY, per the convention)."""
    base = endpoint.rstrip("/")
    tty = sys.stdout.isatty()
    width = shutil.get_terminal_size().columns if tty else None
    color = tty and not os.environ.get("NO_COLOR")
    saw_data = False
    i = 0
    while True:
        queries: List[Dict[str, object]] = []
        for expr in exprs:
            url = (f"{base}/debug/query?expr={quote(expr, safe='')}"
                   f"&range={range_s:g}s")
            payload = _fetch_json(url, timeout_s)
            if payload is None:
                payload = {"expr": expr, "series": []}
            if payload.get("series"):
                saw_data = True
            queries.append(payload)
        alerts = _fetch_json(f"{base}/alerts", timeout_s)
        if alerts is not None:
            saw_data = True
        stamp = time.strftime("%H:%M:%S")
        out(f"-- {base} @ {stamp} "
            f"(range {range_s:g}s, every {interval_s:g}s)")
        out(render_watch_frame(queries, alerts, width=width,
                               color=color))
        i += 1
        if iterations and i >= iterations:
            return 0 if saw_data else 1
        time.sleep(interval_s)


# -- incident bundles (PR 19): offline bundle rendering ---------------------


def _incident_timeline(bundle: Dict[str, object]) -> List[str]:
    """The alert's transition history, oldest first: when it went
    pending, when it started firing, what the value was each time."""
    lines: List[str] = []
    alert_doc = bundle.get("alert.json")
    trans = alert_doc.get("transitions") \
        if isinstance(alert_doc, dict) else None
    rows = [t for t in trans if isinstance(t, dict)] \
        if isinstance(trans, list) else []
    rows.sort(key=lambda t: _f((t.get("attrs") or {}).get("at"))
              if isinstance(t.get("attrs"), dict) else 0.0)
    for t in rows:
        a = t.get("attrs")
        a = a if isinstance(a, dict) else {}
        value = a.get("value")
        vtxt = f" value={value:.4g}" \
            if isinstance(value, (int, float)) else ""
        lines.append(f"  {_f(a.get('at')):.3f}  "
                     f"{a.get('alert')}: {a.get('state_from')} -> "
                     f"{a.get('state_to')}{vtxt}")
    return lines or ["  (no transitions recorded)"]


def _incident_burn(bundle: Dict[str, object]) -> List[str]:
    """Sparkline every burn-rate series the TSDB snapshot retained —
    the shape of the burn curve is the first thing the page runbook
    asks for."""
    doc = bundle.get("tsdb.json")
    series = doc.get("series") if isinstance(doc, dict) else None
    lines: List[str] = []
    for s in series if isinstance(series, list) else []:
        if not isinstance(s, dict):
            continue
        name = str(s.get("name", ""))
        if "burn_rate" not in name:
            continue
        pts = s.get("points")
        pts = pts if isinstance(pts, list) else []
        values = [p[1] for p in pts
                  if isinstance(p, (list, tuple)) and len(p) == 2
                  and isinstance(p[1], (int, float))]
        labels = s.get("labels")
        labels = labels if isinstance(labels, dict) else {}
        lines.append(f"  {name}{_series_label(labels)}")
        lines.append(f"    {sparkline(values)}")
    return lines or ["  (no burn-rate series in the snapshot)"]


def _incident_stacks(bundle: Dict[str, object],
                     per_phase: int = 5) -> List[str]:
    """Top continuous-profile stacks grouped by scheduler phase:
    where the process actually spent its time in the minutes before
    the page."""
    doc = bundle.get("profile.json")
    stacks = doc.get("stacks") if isinstance(doc, dict) else None
    by_phase: Dict[str, List[dict]] = {}
    for s in stacks if isinstance(stacks, list) else []:
        if isinstance(s, dict):
            by_phase.setdefault(str(s.get("phase", "")), []).append(s)
    lines: List[str] = []
    for phase in sorted(by_phase):
        rows = sorted(by_phase[phase],
                      key=lambda s: -_f(s.get("count")))
        total = sum(_f(s.get("count")) for s in rows)
        lines.append(f"  phase {phase} ({total:g} samples):")
        for s in rows[:per_phase]:
            stack = str(s.get("stack", ""))
            leaf = stack.rsplit(";", 2)[-2:]
            lines.append(f"    {_f(s.get('count')):6g}  "
                         f"{';'.join(leaf)}")
    if isinstance(doc, dict):
        lines.append(f"  ({doc.get('samples')} samples over "
                     f"{doc.get('seconds')}s at {doc.get('hz')}hz, "
                     f"overhead {_f(doc.get('overhead_ratio')):.2%})")
    return lines or ["  (no profile in the bundle)"]


def _incident_misses(bundle: Dict[str, object]) -> List[str]:
    """Stitched span trees of the slowest SLO-missed requests the
    bundle captured — per-miss latency attribution without a live
    endpoint."""
    doc = bundle.get("traces.json")
    misses = doc.get("misses") if isinstance(doc, dict) else None
    lines: List[str] = []
    for m in misses if isinstance(misses, list) else []:
        if not isinstance(m, dict):
            continue
        lines.append(f"  -- {m.get('rid')} "
                     f"class={m.get('slo_class')} "
                     f"outcome={m.get('outcome')} "
                     f"total={_f(m.get('duration_s')) * 1000:.1f}ms "
                     f"trace={str(m.get('trace_id'))[:16]}")
        events = m.get("events")
        if isinstance(events, list) and events:
            tree = obs.stitch([e for e in events
                               if isinstance(e, dict)])
            lines.extend("  " + ln for ln in
                         obs.render_tree(tree).splitlines())
    return lines or ["  (no SLO-missed traces in the bundle)"]


def render_incident(dir_path: str, as_json: bool) -> int:
    """Render one incident bundle directory offline: meta header,
    alert timeline, burn sparkline, top profile stacks by phase, then
    the stitched trees of the slowest SLO-missed requests.  Exit 0 on
    a schema-valid bundle, 2 otherwise."""
    try:
        bundle = obs.read_bundle(dir_path)
    except (OSError, ValueError) as e:
        print(f"obs_query: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(bundle, indent=2, default=str))
        return 0
    meta = bundle["meta"]
    print(f"incident {os.path.basename(dir_path.rstrip(os.sep))}")
    print(f"  alert={meta.get('alert')} "
          f"severity={meta.get('severity')} "
          f"state={meta.get('state_to')} at={_f(meta.get('at')):.3f}")
    value = meta.get("value")
    if isinstance(value, (int, float)):
        print(f"  value at transition: {value:.6g}")
    print(f"  files: {', '.join(meta.get('files', []))}")
    errors = meta.get("errors")
    if isinstance(errors, dict) and errors:
        for rel in sorted(errors):
            print(f"  COLLECT ERROR {rel}: {errors[rel]}")
    desc = meta.get("description")
    if desc:
        print(f"  {desc}")
    print("\nalert timeline:")
    print("\n".join(_incident_timeline(bundle)))
    print("\nerror-budget burn:")
    print("\n".join(_incident_burn(bundle)))
    print("\ntop profile stacks by phase:")
    print("\n".join(_incident_stacks(bundle)))
    print("\nslowest SLO-missed requests:")
    print("\n".join(_incident_misses(bundle)))
    replicas = sorted({rel.split("/", 2)[1] for rel in bundle
                       if rel.startswith("replicas/")
                       and rel.count("/") >= 2})
    if replicas:
        print("\nfleet fragments:")
        for rid in replicas:
            statz = bundle.get(f"replicas/{rid}/statz.json")
            mark = " UNREACHABLE" \
                if isinstance(statz, dict) and statz.get("unreachable") \
                else ""
            print(f"  {rid}{mark}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="obs-query",
        description="query live /debug endpoints and flight-recorder "
                    "dumps by trace-id or time range")
    p.add_argument("--trace-id", default=None,
                   help="render this trace's stitched span tree")
    p.add_argument("--endpoint", action="append", default=None,
                   metavar="URL",
                   help="live /debug base URL, e.g. "
                        "http://router:8100 (repeatable)")
    p.add_argument("--dump", action="append", default=None,
                   metavar="PATH",
                   help="flight-record dump file, or a directory of "
                        "flight-*.jsonl dumps (repeatable)")
    p.add_argument("--since", type=float, default=0.0,
                   help="only events after this unix timestamp")
    p.add_argument("--until", type=float, default=0.0,
                   help="only events at or before this unix timestamp")
    p.add_argument("--name", default=None,
                   help="only events with this name")
    p.add_argument("--severity", default=None,
                   choices=["page", "ticket", "info"],
                   help="only events carrying this severity tag "
                        "(alert transitions)")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="per-endpoint fetch timeout (seconds)")
    p.add_argument("--json", action="store_true",
                   help="emit JSON instead of the text rendering")
    p.add_argument("--incident", default=None, metavar="DIR",
                   help="render an incident bundle directory "
                        "(written by a firing page alert under "
                        "--incident-dir) instead of querying "
                        "endpoints")
    p.add_argument("--replay-report", default=None, metavar="FILE",
                   help="render the slowest SLO-missed requests of a "
                        "workloads.replay report (tpu-replay-report/"
                        "v1) instead of querying endpoints")
    p.add_argument("--top", type=int, default=5,
                   help="how many SLO-missed requests to render in "
                        "--replay-report mode")
    p.add_argument("--watch", action="store_true",
                   help="live mode: poll ONE --endpoint's "
                        "/debug/query + /alerts and render sparklines "
                        "plus the firing-alert table")
    p.add_argument("--watch-expr", action="append", default=None,
                   metavar="EXPR",
                   help="expression to sparkline in --watch mode "
                        "(repeatable; default: goodput, burn rate, "
                        "queue depth, free KV pages)")
    p.add_argument("--range", type=float, default=300.0,
                   help="--watch query window in seconds")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch refresh interval in seconds")
    p.add_argument("--iterations", type=int, default=0,
                   help="--watch frames to render before exiting "
                        "(0 = forever; tests use 1)")
    args = p.parse_args(argv)
    if args.incident:
        return render_incident(args.incident, args.json)
    if args.replay_report:
        return render_replay_report(args.replay_report, args.top,
                                    args.json)
    if args.watch:
        if len(args.endpoint or []) != 1:
            p.error("--watch needs exactly one --endpoint")
        return watch(args.endpoint[0],
                     list(args.watch_expr or WATCH_EXPRS),
                     args.range, args.interval, args.iterations,
                     timeout_s=args.timeout)
    if not args.endpoint and not args.dump:
        p.error("need at least one --endpoint or --dump")
    events = collect(args.trace_id, args.endpoint or [],
                     args.dump or [], args.since, args.until,
                     args.name, args.timeout)
    if args.severity:
        events = [e for e in events
                  if obs.event_severity(e) == args.severity]
    if args.trace_id:
        # source label for the tree: a tagged source (the router's
        # stitcher stamps replica ids) wins; else where we found it
        for ev in events:
            if not ev.get("source"):
                ev["source"] = ev.get("_origin", "")
        tree = obs.stitch(events)
        if args.json:
            print(json.dumps({"trace_id": args.trace_id,
                              "events": len(events), "tree": tree},
                             indent=2))
        else:
            print(f"trace {args.trace_id}: {len(events)} event(s)")
            if events:
                print(obs.render_tree(tree))
        return 0 if events else 1
    if args.json:
        print(json.dumps({"events": events}, indent=2))
        return 0 if events else 1
    t0 = _f(events[0].get("t_wall")) if events else 0.0
    for ev in events:
        dt = _f(ev.get("t_wall")) - t0
        tid = ev.get("trace_id") or "-"
        src = ev.get("source") or ev.get("_origin") or ""
        attrs = ev.get("attrs")
        extra = ""
        if isinstance(attrs, dict) and attrs:
            extra = " " + " ".join(
                f"{k}={v}" for k, v in sorted(attrs.items()))
        # severity tag up front so alert transitions stand out (and
        # grep/sort on the second column just works)
        sev = obs.event_severity(ev)
        sev_tag = f" <{sev}>" if sev else ""
        print(f"+{dt:10.4f}s{sev_tag} [{src}] {ev.get('name')} "
              f"trace={str(tid)[:16]}{extra}")
    return 0 if events else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
