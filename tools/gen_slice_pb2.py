"""Emit slice_pb2.py: build the FileDescriptorProto programmatically
(the image has protobuf but no protoc) and embed its serialized bytes in
the same AddSerializedFile style the other *_pb2.py modules use.

Invoked by proto/gen.sh when protoc is absent.  MUST be kept in sync with
proto/slice.proto by hand; tests/test_proto.py pins the service shape so
a drift fails CI.
"""
import os

from google.protobuf import descriptor_pb2 as dp

F = dp.FieldDescriptorProto
fdp = dp.FileDescriptorProto()
fdp.name = "slice.proto"
fdp.package = "tpuslice"
fdp.syntax = "proto3"


def msg(name, fields):
    m = fdp.message_type.add()
    m.name = name
    for fname, num, ftype, label, type_name in fields:
        f = m.field.add()
        f.name = fname
        f.number = num
        f.type = ftype
        f.label = label
        if type_name:
            f.type_name = type_name
    return m


OPT, REP = F.LABEL_OPTIONAL, F.LABEL_REPEATED
S, I32, I64, B, M = (F.TYPE_STRING, F.TYPE_INT32, F.TYPE_INT64,
                     F.TYPE_BOOL, F.TYPE_MESSAGE)

msg("JoinRequest", [
    ("hostname", 1, S, OPT, None),
    ("coords", 2, I32, REP, None),
    ("chip_count", 3, I32, OPT, None),
    ("session", 4, S, OPT, None),
])
msg("Membership", [
    ("slice_id", 1, S, OPT, None),
    ("generation", 2, I64, OPT, None),
    ("num_workers", 3, I32, OPT, None),
    ("hostnames", 4, S, REP, None),
    ("coordinator_address", 5, S, OPT, None),
    ("reshaped_from", 6, S, REP, None),
    ("degraded", 7, B, OPT, None),
])
msg("JoinResponse", [
    ("formed", 1, B, OPT, None),
    ("rank", 2, I32, OPT, None),
    ("joined", 3, I32, OPT, None),
    ("expected", 4, I32, OPT, None),
    ("membership", 5, M, OPT, ".tpuslice.Membership"),
])
msg("HeartbeatRequest", [
    ("hostname", 1, S, OPT, None),
    ("healthy", 2, B, OPT, None),
    ("reason", 3, S, OPT, None),
    ("generation", 4, I64, OPT, None),
])
msg("HeartbeatResponse", [
    ("slice_healthy", 1, B, OPT, None),
    ("unhealthy_hostnames", 2, S, REP, None),
    ("membership", 3, M, OPT, ".tpuslice.Membership"),
])

svc = fdp.service.add()
svc.name = "SliceRendezvous"
for mname, inp, outp in [
    ("Join", ".tpuslice.JoinRequest", ".tpuslice.JoinResponse"),
    ("Heartbeat", ".tpuslice.HeartbeatRequest", ".tpuslice.HeartbeatResponse"),
]:
    meth = svc.method.add()
    meth.name = mname
    meth.input_type = inp
    meth.output_type = outp

serialized = fdp.SerializeToString()

TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated protocol buffer code.  DO NOT EDIT!
# source: slice.proto
#
# Built by proto/gen.sh's no-protoc fallback (tools/gen_slice_pb2.py):
# the build image ships protobuf but no protoc, so the serialized
# FileDescriptorProto below is constructed with descriptor_pb2 instead of
# compiled -- byte layout differs from protoc output, wire format does not.
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({serialized!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'slice_pb2', globals())
'''

_out = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tpu_k8s_device_plugin", "proto", "slice_pb2.py",
)
with open(_out, "w") as f:
    f.write(TEMPLATE.format(serialized=serialized))
print("wrote", _out + ",", len(serialized), "descriptor bytes")
