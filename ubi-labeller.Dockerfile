# UBI-based node-labeller image (≈ ubi-labeller.Dockerfile in the
# reference): standalone Red Hat build for OpenShift environments that
# pull the labeller independently of the device plugin.  The labeller
# needs sysfs + the tpu-env file only, so the final stage carries no
# extra privileges or device libraries.
FROM registry.access.redhat.com/ubi9/python-311 AS builder
ARG GIT_DESCRIBE=unknown
USER 0
RUN dnf install -y gcc-c++ make && dnf clean all
WORKDIR /src
COPY pyproject.toml README.md LICENSE ./
COPY tpu_k8s_device_plugin/ tpu_k8s_device_plugin/
COPY native/ native/
RUN make -C native/tpuprobe \
    && pip install --no-cache-dir --prefix=/install . \
    && cp tpu_k8s_device_plugin/hostinfo/libtpuprobe.so \
         /install/lib/python3.11/site-packages/tpu_k8s_device_plugin/hostinfo/ \
    && echo "${GIT_DESCRIBE}" > /install/git-describe

FROM registry.access.redhat.com/ubi9/python-311
LABEL \
    org.opencontainers.image.title="k8s-tpu-node-labeller" \
    org.opencontainers.image.description="Kubernetes node labeller for Google Cloud TPUs" \
    org.opencontainers.image.licenses="Apache-2.0"
RUN mkdir -p /licenses
COPY LICENSE /licenses/LICENSE
COPY --from=builder /install /usr/local
ENV PYTHONPATH=/usr/local/lib/python3.11/site-packages
ENTRYPOINT ["/usr/local/bin/k8s-tpu-node-labeller"]
