"""The serving benchmark CLI paths stay runnable (the pods call these)."""

import pytest

from tpu_k8s_device_plugin.workloads.bench_serving import (
    CONFIGS,
    build_model_and_params,
    run,
)


def test_uniform_path_runs():
    stats = run("tiny", quantized=True, batch=2, steps=4,
                prompt_len=8, max_len=64)
    assert stats["tokens_per_sec"] > 0
    assert stats["batch"] == 2.0


def test_engine_path_runs():
    stats = run("tiny", quantized=False, batch=2, steps=4,
                prompt_len=8, max_len=128, engine=True)
    assert stats["tokens_per_sec"] > 0
    assert stats["engine"] is True


def test_configs_cover_llama_presets():
    assert {"llama3-8b", "llama2-7b", "tiny"} <= set(CONFIGS)


def test_engine_headroom_validated_up_front():
    # library callers get the same fail-fast guard as the CLI: engine
    # mode burns (warmup + rounds) scan windows of cache headroom
    with pytest.raises(ValueError, match="max_len"):
        run("tiny", quantized=False, batch=1, steps=16,
            prompt_len=8, max_len=64, engine=True)


def test_spec_path_runs():
    stats = run("tiny", quantized=False, batch=2, steps=4,
                prompt_len=8, max_len=128, spec=2)
    assert stats["spec_round_ms"] > 0
    assert stats["plain_step_ms"] > 0
    assert 0.0 <= stats["breakeven_accept"] <= 1.0
    assert stats["draft"] == "tiny-draft"
    assert stats["tokens_per_sec_at_accept_1.0"] > 0


def test_int4_path_runs():
    stats = run("tiny", quantized="int4", batch=1, steps=4,
                prompt_len=8, max_len=64)
    assert stats["tokens_per_sec"] > 0
    assert stats["quantized"] == "int4"


@pytest.mark.parametrize("quantized", [False, True])
def test_build_with_mesh_materializes_sharded(quantized):
    # the --tp path: leaves must come out ALREADY on their TP
    # placement (build-then-reshard would peak the full tree on one
    # device — the thing tensor parallelism exists to avoid)
    from tpu_k8s_device_plugin.workloads.transformer import make_lm_mesh

    mesh = make_lm_mesh(seq=1, model=2, expert=1)
    _, _, params = build_model_and_params(
        "tiny", 64, quantized, mesh=mesh)
    leaf_name = "kernel_int8" if quantized else "kernel"
    leaf = params["block_0"]["mlp_gate"][leaf_name]
    assert leaf.sharding.mesh.shape["model"] == 2
    assert tuple(leaf.sharding.spec) == (None, "model")


def test_http_load_path_runs():
    """The front-door load bench (VERDICT r4 #5): concurrent streaming
    clients, mixed priorities, a cancel, and the direct-engine
    comparison — all on the tiny config."""
    stats = run("tiny", quantized=False, batch=2, steps=4,
                prompt_len=8, max_len=64, http_clients=3,
                http_requests=6, cancel_every=3)
    assert stats["http"] is True
    assert stats["requests_cancelled"] == 2.0
    assert stats["requests_completed"] == 4.0
    assert stats["req_per_sec"] > 0
    assert stats["tokens_per_sec_http"] > 0
    assert stats["tokens_per_sec_engine"] > 0
    for k in ("ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
              "tpot_ms_p99"):
        assert stats[k] == stats[k] and stats[k] >= 0  # not NaN


def test_http_burst_phase_reports_shed_mix():
    """The backpressure phase: a post-load burst against the bench's
    deliberately small pool must come back fully accounted — every
    request a 200 or a shed 429, with the server-side counters
    agreeing that shedding (not thread growth) absorbed the spike."""
    stats = run("tiny", quantized=False, batch=2, steps=4,
                prompt_len=8, max_len=64, http_clients=2,
                http_requests=4, burst=16)
    assert stats["burst_requests"] == 16.0
    assert (stats["burst_ok"] + stats["burst_429"]
            + stats["burst_errors"]) == 16.0
    assert stats["burst_ok"] >= 1.0    # engine kept serving admits
    assert stats["burst_errors"] == 0.0
    if stats["burst_429"]:
        # shed responses are accounted server-side too
        assert (stats["connections_rejected"]
                + stats["requests_throttled"]) >= stats["burst_429"]
    assert stats["http_workers"] == 4.0  # clients + 2, fixed


def test_load_checkpoint_params_serves_real_weights(tmp_path):
    """The serving CLI's --checkpoint path: restore a train-layout
    orbax checkpoint, (optionally) quantize on load, and decode — the
    bf16 restore must reproduce the SOURCE weights' tokens exactly,
    and the quantized rungs must build the quantized layouts."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_k8s_device_plugin.workloads import llama
    from tpu_k8s_device_plugin.workloads.bench_serving import (
        load_checkpoint_params,
    )
    from tpu_k8s_device_plugin.workloads.checkpoint import (
        save_checkpoint,
    )
    from tpu_k8s_device_plugin.workloads.inference import (
        greedy_generate,
    )

    cfg = llama.TINY_LLAMA
    train = llama.train_model(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = train.init(jax.random.PRNGKey(7), tokens, pos)["params"]
    save_checkpoint(str(tmp_path), 3, {"params": params})

    _, model, loaded = load_checkpoint_params(
        "tiny", 64, False, str(tmp_path))
    want, _ = greedy_generate(
        model, params, jnp.asarray([[5, 17, 3]], jnp.int32), 6)
    got, _ = greedy_generate(
        model, loaded, jnp.asarray([[5, 17, 3]], jnp.int32), 6)
    assert np.asarray(got).tolist() == np.asarray(want).tolist()

    for q in (True, "int4"):
        _, qmodel, qparams = load_checkpoint_params(
            "tiny", 64, q, str(tmp_path), step=3)
        out, _ = greedy_generate(
            qmodel, qparams, jnp.asarray([[5, 17, 3]], jnp.int32), 4)
        assert np.asarray(out).shape == (1, 4)
