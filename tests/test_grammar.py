"""Grammar-constrained decoding: a token-level DFA rides the decode
scan's carry, so every emitted sequence FULL-MATCHES the grammar (or
is one of its prefixes at the budget), step and run_scan agree
token-for-token, and unconstrained neighbors are untouched."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.grammar import (
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
EOS = 0
PATTERN = "(ab|cd)+e"


def _init(model, seed=0):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    # byte-per-token vocab (ids < 128 are their ascii bytes; 0 = eos)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return model, _init(model), dfa


def _decode(ids):
    return bytes(t for t in ids if t).decode("latin-1")


def test_regex_compiler_grid():
    d = regex_to_dfa(r"\d+(\.\d+)?")

    def m(s):
        cur = 0
        for b in s.encode():
            cur = int(d.table[cur, b])
            if cur < 0:
                return False
        return bool(d.accepting[cur])

    assert m("42") and m("3.14") and m("0")
    assert not m("") and not m(".5") and not m("3.") and not m("a")


def test_regex_compiler_fuzz_vs_re():
    """Differential fuzz: random patterns from the served subset,
    random byte strings — the DFA's full-match verdict must agree
    with Python's re on every sample (the compiler backs a public,
    per-request API; a mis-compile silently mis-constrains)."""
    import random

    rnd = random.Random(1234)
    alphabet = "abc01"

    def gen(depth):
        kind = rnd.choice(
            ["lit", "lit", "class", "alt", "cat", "star", "plus",
             "opt"] if depth > 0 else ["lit", "class"])
        if kind == "lit":
            return rnd.choice(alphabet)
        if kind == "class":
            chars = "".join(sorted(set(
                rnd.choice(alphabet)
                for _ in range(rnd.randint(1, 3)))))
            neg = "^" if rnd.random() < 0.2 else ""
            return f"[{neg}{chars}]"
        if kind == "alt":
            return ("(" + gen(depth - 1) + "|" + gen(depth - 1)
                    + ")")
        if kind == "cat":
            return gen(depth - 1) + gen(depth - 1)
        return "(" + gen(depth - 1) + ")" + {
            "star": "*", "plus": "+", "opt": "?"}[kind]

    for _ in range(60):
        pat = gen(3)
        try:
            d = regex_to_dfa(pat)
        except ValueError:
            continue  # e.g. an empty alternation arm; re may differ
        gold = re.compile(f"(?s:{pat})")
        for _ in range(40):
            s = "".join(rnd.choice(alphabet)
                        for _ in range(rnd.randint(0, 6)))
            cur = 0
            for b in s.encode():
                cur = int(d.table[cur, b])
                if cur < 0:
                    break
            got = cur >= 0 and bool(d.accepting[cur])
            want = gold.fullmatch(s) is not None
            assert got == want, (pat, s)


def test_constrained_output_matches_grammar(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    s = eng.admit([70, 71, 72], grammar=True)
    eng.run(20)
    out = eng.output(s)
    text = _decode(out)
    if eng.finish_reason(s) == "eos":
        assert re.fullmatch(PATTERN, text), text
    else:  # budget/cache cut: still a valid PREFIX of the grammar
        d = regex_to_dfa(PATTERN)
        cur = 0
        for b in text.encode():
            cur = int(d.table[cur, b])
            assert cur >= 0, text


def test_scan_and_step_agree_constrained(setup):
    model, params, dfa = setup

    def mk():
        e = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                          max_new_tokens=10, grammar=dfa)
        return e, e.admit([70, 71], grammar=True), e.admit([5, 9, 3])

    a, sa, ua = mk()
    for _ in range(12):
        a.step()
    b, sb, ub = mk()
    b.run_scan(4)  # grammar state must survive the window boundary
    b.run_scan(6)
    assert a.output(sa) == b.output(sb)
    assert a.output(ua) == b.output(ub)
    # the unconstrained neighbor decodes exactly its solo stream
    want, _ = greedy_generate(
        model, params, jnp.asarray([[5, 9, 3]], jnp.int32), 10)
    assert a.output(ua) == np.asarray(want)[0].tolist()


def test_sampled_constrained_still_matches_grammar(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    s = eng.admit([70, 71, 72], grammar=True, temperature=1.0,
                  seed=7)
    eng.run(20)
    text = _decode(eng.output(s))
    d = regex_to_dfa(PATTERN)
    cur = 0
    for b in text.encode():
        cur = int(d.table[cur, b])
        assert cur >= 0, text


def test_grammar_requires_engine_grammar(setup):
    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="grammar"):
        eng.admit([1, 2], grammar=True)


def test_grammar_excludes_spec(setup):
    model, params, dfa = setup
    draft = make_decoder(vocab=CFG["vocab"], d_model=32, n_heads=2,
                         n_layers=1, d_ff=64, max_len=64,
                         dtype=jnp.float32)
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa, draft=(draft, _init(draft, 1)))
    eng.admit([70, 71], grammar=True)
    assert not eng.spec_ready()
    with pytest.raises(ValueError, match="grammar"):
        eng.spec_round()


def test_per_request_grammars(setup):
    """The registry: two grammars on one engine, each request decoding
    under its OWN DFA (admit(grammar=gid)), bit-independent of the
    neighbor's constraint."""
    model, params, dfa = setup
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    digits = token_dfa(regex_to_dfa(r"\d+"), tb, eos_id=EOS)
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                        max_new_tokens=12, grammar=dfa)
    gid2 = eng.register_grammar(digits)
    assert gid2 == 1 and eng.n_grammars == 2
    s0 = eng.admit([70, 71, 72], grammar=True)     # (ab|cd)+e
    s1 = eng.admit([70, 71, 72], grammar=gid2)     # \d+
    eng.run(14)
    t0, t1 = _decode(eng.output(s0)), _decode(eng.output(s1))
    for text, pat in ((t0, PATTERN), (t1, r"\d+")):
        d = regex_to_dfa(pat)
        cur = 0
        for b in text.encode():
            cur = int(d.table[cur, b])
            assert cur >= 0, (text, pat)
    assert t1 and all(c.isdigit() for c in t1)


def test_register_after_construction(setup):
    """An engine built without a ctor grammar can still register one
    later (the server's lazy per-request compile path)."""
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        max_new_tokens=8)
    with pytest.raises(ValueError, match="grammar"):
        eng.admit([70], grammar=True)
    gid = eng.register_grammar(dfa)
    s = eng.admit([70, 71, 72], grammar=gid)
    eng.run(10)
    d = regex_to_dfa(PATTERN)
    cur = 0
    for b in _decode(eng.output(s)).encode():
        cur = int(d.table[cur, b])
        assert cur >= 0


def test_unknown_grammar_id_rejected(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    with pytest.raises(ValueError, match="unknown grammar id"):
        eng.admit([70], grammar=3)


def test_vocab_mismatch_rejected(setup):
    model, params, _ = setup
    # byte "0" (0x30) IS inside the 64-byte vocab, so the DFA builds
    # fine and the engine's vocab-size check is what must reject it
    tb = [bytes([i]) if i else b"" for i in range(64)]
    small = token_dfa(regex_to_dfa("0+"), tb, eos_id=0)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, params, n_slots=1, grammar=small)


def test_dead_end_grammar_rejected():
    # byte "a" (0x61) is OUTSIDE a 64-byte vocab: every state rejects
    # every token, which the dead-end guard must catch at build time
    tb = [bytes([i]) if i else b"" for i in range(64)]
    with pytest.raises(ValueError, match="dead-end"):
        token_dfa(regex_to_dfa("a+"), tb, eos_id=0)


def test_trap_transitions_trimmed():
    """A token step into a state from which acceptance is unreachable
    must be rejected up front (co-accessible trim): pattern 'ab' with
    a vocab holding 'a' but NOT 'b' — entering after 'a' would trap
    generation, so 'a' itself must be masked out and the grammar is a
    dead end at the start state."""
    tb = [b"", b"a", b"c"]
    with pytest.raises(ValueError, match="dead-end"):
        token_dfa(regex_to_dfa("ab"), tb, eos_id=0)
    # but with an alternative the trap branch is trimmed, not fatal
    td = token_dfa(regex_to_dfa("ab|c"), tb, eos_id=0)
    assert td.mask[0, 1] <= -1e8      # 'a' leads only to the trap
    assert td.mask[0, 2] > -1e8       # 'c' accepts


def test_json_lowering_is_rfc_strict():
    """The guided-JSON regexes must only admit parseable JSON: raw
    control chars in strings, leading-zero integers, and invalid
    escapes are rejected; enum/property strings with quotes lower to
    their escaped encodings."""
    import json as _json

    from tpu_k8s_device_plugin.workloads.grammar import (
        json_value_regex,
        schema_to_regex,
    )

    d = regex_to_dfa(json_value_regex(1))

    def m(s):
        cur = 0
        for b in s.encode():
            cur = int(d.table[cur, b])
            if cur < 0:
                return False
        return bool(d.accepting[cur])

    assert m('"a\\nb"') and m('"q\\"uo"') and m('"u\\u00e9x"')
    assert not m('"a\nb"')      # raw newline inside a string
    assert not m('"a\\qb"')     # \q is not a JSON escape
    assert not m("007")         # leading zeros
    assert m("0") and m("0.5") and m("-10e3")
    # enum values with quotes/backslashes force ESCAPED output
    e = regex_to_dfa(schema_to_regex({"enum": ['say "hi"']}))

    def me(s):
        cur = 0
        for b in s.encode():
            cur = int(e.table[cur, b])
            if cur < 0:
                return False
        return bool(e.accepting[cur])

    assert me(_json.dumps('say "hi"'))
    assert not me('"say "hi""')
    # property names JSON-encode too
    sr = schema_to_regex({"type": "object",
                          "properties": {'a"b': {"type": "null"}}})
    p = regex_to_dfa(sr)
    cur = 0
    for b in '{"a\\"b":null}'.encode():  # compact: schema default
        cur = int(p.table[cur, b])
        assert cur >= 0
    assert bool(p.accepting[cur])


def test_schema_lowering_fuzz():
    """Differential fuzz for the schema subset: random schemas,
    random CONFORMING values (accepted) and random mutations
    (rejected unless still conforming) — the DFA is the product
    clients trust for structured output."""
    import json as _json
    import random

    from tpu_k8s_device_plugin.workloads.grammar import schema_to_regex

    rnd = random.Random(99)

    def gen_schema(depth):
        kinds = ["string", "integer", "boolean", "null", "enum"]
        if depth > 0:
            kinds += ["object", "array"]
        k = rnd.choice(kinds)
        if k == "enum":
            return {"enum": rnd.sample(
                ["a", "b c", 'q"t', 0, 17, True, None], 3)}
        if k == "object":
            return {"type": "object", "properties": {
                name: gen_schema(depth - 1)
                for name in rnd.sample(["x", "y", "z"],
                                       rnd.randint(1, 3))}}
        if k == "array":
            return {"type": "array", "items": gen_schema(depth - 1)}
        return {"type": k}

    def gen_value(schema):
        if "enum" in schema:
            return rnd.choice(schema["enum"])
        t = schema["type"]
        if t == "string":
            return rnd.choice(["", "hi", 'sa"y', "a\\b", "é✓"])
        if t == "integer":
            return rnd.choice([0, 7, -13, 100200])
        if t == "boolean":
            return rnd.random() < 0.5
        if t == "null":
            return None
        if t == "array":
            return [gen_value(schema["items"])
                    for _ in range(rnd.randint(0, 3))]
        return {n: gen_value(sub)
                for n, sub in schema["properties"].items()}

    def accepts(d, s):
        cur = 0
        for b in s.encode():
            cur = int(d.table[cur, b])
            if cur < 0:
                return False
        return bool(d.accepting[cur])

    for _ in range(30):
        schema = gen_schema(2)
        d = regex_to_dfa(schema_to_regex(schema))
        for _ in range(8):
            v = gen_value(schema)
            compact = _json.dumps(v, separators=(",", ":"),
                                  ensure_ascii=False)
            assert accepts(d, compact), (schema, compact)
            # mutations: truncation and trailing junk never conform
            # (except dropping a digit from a bare integer, which may
            # leave another valid integer)
            if len(compact) > 1 and not compact[-1].isdigit():
                assert not accepts(d, compact[:-1]), compact
            assert not accepts(d, compact + "x"), compact


def test_grammar_composes_with_apc(setup):
    """A constrained admit sharing a cached prefix must reuse it (APC
    hit) and still decode in-grammar — prefix reuse only skips
    prefill, never the DFA."""
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                        max_new_tokens=8, chunk=4, auto_prefix_min=4,
                        grammar=dfa)
    shared = [7, 3, 9, 12, 5, 8, 1, 2]
    eng.admit(shared + [5, 9])
    before = eng.stats()["prefix_cache_hits"]
    sg = eng.admit(shared + [44], grammar=True)
    assert eng.stats()["prefix_cache_hits"] == before + 1
    eng.run(10)
    d = regex_to_dfa(PATTERN)
    cur = 0
    for b in _decode(eng.output(sg)).encode():
        cur = int(d.table[cur, b])
        assert cur >= 0


def test_grammar_composes_with_lora(setup):
    """Per-request adapters and per-request grammars are orthogonal
    slot data: a constrained adapter request and an unconstrained base
    request decode in the same batch, both correct."""
    from tpu_k8s_device_plugin.workloads.inference import (
        attach_lora,
        greedy_generate,
    )

    model, params, dfa = setup
    lora_mdl = make_decoder(**CFG, max_len=64, dtype=jnp.float32,
                            n_adapters=2, lora_rank=4)
    lora_params = attach_lora(params, lora_mdl, jax.random.PRNGKey(3))
    eng = ServingEngine(lora_mdl, lora_params, n_slots=2, eos_id=EOS,
                        max_new_tokens=8, grammar=dfa)
    sg = eng.admit([70, 71, 72], grammar=True, adapter=1)
    su = eng.admit([5, 9, 3])
    eng.run(10)
    d = regex_to_dfa(PATTERN)
    cur = 0
    for b in _decode(eng.output(sg)).encode():
        cur = int(d.table[cur, b])
        assert cur >= 0
    want, _ = greedy_generate(
        lora_mdl, lora_params,
        jnp.asarray([[5, 9, 3]], jnp.int32), 8)
    assert eng.output(su) == np.asarray(want)[0].tolist()


# -- structural jump-ahead (grammar-forced chains) ---------------------------

def _walk_valid(text, pattern):
    d = regex_to_dfa(pattern)
    cur = 0
    for b in text.encode():
        cur = int(d.table[cur, b])
        if cur < 0:
            return False
    return True


def test_jump_round_matches_step_decoding(setup):
    """jump_round commits DFA-forced chains in one extend; tokens must
    be bit-identical to plain step() decoding on an equivalent engine
    (a forced token IS the greedy pick under the mask hierarchy)."""
    from tpu_k8s_device_plugin.workloads.grammar import schema_to_regex

    model, params, _ = setup
    # a schema with literal keys: long forced runs between values
    schema = {"type": "object",
              "properties": {"id": {"type": "integer"},
                             "ok": {"type": "boolean"}}}
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(schema_to_regex(schema)), tb,
                    eos_id=EOS)

    def mk():
        e = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                          max_new_tokens=24, grammar=dfa, jump_len=6)
        return e, e.admit([70, 71, 72], grammar=True), e.admit([5, 9])

    a, sa, ua = mk()
    for _ in range(30):
        if not any(a.active):
            break
        a.step()
    b, sb, ub = mk()
    rounds = 0
    best_chain = 0
    while any(b.active) and rounds < 30:
        if b.forced_pending():
            got = b.jump_round()
            assert got is not None
            best_chain = max(best_chain,
                             max(len(v) for v in got.values()))
        else:
            b.step()
        rounds += 1
    assert a.output(sa) == b.output(sb)
    assert a.output(ua) == b.output(ub)
    # at least one jump committed a multi-token forced chain (the
    # schema's literal keys) — the compression the feature exists for
    assert best_chain >= 2, best_chain
    text = _decode(b.output(sb))
    assert _walk_valid(text, schema_to_regex(schema)), text


def test_jump_round_guards(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    eng.admit([70], grammar=True, temperature=0.7)
    assert not eng.jump_ready() and not eng.forced_pending()
    with pytest.raises(ValueError, match="jump_ready"):
        eng.jump_round()


def test_jump_round_endgame_returns_none(setup):
    """Too little headroom for the fixed band: jump_round must refuse
    (None) and leave the engine fully usable by step()."""
    model, params, _ = setup
    small = make_decoder(**CFG, max_len=16, dtype=jnp.float32)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa("(AB|CD)+E"), tb, eos_id=EOS)
    eng = ServingEngine(small, params, n_slots=1, eos_id=EOS,
                        grammar=dfa, jump_len=8)
    s = eng.admit([70, 71, 72, 73, 74, 75, 76, 77], grammar=True)
    assert eng.jump_round() is None  # 16 - 8 rows < jump_len + 1 = 9
    eng.step()
    assert len(eng.output(s)) >= 2


def test_jump_used_by_server(setup):
    """The scheduler takes the jump path for forced chains: a schema
    request over HTTP must finish with fewer decode rounds than
    tokens."""
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                        jump_len=6)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=24, window=4,
                       token_bytes=tb)
    srv.start(host="127.0.0.1", port=0)
    try:
        schema = {"type": "object",
                  "properties": {"id": {"type": "integer"}}}
        status, events = _post(srv.port, {
            "tokens": [70, 71], "guided_json": schema,
            "stream": False})
        assert status == 200
        toks = events[0]["tokens"]
        from tpu_k8s_device_plugin.workloads.grammar import (
            schema_to_regex,
        )

        assert _walk_valid(_decode(toks), schema_to_regex(schema))
        # forced keys commit in jumps: rounds < emitted tokens, and
        # the observability counters say so
        st = eng.stats()
        assert st["decode_steps"] < st["tokens_emitted"]
        assert st["jump_rounds"] >= 1
        assert st["jump_forced_tokens"] >= 2
        # the combined table packs to int16 while states fit
        assert eng._gtable_np.dtype == np.int16
    finally:
        srv.stop()


# -- the served surface: guided decoding over HTTP ---------------------------

def _post(port, payload, path="/generate"):
    import http.client
    import json as _json
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", path, _json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        events = [_json.loads(line) for line in resp if line.strip()]
        return resp.status, events
    finally:
        conn.close()


def _valid_prefix(text, pattern):
    d = regex_to_dfa(pattern)
    cur = 0
    for b in text.encode():
        cur = int(d.table[cur, b])
        if cur < 0:
            return False
    return True


@pytest.fixture()
def grammar_server(setup):
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=16, window=4,
                       token_bytes=tb)
    srv.start(host="127.0.0.1", port=0)
    yield srv, eng
    srv.stop()


def test_guided_regex_over_http(grammar_server):
    srv, eng = grammar_server
    status, events = _post(srv.port, {
        "tokens": [70, 71, 72], "guided_regex": PATTERN,
        "stream": False})
    assert status == 200
    text = _decode(events[0]["tokens"])
    if events[0]["finish_reason"] == "eos":
        assert re.fullmatch(PATTERN, text), text
    else:
        assert _valid_prefix(text, PATTERN), text
    # same pattern again: cache hit, no second registration
    status, _ = _post(srv.port, {
        "tokens": [9, 4], "guided_regex": PATTERN, "stream": False})
    assert status == 200
    assert srv.stats()["grammar_patterns"] == 1
    assert eng.n_grammars == 1
    # post-registration, the standalone TokenDfa host copy is dropped
    # (the engine's combined table holds the rows; keeping both would
    # pin a redundant [N, V] per pattern for the server's lifetime)
    assert PATTERN in srv._grammar_gids
    assert PATTERN not in srv._grammar_tdfas


def test_guided_json_schema_over_http(grammar_server):
    from tpu_k8s_device_plugin.workloads.grammar import schema_to_regex

    srv, _ = grammar_server
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"}}}
    status, events = _post(srv.port, {
        "tokens": [70, 71], "guided_json": schema, "stream": False})
    assert status == 200
    text = _decode(events[0]["tokens"])
    assert _valid_prefix(text, schema_to_regex(schema)), text
    assert text.startswith("{")


def test_guided_choice_over_http(grammar_server):
    """vLLM's guided_choice: the output is exactly one of the listed
    literals (or a prefix at the budget)."""
    srv, _ = grammar_server
    choices = ["AB", "CDE"]
    status, events = _post(srv.port, {
        "tokens": [70, 71], "guided_choice": choices,
        "stream": False})
    assert status == 200
    text = _decode(events[0]["tokens"])
    if events[0]["finish_reason"] == "eos":
        assert text in choices, text
    else:
        assert any(c.startswith(text) for c in choices), text
    status, _ = _post(srv.port, {
        "tokens": [1], "guided_choice": []})
    assert status == 400
    status, _ = _post(srv.port, {
        "tokens": [1], "guided_choice": ["A"], "guided_regex": "B"})
    assert status == 400


def test_grammar_beats_min_tokens_floor(grammar_server):
    """Mask hierarchy: when the DFA reaches an accepting state whose
    ONLY continuation is eos while a min_tokens floor still masks eos,
    the grammar (-1e9) must beat the floor (-1e6) — the request
    retires IN-GRAMMAR below its floor instead of degenerating to
    unmasked argmax and silently leaving the grammar."""
    srv, _ = grammar_server
    status, events = _post(srv.port, {
        "tokens": [70, 71], "guided_choice": ["AB"],
        "min_tokens": 6, "stream": False})
    assert status == 200
    ev = events[0]
    text = _decode(ev["tokens"])
    assert text == "AB", (text, ev)
    assert ev["finish_reason"] == "eos"


def test_guided_errors_are_400s(grammar_server, setup):
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    srv, _ = grammar_server
    status, events = _post(srv.port, {
        "tokens": [1], "guided_regex": "(oops"})
    assert status == 400 and "error" in events[0]
    status, events = _post(srv.port, {
        "tokens": [1], "guided_regex": "a+",
        "guided_json": True})
    assert status == 400
    # a server with no token-byte table rejects cleanly
    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS)
    bare = EngineServer(eng, max_new_tokens=4)
    bare.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(bare.port, {
            "tokens": [1], "guided_regex": "a+"})
        assert status == 400
        assert "token" in events[0]["error"]
    finally:
        bare.stop()


def test_guided_composes_with_ngram_spec(setup):
    """Constrained requests decode via run_scan (spec_ready gates on
    grammar-live slots); once they drain, greedy traffic resumes spec
    rounds — the adaptive composition the scheduler promises."""
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        draft="ngram", gamma=3)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=8, window=4,
                       token_bytes=tb)
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(srv.port, {
            "tokens": [70, 71, 72], "guided_regex": PATTERN,
            "stream": False})
        assert status == 200
        assert _valid_prefix(_decode(events[0]["tokens"]), PATTERN)
        rounds_after_grammar = eng.stats()["spec_rounds"]
        status, _ = _post(srv.port, {"tokens": [5, 9, 3],
                                     "stream": False})
        assert status == 200
        assert eng.stats()["spec_rounds"] > rounds_after_grammar
    finally:
        srv.stop()


def test_concurrent_distinct_patterns(grammar_server):
    """Two clients with two NEW patterns in flight at once: handler
    threads compile concurrently, the scheduler registers both, and
    each stream honors its OWN grammar (the _glock/_grammar_gids
    handoff under real concurrency)."""
    import threading

    srv, eng = grammar_server
    pats = {"(AB)+E": None, "(CD)+E": None}
    results = {}

    def go(pat):
        results[pat] = _post(srv.port, {
            "tokens": [70, 71], "guided_regex": pat, "stream": False})

    ts = [threading.Thread(target=go, args=(p,)) for p in pats]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for pat, (status, events) in results.items():
        assert status == 200, (pat, events)
        assert _valid_prefix(_decode(events[0]["tokens"]), pat), pat
    assert eng.n_grammars >= 2


def test_response_format_openai(setup):
    """OpenAI response_format={"type": "json_object"} constrains
    /v1/completions output to a JSON OBJECT (token bytes derived from
    the tokenizer); a json_schema without a schema object is a 400,
    never a silent fallback."""
    from tpu_k8s_device_plugin.workloads.grammar import (
        json_object_regex,
    )
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    class _ByteTok:
        def encode(self, s):
            return list(s.encode("latin-1"))

        def decode(self, ids):
            return bytes(int(t) % 256 for t in ids).decode("latin-1")

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS)
    srv = EngineServer(eng, max_new_tokens=12, window=4,
                       tokenizer=_ByteTok())
    srv.start(host="127.0.0.1", port=0)
    try:
        status, events = _post(srv.port, {
            "prompt": "Fe", "temperature": 0.0,
            "max_tokens": 12,
            "response_format": {"type": "json_object"}},
            path="/v1/completions")
        assert status == 200
        text = events[0]["choices"][0]["text"]
        assert _valid_prefix(text, json_object_regex()), text
        assert text.startswith("{")
        # malformed json_schema (schema key missing) -> 400
        status, events = _post(srv.port, {
            "prompt": "Fe", "max_tokens": 4,
            "response_format": {"type": "json_schema",
                                "json_schema": {"name": "x"}}},
            path="/v1/completions")
        assert status == 400
    finally:
        srv.stop()


def test_schema_empty_object_additional_properties_false():
    """ADVICE r5: {"type": "object", "additionalProperties": false}
    with no (or empty) properties admits ONLY the empty object — the
    old lowering fell through to json_object_regex, which permits
    arbitrary members the schema forbids."""
    from tpu_k8s_device_plugin.workloads.grammar import (
        json_object_regex,
        schema_to_regex,
    )

    pat = schema_to_regex({"type": "object",
                           "additionalProperties": False})
    assert pat == r"\{\}"
    assert schema_to_regex({"type": "object", "properties": {},
                            "additionalProperties": False}) == r"\{\}"
    # the lenient-whitespace variant keeps its separator fragment
    assert schema_to_regex(
        {"type": "object", "additionalProperties": False},
        ws=r"\s*") == r"\{\s*\}"
    # without the additionalProperties:false marker a schemaless
    # object still lowers to the general (members-allowed) form
    assert schema_to_regex({"type": "object"}) == json_object_regex(3)
    d = regex_to_dfa(pat)

    def m(s):
        cur = 0
        for b in s.encode():
            cur = int(d.table[cur, b])
            if cur < 0:
                return False
        return bool(d.accepting[cur])

    assert m("{}")
    assert not m('{"a":1}') and not m("{ }")


def test_grammar_cost_caps_reject_before_table(setup):
    """ADVICE r5: client-supplied guided_regex cost is bounded — a
    pattern compiling past --max-grammar-states (or past the pattern
    length bound) answers 400 BEFORE the [N, V] token table build."""
    from tpu_k8s_device_plugin.workloads.server import EngineServer

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=8, window=2,
                       token_bytes=tb, max_grammar_states=8)
    srv.start(host="127.0.0.1", port=0)
    try:
        # 12 literal chars -> 13 char-DFA states > the bound of 8
        status, events = _post(srv.port, {
            "tokens": [70], "guided_regex": "aaaaaaaaaaaa",
            "stream": False})
        assert status == 400
        assert "states" in events[0]["error"]
        # within the bound still serves
        status, _ = _post(srv.port, {
            "tokens": [70], "guided_regex": "ab", "stream": False})
        assert status == 200
        # the raw pattern-length bound rejects before compilation
        status, events = _post(srv.port, {
            "tokens": [70], "guided_regex": "a" * 5000,
            "stream": False})
        assert status == 400
        assert "chars" in events[0]["error"]
        assert srv.stats()["grammar_patterns"] == 1  # only "ab" got in
    finally:
        srv.stop()


def test_concurrent_distinct_patterns_respect_max_grammars(setup):
    """ADVICE r5 (_glock): concurrent first requests with DISTINCT
    patterns race the compiled->registered handoff; the distinct
    pattern count must never overshoot max_grammars, and every request
    answers cleanly (200, or the cache-full 400)."""
    import threading

    from tpu_k8s_device_plugin.workloads.server import EngineServer

    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=4, window=2,
                       token_bytes=tb, max_grammars=3)
    srv.start(host="127.0.0.1", port=0)
    try:
        patterns = [f"(ab|cd)+{c}" for c in "efghij"]
        results = [None] * len(patterns)

        def one(i):
            results[i] = _post(srv.port, {
                "tokens": [70], "guided_regex": patterns[i],
                "stream": False})

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(patterns))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        statuses = [r[0] for r in results]
        assert set(statuses) <= {200, 400}, statuses
        served = statuses.count(200)
        assert 1 <= served <= 3
        # the bound held through the race: never more distinct
        # patterns than max_grammars, pending or registered
        assert srv.stats()["grammar_patterns"] <= 3
    finally:
        srv.stop()
