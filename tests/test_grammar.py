"""Grammar-constrained decoding: a token-level DFA rides the decode
scan's carry, so every emitted sequence FULL-MATCHES the grammar (or
is one of its prefixes at the budget), step and run_scan agree
token-for-token, and unconstrained neighbors are untouched."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.grammar import (
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
EOS = 0
PATTERN = "(ab|cd)+e"


def _init(model, seed=0):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    # byte-per-token vocab (ids < 128 are their ascii bytes; 0 = eos)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return model, _init(model), dfa


def _decode(ids):
    return bytes(t for t in ids if t).decode("latin-1")


def test_regex_compiler_grid():
    d = regex_to_dfa(r"\d+(\.\d+)?")

    def m(s):
        cur = 0
        for b in s.encode():
            cur = int(d.table[cur, b])
            if cur < 0:
                return False
        return bool(d.accepting[cur])

    assert m("42") and m("3.14") and m("0")
    assert not m("") and not m(".5") and not m("3.") and not m("a")


def test_constrained_output_matches_grammar(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    s = eng.admit([70, 71, 72], grammar=True)
    eng.run(20)
    out = eng.output(s)
    text = _decode(out)
    if eng.finish_reason(s) == "eos":
        assert re.fullmatch(PATTERN, text), text
    else:  # budget/cache cut: still a valid PREFIX of the grammar
        d = regex_to_dfa(PATTERN)
        cur = 0
        for b in text.encode():
            cur = int(d.table[cur, b])
            assert cur >= 0, text


def test_scan_and_step_agree_constrained(setup):
    model, params, dfa = setup

    def mk():
        e = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                          max_new_tokens=10, grammar=dfa)
        return e, e.admit([70, 71], grammar=True), e.admit([5, 9, 3])

    a, sa, ua = mk()
    for _ in range(12):
        a.step()
    b, sb, ub = mk()
    b.run_scan(4)  # grammar state must survive the window boundary
    b.run_scan(6)
    assert a.output(sa) == b.output(sb)
    assert a.output(ua) == b.output(ub)
    # the unconstrained neighbor decodes exactly its solo stream
    want, _ = greedy_generate(
        model, params, jnp.asarray([[5, 9, 3]], jnp.int32), 10)
    assert a.output(ua) == np.asarray(want)[0].tolist()


def test_sampled_constrained_still_matches_grammar(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa)
    s = eng.admit([70, 71, 72], grammar=True, temperature=1.0,
                  seed=7)
    eng.run(20)
    text = _decode(eng.output(s))
    d = regex_to_dfa(PATTERN)
    cur = 0
    for b in text.encode():
        cur = int(d.table[cur, b])
        assert cur >= 0, text


def test_grammar_requires_engine_grammar(setup):
    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="grammar"):
        eng.admit([1, 2], grammar=True)


def test_grammar_excludes_spec(setup):
    model, params, dfa = setup
    draft = make_decoder(vocab=CFG["vocab"], d_model=32, n_heads=2,
                         n_layers=1, d_ff=64, max_len=64,
                         dtype=jnp.float32)
    eng = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                        grammar=dfa, draft=(draft, _init(draft, 1)))
    eng.admit([70, 71], grammar=True)
    assert not eng.spec_ready()
    with pytest.raises(ValueError, match="grammar"):
        eng.spec_round()


def test_vocab_mismatch_rejected(setup):
    model, params, _ = setup
    # byte "0" (0x30) IS inside the 64-byte vocab, so the DFA builds
    # fine and the engine's vocab-size check is what must reject it
    tb = [bytes([i]) if i else b"" for i in range(64)]
    small = token_dfa(regex_to_dfa("0+"), tb, eos_id=0)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, params, n_slots=1, grammar=small)


def test_dead_end_grammar_rejected():
    # byte "a" (0x61) is OUTSIDE a 64-byte vocab: every state rejects
    # every token, which the dead-end guard must catch at build time
    tb = [bytes([i]) if i else b"" for i in range(64)]
    with pytest.raises(ValueError, match="dead-end"):
        token_dfa(regex_to_dfa("a+"), tb, eos_id=0)
