"""Table-driven allocator tests asserting exact chosen subsets.

Mirrors the reference's besteffort_policy_test.go:25-216 (exact expected
device sets across topologies, sizes, and availability variations) on the
TPU fixture hosts.
"""

import os

import pytest

from tpu_k8s_device_plugin.allocator import (
    AllocationError,
    BestEffortPolicy,
    devices_from_discovery,
)
from tpu_k8s_device_plugin.tpu import get_tpu_chips


def make_policy(testdata, name):
    root = os.path.join(testdata, name)
    chips, topo = get_tpu_chips(
        os.path.join(root, "sys"), "/dev",
        os.path.join(root, "run", "tpu", "tpu-env"),
    )
    devs = devices_from_discovery(chips)
    policy = BestEffortPolicy()
    policy.init(devs, topo)
    return policy, devs


def addr(i):
    """PCI address of chip index i in the generated fixtures."""
    return f"0000:00:{4 + i:02x}.0"


# ---------------------------------------------------------------------------
# v5e-8: 8 whole chips on a 2x4 mesh (x-fastest indexing)
# ---------------------------------------------------------------------------

class TestV5e8WholeChips:
    @pytest.fixture(autouse=True)
    def _setup(self, testdata):
        self.policy, self.devs = make_policy(testdata, "v5e-8")
        self.all_ids = [d.id for d in self.devs]

    def test_size_two_adjacent_pair(self):
        got = self.policy.allocate(self.all_ids, [], 2)
        assert got == [addr(0), addr(1)]

    def test_size_four_prefers_2x2_submesh(self):
        got = self.policy.allocate(self.all_ids, [], 4)
        assert got == [addr(0), addr(1), addr(2), addr(3)]

    def test_size_four_fragmented_falls_to_column(self):
        # chips 3 and 5 taken: no 2x2 box is free; the x=0 column (a real
        # 1x4 ICI strip) must win over L-shaped blobs.
        avail = [addr(i) for i in (0, 1, 2, 4, 6, 7)]
        got = self.policy.allocate(avail, [], 4)
        assert got == [addr(0), addr(2), addr(4), addr(6)]

    def test_size_three_column_segment(self):
        got = self.policy.allocate(self.all_ids, [], 3)
        assert got == [addr(0), addr(2), addr(4)]

    def test_required_anchors_the_submesh(self):
        got = self.policy.allocate(self.all_ids, [addr(3)], 2)
        assert got == [addr(1), addr(3)]

    def test_full_set_returned_as_is(self):
        got = self.policy.allocate(self.all_ids, [], 8)
        assert got == [addr(i) for i in range(8)]

    def test_required_equals_size(self):
        got = self.policy.allocate(self.all_ids, [addr(5), addr(2)], 2)
        assert got == [addr(2), addr(5)]

    def test_size_eight_unavailable_one(self):
        with pytest.raises(AllocationError):
            self.policy.allocate(self.all_ids[:7], [], 8)

    def test_validation_errors(self):
        with pytest.raises(AllocationError):
            self.policy.allocate(self.all_ids, [], 0)
        with pytest.raises(AllocationError):
            self.policy.allocate(self.all_ids, [addr(0), addr(1)], 1)
        with pytest.raises(AllocationError):
            self.policy.allocate([addr(0)], [addr(1)], 1)
        with pytest.raises(AllocationError):
            self.policy.allocate(self.all_ids, ["bogus"], 1)

    def test_uninitialised_policy(self):
        with pytest.raises(AllocationError):
            BestEffortPolicy().allocate([addr(0)], [], 1)


# ---------------------------------------------------------------------------
# v5p-8-core: 4 chips (2x2) x 2 TensorCore partitions
# ---------------------------------------------------------------------------

def core(i, k):
    return f"{addr(i)}#core{k}"


class TestV5pCorePartitions:
    @pytest.fixture(autouse=True)
    def _setup(self, testdata):
        self.policy, self.devs = make_policy(testdata, "v5p-8-core")
        self.all_ids = [d.id for d in self.devs]

    def test_pair_stays_on_one_chip(self):
        got = self.policy.allocate(self.all_ids, [], 2)
        assert got == [core(0, 0), core(0, 1)]

    def test_size_three_spills_to_neighbor(self):
        got = self.policy.allocate(self.all_ids, [], 3)
        assert got == [core(0, 0), core(0, 1), core(1, 0)]

    def test_size_four_is_2x1_chip_box(self):
        got = self.policy.allocate(self.all_ids, [], 4)
        assert got == [core(0, 0), core(0, 1), core(1, 0), core(1, 1)]

    def test_singleton_hole_fills_least_free_chip(self):
        # chip1 has both cores free, chips 2,3 have one free each: the
        # fragmented chips must be preferred for a single-core ask
        # (anti-fragmentation, ≈ reference fewest-free-first).
        avail = [core(1, 0), core(1, 1), core(2, 1), core(3, 0)]
        got = self.policy.allocate(avail, [], 1)
        assert got == [core(2, 1)]

    def test_required_core_pulls_sibling(self):
        got = self.policy.allocate(self.all_ids, [core(2, 1)], 2)
        assert got == [core(2, 0), core(2, 1)]


# ---------------------------------------------------------------------------
# vfio-pf: no tpu-env metadata → PCIe/NUMA weights only
# ---------------------------------------------------------------------------

class TestNoTopologyFallback:
    @pytest.fixture(autouse=True)
    def _setup(self, testdata):
        root = os.path.join(testdata, "vfio-pf")
        chips, _topo = get_tpu_chips(
            os.path.join(root, "sys"), "/dev",
            os.path.join(root, "run", "tpu", "tpu-env"),
        )
        devs = devices_from_discovery(chips)
        self.policy = BestEffortPolicy()
        # deliberately no topology: exercises the PCIe/NUMA-only weights
        self.policy.init(devs, None)
        self.all_ids = [d.id for d in devs]

    def test_pair_prefers_same_numa(self):
        # fixture NUMA split: chips 0,1 node0; chips 2,3 node1
        got = self.policy.allocate(self.all_ids, [], 2)
        assert got == [addr(0), addr(1)]

    def test_required_cross_numa(self):
        got = self.policy.allocate(self.all_ids, [addr(2)], 2)
        assert got == [addr(2), addr(3)]


def test_empty_init_rejected():
    with pytest.raises(AllocationError):
        BestEffortPolicy().init([], None)


# ---------------------------------------------------------------------------
# Optimality contract, verified against brute force over all C(8,k)
# subsets (stronger than the reference's argmin-over-candidates,
# besteffort_policy.go:133-150): when a contiguous box covering the
# request exists it takes strict priority (only a real sub-mesh gives the
# workload ICI collectives — an L-shape can score lower on raw pairwise
# weight but is the worse grant); when no box exists, the pick must match
# the true pairwise-weight optimum.
# ---------------------------------------------------------------------------

class TestV5e8BruteForceOptimality:
    @pytest.fixture(autouse=True)
    def _setup(self, testdata):
        self.policy, self.devs = make_policy(testdata, "v5e-8")
        self.all_ids = [d.id for d in self.devs]
        self.model = self.policy._model

    @staticmethod
    def is_box(devs):
        """Independent contiguity oracle (no allocator code): the chosen
        chips form an axis-aligned box exactly covering their extents.
        (v5e has no wraparound, so plain interval contiguity is exact.)"""
        coords = [d.coords for d in devs]
        lens = []
        for axis in range(3):
            vals = sorted({c[axis] for c in coords})
            if vals[-1] - vals[0] + 1 != len(vals):
                return False
            lens.append(len(vals))
        return lens[0] * lens[1] * lens[2] == len(set(coords))

    def expected_weight(self, ids, size):
        """Brute-force oracle: min weight over contiguous boxes when any
        subset forms one, else min weight over all subsets."""
        import itertools
        by_id = self.model.by_id
        subsets = list(itertools.combinations(ids, size))
        box_weights = [
            self.model.set_weight(c)
            for c in subsets
            if self.is_box([by_id[i] for i in c])
        ]
        if box_weights:
            return min(box_weights)
        return min(self.model.set_weight(c) for c in subsets)

    @pytest.mark.parametrize("size", range(1, 9))
    def test_full_availability(self, size):
        got = self.policy.allocate(self.all_ids, [], size)
        assert len(got) == size
        assert self.model.set_weight(got) == self.expected_weight(
            self.all_ids, size
        )

    @pytest.mark.parametrize("size", range(1, 6))
    def test_fragmented_availability(self, size):
        # chips 1 and 6 taken: holes at (1,0) and (0,3)
        avail = [i for i in self.all_ids if i not in (addr(1), addr(6))]
        got = self.policy.allocate(avail, [], size)
        assert len(got) == size
        assert set(got) <= set(avail)
        assert self.model.set_weight(got) == self.expected_weight(avail, size)


# ---------------------------------------------------------------------------
# Torus wrap (v4/v5p-style): opposite grid edges are ICI neighbours
# ---------------------------------------------------------------------------

class TestTorusWrap:
    @pytest.fixture(autouse=True)
    def _setup(self):
        from tpu_k8s_device_plugin.allocator.device import AllocDevice
        from tpu_k8s_device_plugin.tpu.topology import IciTopology

        # one host row of a 4x1 torus ring: x wraps, so chip 0 and chip 3
        # are 1 hop apart
        self.topo = IciTopology(
            chips_per_host_bounds=(4, 1, 1),
            host_bounds=(1, 1, 1),
            wrap=(True, False, False),
        )
        self.devs = [
            AllocDevice(id=f"c{i}", parent_id=f"c{i}", chip_index=i,
                        coords=(i, 0, 0))
            for i in range(4)
        ]
        self.policy = BestEffortPolicy()
        self.policy.init(self.devs, self.topo)

    def test_wrap_edge_is_one_hop(self):
        assert self.topo.ici_distance(0, 3) == 1
        assert self.topo.ici_distance(0, 2) == 2

    def test_seam_pair_tie_break_is_deterministic(self):
        # {c0,c3} (1 hop via wrap) and {c2,c3} (1 hop linear) tie on
        # weight; the sort-key tie-break must pick the lower-indexed set
        # deterministically
        got = self.policy.allocate(["c0", "c2", "c3"], [], 2)
        assert sorted(got) == ["c0", "c3"]

    def test_required_uses_wrap_neighbor(self):
        got = self.policy.allocate(["c0", "c1", "c3"], ["c3"], 2)
        # c3-c0 is 1 hop (wrap), c3-c1 is 2 hops: c0 must win strictly
        assert sorted(got) == ["c0", "c3"]


class TestTorusSeamStrict:
    """A 5-ring where the seam pair is strictly cheaper than any
    alternative — passes only with wrap-aware box enumeration, no
    tie-break involved."""

    @pytest.fixture(autouse=True)
    def _setup(self):
        from tpu_k8s_device_plugin.allocator.device import AllocDevice
        from tpu_k8s_device_plugin.tpu.topology import IciTopology

        self.topo = IciTopology(
            chips_per_host_bounds=(5, 1, 1),
            host_bounds=(1, 1, 1),
            wrap=(True, False, False),
        )
        devs = [
            AllocDevice(id=f"c{i}", parent_id=f"c{i}", chip_index=i,
                        coords=(i, 0, 0))
            for i in range(5)
        ]
        self.policy = BestEffortPolicy()
        self.policy.init(devs, self.topo)

    def test_seam_pair_strictly_cheaper(self):
        # available c0, c2, c4: (c4,c0)=1 hop via wrap; (c0,c2)=(c2,c4)=2
        got = self.policy.allocate(["c0", "c2", "c4"], [], 2)
        assert sorted(got) == ["c0", "c4"]


class TestPerfGuard:
    """Budget guard for GetPreferredAllocation on the worst realistic case
    (VERDICT r1 #9, SURVEY §3.4: 'the only super-linear code in the repo').
    16 core-partition devices on an 8-chip host with fragmented
    availability must answer well inside the kubelet's patience — the
    greedy multi-seed fallback must not quietly go quadratic-times-seeds."""

    @pytest.fixture(autouse=True)
    def _setup(self):
        from tpu_k8s_device_plugin.allocator.device import AllocDevice
        from tpu_k8s_device_plugin.tpu.topology import (
            ACCELERATOR_SPECS, IciTopology,
        )

        self.topo = IciTopology(
            accelerator_type="v5p-16",
            spec=ACCELERATOR_SPECS["v5p"],
            chips_per_host_bounds=(2, 4, 1),
            host_bounds=(1, 1, 1),
        )
        devs = []
        for i in range(8):
            for k in range(2):
                devs.append(AllocDevice(
                    id=f"{addr(i)}#core{k}", parent_id=addr(i),
                    chip_index=i, core_index=k,
                    coords=(i % 2, i // 2, 0), numa_node=i // 4,
                ))
        self.devs = devs
        self.policy = BestEffortPolicy()
        self.policy.init(devs, self.topo)

    def test_fragmented_worst_case_under_budget(self):
        import time

        all_ids = [d.id for d in self.devs]
        # fragmentation patterns: every other core, one core per chip,
        # everything, and a required-anchored ask
        cases = [
            (all_ids[::2] + all_ids[1::4], [], 5),
            ([f"{addr(i)}#core0" for i in range(8)], [], 5),
            (all_ids, [], 7),
            (all_ids, [f"{addr(3)}#core1"], 6),
            (all_ids[3:], [all_ids[4]], 9),
        ]
        for avail, req, size in cases:  # correctness + warmup
            got = self.policy.allocate(avail, req, size)
            assert len(got) == size and set(req) <= set(got)
        t0 = time.perf_counter()
        rounds = 20
        for _ in range(rounds):
            for avail, req, size in cases:
                self.policy.allocate(avail, req, size)
        per_call_ms = (time.perf_counter() - t0) * 1000 / (rounds * len(cases))
        # generous for shared CI hosts; the point is catching a complexity
        # regression (an accidental exponential blows past this by orders)
        assert per_call_ms < 25.0, f"preferred allocation {per_call_ms:.1f}ms"
