"""CI metrics-lint: scrape EVERY /metrics surface in-process and run
tools/promlint.py over the bodies — the acceptance gate that all four
surfaces render promlint-clean exposition through the one obs.Registry
renderer.  Runs inside the race-stress loop too, so scrapes race real
traffic (handler threads, scheduler, pulse beats)."""

import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tools.promlint import lint
from tpu_k8s_device_plugin import obs

pytestmark = pytest.mark.filterwarnings("ignore")


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.status, resp.read().decode()


def _assert_clean(body, surface):
    errs = lint(body)
    assert not errs, f"{surface} /metrics fails promlint: {errs[:5]}"


def test_plugin_debug_surface_lints(testdata, tmp_path):
    """Plugin debug /metrics (surface 1) + the slice metric set
    (surface 4, same scrape) lint clean with live RPC traffic."""
    from fake_kubelet import FakeKubelet
    from tpu_k8s_device_plugin.manager import PluginManager
    from tpu_k8s_device_plugin.observability import DebugServer
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
    from tpu_k8s_device_plugin.slice import SliceMetrics, SliceState
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    kubelet = FakeKubelet(str(tmp_path / "device-plugins")).start()
    registry = obs.Registry()
    # the slice metric set rides the same registry the CLI would share
    state = SliceState(expected_workers=2, jax_port=8476,
                       metrics=SliceMetrics(registry))
    registry.on_collect(lambda: state.refresh_ages(10.0))
    state.join("host-a", coords=(0,), now=0.0)
    state.join("host-b", coords=(1,), now=0.0)
    state.heartbeat("host-a", healthy=False, reason="wedged", now=1.0)
    state.heartbeat("host-b", healthy=True, now=2.0)
    manager = PluginManager(impl, kubelet_dir=kubelet.dir,
                            kubelet_watch_interval_s=0.1,
                            registry=registry)
    manager.run(block=False)
    debug = DebugServer(manager, port=0).start()
    try:
        assert kubelet.wait_for_registration()
        stub = kubelet.plugin_stub("google.com_tpu")
        stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[pluginapi.ContainerAllocateRequest(
                devices_ids=["0000:00:04.0"])]))
        status, body = _get(debug.port, "/metrics")
        assert status == 200
        _assert_clean(body, "plugin-debug")
        # both surfaces present in the one scrape
        assert "tpu_plugin_rpc_total" in body
        assert "tpu_plugin_allocate_seconds_bucket" in body
        assert "tpu_slice_membership_transitions_total" in body
        assert "tpu_slice_heartbeat_age_seconds" in body
    finally:
        debug.stop()
        manager.stop()
        kubelet.stop()


def test_health_exporter_surface_lints(testdata):
    """Exporter /metrics (surface 2) lints clean over the fixture
    tree, including the probe-duration histogram."""
    from tpu_k8s_device_plugin.health.metrics import MetricsHTTPServer

    root = os.path.join(testdata, "v5e-8")
    srv = MetricsHTTPServer(port=0, host="127.0.0.1",
                            sysfs_root=os.path.join(root, "sys"),
                            dev_root=os.path.join(root, "dev")).start()
    try:
        for _ in range(2):  # second scrape reuses the live registry
            status, body = _get(srv.port, "/metrics")
        assert status == 200
        _assert_clean(body, "health-exporter")
        assert "tpu_device_health{" in body
        assert "tpu_exporter_probe_seconds_bucket" in body
        assert "tpu_exporter_scrapes_total 2" in body
    finally:
        srv.stop()


def test_serving_surface_lints():
    """Serving /metrics (surface 3) lints clean with real traffic:
    served requests, a shed 429, and the latency histograms."""
    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"tokens": [1, 2, 3],
                             "stream": False}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
        status, body = _get(srv.port, "/metrics")
        assert status == 200
        _assert_clean(body, "serving")
        samples = obs.parse_exposition(body)
        by = {(n, tuple(sorted(ls.items()))): v for n, ls, v in samples}
        assert by[("tpu_serve_request_seconds_count",
                   (("outcome", "ok"),))] >= 1
        assert by[("tpu_serve_ttft_seconds_count", ())] >= 1
        assert by[("tpu_serve_token_seconds_count", ())] >= 1
        # bridged stats renamed with the counter suffix
        assert by[("tpu_serving_requests_served_total", ())] >= 1
        # percentile estimation works end to end on the scraped body
        p95 = obs.histogram_quantile(samples, "tpu_serve_ttft_seconds",
                                     0.95)
        assert p95 == p95 and p95 >= 0
    finally:
        srv.stop()


def test_slice_registry_lints_standalone():
    """The slice metric set lints clean on its own registry (the
    bare-grpc deployment shape, no manager around it)."""
    from tpu_k8s_device_plugin.slice import SliceMetrics, SliceState

    metrics = SliceMetrics()
    state = SliceState(expected_workers=2, jax_port=8476,
                       heartbeat_timeout_s=5.0, metrics=metrics)
    state.join("b-host", coords=(1,), now=0.0)
    state.join("a-host", coords=(0,), now=0.0)
    state.heartbeat("a-host", healthy=True, now=1.0)
    state.heartbeat("b-host", healthy=False, reason="sysfs", now=1.5)
    state.heartbeat("a-host", healthy=True, now=2.0)
    state.heartbeat("b-host", healthy=True, now=3.0)
    state.refresh_ages(now=4.0)
    body = metrics.registry.render()
    _assert_clean(body, "slice")
    assert "tpu_slice_demotion_propagation_seconds_bucket" in body
