"""Ring attention vs the single-device oracle on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh
from jax.experimental import mesh_utils

from tpu_k8s_device_plugin.workloads.ring_attention import (
    full_attention,
    make_ring_attention,
    zigzag_permute,
    zigzag_unpermute,
)


@pytest.fixture(scope="module")
def mesh():
    devs = mesh_utils.create_device_mesh((8,), devices=jax.devices()[:8])
    return Mesh(devs, axis_names=("seq",))


def qkv(dtype=jnp.float32, B=2, T=64, H=2, D=16):
    # smallest shape with 8 ring steps still doing real multi-row tiles
    # (T/8 = 8 rows/device); interpret-mode cost scales with B*T^2*H*D
    # and this file is on the suite's critical path (1-core box)
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(mesh, causal):
    q, k, v = qkv()
    ring_fn, sharding = make_ring_attention(mesh, "seq", causal=causal)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = ring_fn(qs, ks, vs)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_output_stays_sequence_sharded(mesh):
    q, k, v = qkv()
    ring_fn, sharding = make_ring_attention(mesh, "seq")
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_fn(qs, ks, vs)
    # each device holds exactly its local T/8 sequence slice
    assert out.sharding.spec == sharding.spec
    assert out.addressable_shards[0].data.shape == (2, 64 // 8, 2, 16)


def test_bf16_inputs(mesh):
    q, k, v = qkv(jnp.bfloat16)
    ring_fn, sharding = make_ring_attention(mesh, "seq", causal=True)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = ring_fn(qs, ks, vs)
    want = full_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2,
    )


class TestZigzag:
    """Balanced causal layout (VERDICT r1 #6): same math as the oracle,
    rank-uniform work."""

    def test_permute_roundtrip(self):
        x = jnp.arange(2 * 32 * 3).reshape(2, 32, 3).astype(jnp.float32)
        z = zigzag_permute(x, 4)
        assert z.shape == x.shape
        np.testing.assert_array_equal(
            np.asarray(zigzag_unpermute(z, 4)), np.asarray(x)
        )
        # rank 0's shard (first T/4) must hold chunks 0 and 7 of 8
        np.testing.assert_array_equal(
            np.asarray(z[:, :8]),
            np.concatenate(
                [np.asarray(x[:, 0:4]), np.asarray(x[:, 28:32])], axis=1
            ),
        )

    @pytest.mark.parametrize("n_devs,T", [(4, 32), (8, 64)])
    def test_matches_full_attention(self, n_devs, T):
        devs = mesh_utils.create_device_mesh(
            (n_devs,), devices=jax.devices()[:n_devs]
        )
        mesh_n = Mesh(devs, axis_names=("seq",))
        q, k, v = qkv(T=T)
        ring_fn, sharding = make_ring_attention(
            mesh_n, "seq", causal=True, layout="zigzag"
        )
        qz, kz, vz = (
            jax.device_put(zigzag_permute(x, n_devs), sharding)
            for x in (q, k, v)
        )
        got = zigzag_unpermute(ring_fn(qz, kz, vz), n_devs)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_bf16(self, mesh):
        q, k, v = qkv(jnp.bfloat16)
        ring_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, layout="zigzag"
        )
        qz, kz, vz = (
            jax.device_put(zigzag_permute(x, 8), sharding) for x in (q, k, v)
        )
        got = zigzag_unpermute(ring_fn(qz, kz, vz), 8)
        assert got.dtype == jnp.bfloat16
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=3e-2, rtol=3e-2,
        )

    def test_non_causal_rejected(self, mesh):
        with pytest.raises(ValueError):
            make_ring_attention(mesh, "seq", causal=False, layout="zigzag")

    def test_indivisible_seq_rejected(self):
        x = jnp.zeros((1, 30, 1, 4))
        with pytest.raises(ValueError):
            zigzag_permute(x, 4)  # 30 % 8 != 0


def test_uneven_causal_first_block_rows():
    """Row 0 of the sequence attends only to itself — the fully-masked
    correction path (exp of -inf maxima) must not produce NaNs."""
    devs = mesh_utils.create_device_mesh((4,), devices=jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("seq",))
    q, k, v = qkv(B=1, T=16, H=1, D=8)
    ring_fn, sharding = make_ring_attention(mesh, "seq", causal=True)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = np.asarray(ring_fn(qs, ks, vs))
    assert not np.isnan(got).any()
    want = np.asarray(full_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


class TestFlashImpl:
    """impl="flash": Pallas kernels inside the ring (interpret mode on
    the CPU mesh — the identical code path that compiles on TPU)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh, causal):
        q, k, v = qkv()
        ring_fn, sharding = make_ring_attention(
            mesh, "seq", causal=causal, impl="flash"
        )
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        got = ring_fn(qs, ks, vs)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_oracle(self, mesh, causal):
        """The custom-VJP ring backward (rotating dK/dV partial sums,
        Pallas dq/dkv kernels with the global lse) equals autodiff
        through the dense oracle."""
        q, k, v = qkv(B=1, T=32, H=2, D=8)
        ring_fn, sharding = make_ring_attention(
            mesh, "seq", causal=causal, impl="flash"
        )

        def ring_loss(q, k, v):
            qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
            return jnp.sum(ring_fn(qs, ks, vs) ** 2)

        def oracle_loss(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4
            )

    def test_bf16(self, mesh):
        q, k, v = qkv(jnp.bfloat16)
        ring_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, impl="flash"
        )
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        got = ring_fn(qs, ks, vs)
        want = full_attention(q, k, v, causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=3e-2, rtol=3e-2,
        )

    def test_bf16_gradients(self, mesh):
        """Per-block partials stay f32 (flash_block_grads) so the ring
        sum only rounds once at the end — bf16 grads must track the
        oracle about as tightly as the dense flash kernel's."""
        # D=16, not 8: the CPU emitter rejects bf16 dots at T=32/D=8
        q, k, v = qkv(jnp.bfloat16, B=1, T=32, H=2, D=16)
        ring_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, impl="flash"
        )

        def ring_loss(q, k, v):
            qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
            return jnp.sum(ring_fn(qs, ks, vs).astype(jnp.float32) ** 2)

        def oracle_loss(q, k, v):
            return jnp.sum(
                full_attention(q, k, v, causal=True).astype(jnp.float32)
                ** 2
            )

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(w, np.float32),
                atol=6e-2, rtol=6e-2,
            )

    def test_matches_einsum_impl(self, mesh):
        q, k, v = qkv()
        flash_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, impl="flash"
        )
        einsum_fn, _ = make_ring_attention(
            mesh, "seq", causal=True, impl="einsum"
        )
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        np.testing.assert_allclose(
            np.asarray(flash_fn(qs, ks, vs)),
            np.asarray(einsum_fn(qs, ks, vs)),
            atol=2e-5, rtol=2e-5,
        )

    def test_unknown_impl_rejected(self, mesh):
        with pytest.raises(ValueError):
            make_ring_attention(mesh, "seq", impl="fused")


class TestZigzagFlash:
    """layout="zigzag" + impl="flash": the balanced causal layout with
    the Pallas kernels per tile."""

    def test_matches_full_attention(self, mesh):
        q, k, v = qkv()
        zz_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, layout="zigzag", impl="flash"
        )
        qz, kz, vz = (
            jax.device_put(zigzag_permute(x, 8), sharding)
            for x in (q, k, v)
        )
        got = zigzag_unpermute(zz_fn(qz, kz, vz), 8)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_matches_einsum_zigzag(self, mesh):
        q, k, v = qkv()
        flash_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, layout="zigzag", impl="flash"
        )
        einsum_fn, _ = make_ring_attention(
            mesh, "seq", causal=True, layout="zigzag", impl="einsum"
        )
        args = tuple(
            jax.device_put(zigzag_permute(x, 8), sharding)
            for x in (q, k, v)
        )
        np.testing.assert_allclose(
            np.asarray(flash_fn(*args)), np.asarray(einsum_fn(*args)),
            atol=2e-5, rtol=2e-5,
        )

    def test_gradients_match_oracle(self, mesh):
        """The zig-zag flash custom-VJP (three-tile branches, zero-padded
        dK/dV contributions riding the ring) equals dense autodiff."""
        q, k, v = qkv(B=1, T=32, H=2, D=8)
        zz_fn, sharding = make_ring_attention(
            mesh, "seq", causal=True, layout="zigzag", impl="flash"
        )

        def ring_loss(q, k, v):
            args = tuple(
                jax.device_put(zigzag_permute(x, 8), sharding)
                for x in (q, k, v)
            )
            out = zigzag_unpermute(zz_fn(*args), 8)
            return jnp.sum(out ** 2)

        def oracle_loss(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4
            )
