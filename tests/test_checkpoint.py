"""Checkpoint/resume: the restored trajectory must equal the
uninterrupted one — including across real process boundaries (save in
a SIGKILLed subprocess, restore in a fresh one) and onto a different
mesh shape than the save ran on."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.checkpoint import (
    latest_step,
    list_steps,
    restore_checkpoint,
    save_checkpoint,
)
from tpu_k8s_device_plugin.workloads.transformer import (
    lm_tree_shardings,
    lm_train_step,
    make_lm_mesh,
    synthetic_lm_batch,
)

CFG = llama.TINY_LLAMA


def _setup():
    model = llama.train_model(CFG, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens, labels, positions = synthetic_lm_batch(rng, 4, 16, CFG.vocab)
    params = model.init(rng, tokens, positions)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    import functools

    step = jax.jit(functools.partial(lm_train_step, model, tx))
    return step, params, opt_state, (tokens, labels, positions)


def test_resume_trajectory_identical(tmp_path):
    step, params, opt_state, batch = _setup()
    # uninterrupted: 5 steps
    p, o = params, opt_state
    losses = []
    for _ in range(5):
        p, o, loss = step(p, o, *batch)
        losses.append(float(loss))
    # interrupted: 2 steps, save, "crash", restore, 3 more
    p2, o2 = params, opt_state
    for _ in range(2):
        p2, o2, _ = step(p2, o2, *batch)
    save_checkpoint(str(tmp_path), 2, {"params": p2, "opt_state": o2})
    del p2, o2
    template = {"params": params, "opt_state": opt_state}
    restored = restore_checkpoint(str(tmp_path), template=template)
    p3, o3 = restored["params"], restored["opt_state"]
    resumed = []
    for _ in range(3):
        p3, o3, loss = step(p3, o3, *batch)
        resumed.append(float(loss))
    np.testing.assert_array_equal(np.asarray(losses[2:]),
                                  np.asarray(resumed))


def test_sharded_restore_onto_mesh(tmp_path):
    step, params, opt_state, batch = _setup()
    save_checkpoint(str(tmp_path), 0, {"params": params})
    mesh = make_lm_mesh(seq=1, model=2, expert=1)
    sh = {"params": lm_tree_shardings(mesh, params)}
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params}, shardings=sh)
    leaf = restored["params"]["block_0"]["mlp_gate"]["kernel"]
    assert leaf.sharding.spec == ("model",) or tuple(
        leaf.sharding.spec) == (None, "model")
    np.testing.assert_array_equal(
        np.asarray(leaf),
        np.asarray(params["block_0"]["mlp_gate"]["kernel"]))


def test_latest_and_gc(tmp_path):
    _, params, _, _ = _setup()
    for s in (1, 3, 7):
        save_checkpoint(str(tmp_path), s, {"params": params})
    assert list_steps(str(tmp_path)) == [1, 3, 7]
    assert latest_step(str(tmp_path)) == 7
    save_checkpoint(str(tmp_path), 9, {"params": params}, keep_last=2)
    assert list_steps(str(tmp_path)) == [7, 9]
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params})
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["final_norm"]["scale"]),
        np.asarray(params["final_norm"]["scale"]))


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "empty"))
    _, params, _, _ = _setup()
    save_checkpoint(str(tmp_path), 2, {"params": params})
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path), step=5,
                           template={"params": params})


def test_cross_process_crash_resume(tmp_path):
    # the claim is CROSS-process: one interpreter trains and is
    # SIGKILLed right after the save (no atexit, no orbax cleanup — a
    # preempted pod), a second fresh interpreter restores and
    # continues, and the trajectory must equal an uninterrupted run
    import subprocess
    import sys as _sys

    worker = os.path.join(os.path.dirname(__file__), "ckpt_worker.py")
    base = str(tmp_path / "ckpts")
    out = str(tmp_path / "resumed.json")
    crash = subprocess.run(
        [_sys.executable, worker, "train-crash", base, out],
        capture_output=True, text=True, timeout=300)
    assert crash.returncode == -9, crash.stderr  # died by SIGKILL
    assert "saved" in crash.stdout
    resume = subprocess.run(
        [_sys.executable, worker, "resume", base, out],
        capture_output=True, text=True, timeout=300)
    assert resume.returncode == 0, resume.stderr
    with open(out) as f:
        data = json.load(f)
    assert data["start_step"] == 2
    # oracle: the uninterrupted 5-step run (same seeds/config as the
    # worker), computed in THIS process
    step, params, opt_state, batch = _setup()
    p, o = params, opt_state
    losses = []
    for _ in range(5):
        p, o, loss = step(p, o, *batch)
        losses.append(float(loss))
    np.testing.assert_array_equal(
        np.asarray(losses[2:]), np.asarray(data["losses"]))


def test_restore_onto_different_mesh_shape(tmp_path):
    # a rescheduled job rarely lands on the same topology: save from a
    # model=2 placement, restore directly onto model=4 — values exact,
    # leaves placed on the NEW mesh without host-staging the tree
    step, params, opt_state, batch = _setup()
    mesh1 = make_lm_mesh(seq=1, model=2, expert=1)
    sharded = jax.device_put(params, lm_tree_shardings(mesh1, params))
    save_checkpoint(str(tmp_path), 0, {"params": sharded})
    mesh2 = make_lm_mesh(seq=1, model=4, expert=1)
    sh2 = {"params": lm_tree_shardings(mesh2, params)}
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params}, shardings=sh2)
    leaf = restored["params"]["block_0"]["mlp_gate"]["kernel"]
    assert leaf.sharding.mesh.shape["model"] == 4
    np.testing.assert_array_equal(
        np.asarray(leaf),
        np.asarray(params["block_0"]["mlp_gate"]["kernel"]))
    # and the restored tree trains: one step on the new placement
    p, o, loss = step(restored["params"], opt_state, *batch)
    assert np.isfinite(float(loss))


def test_torn_checkpoints_skipped_not_fatal(tmp_path):
    """Crash-safety satellite: torn/partial step dirs — an interrupted
    external copy, a truncated metadata file, an empty dir — are
    SKIPPED by latest_step/list_steps/restore_checkpoint, never raised
    on; the newest WHOLE checkpoint wins."""
    import shutil

    _, params, _, _ = _setup()
    for s in (1, 3):
        save_checkpoint(str(tmp_path), s, {"params": params})
    assert latest_step(str(tmp_path)) == 3

    # torn variant 1: an empty step dir (mkdir happened, nothing else)
    os.makedirs(tmp_path / "step_5")
    # torn variant 2: a truncated copy — every file cut to 1 byte,
    # including the orbax metadata (rsync died early)
    shutil.copytree(tmp_path / "step_3", tmp_path / "step_7")
    for root, _, files in os.walk(tmp_path / "step_7"):
        for name in files:
            with open(os.path.join(root, name), "r+b") as f:
                f.truncate(1)

    assert list_steps(str(tmp_path)) == [1, 3]
    assert latest_step(str(tmp_path)) == 3
    restored = restore_checkpoint(str(tmp_path),
                                  template={"params": params})
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["final_norm"]["scale"]),
        np.asarray(params["final_norm"]["scale"]))

    # torn variant 3: metadata intact but array payloads truncated —
    # structurally complete, so restore must FALL BACK to the next
    # older whole checkpoint instead of raising
    shutil.copytree(tmp_path / "step_3", tmp_path / "step_9")
    for root, _, files in os.walk(tmp_path / "step_9"):
        for name in files:
            if name in ("_CHECKPOINT_METADATA", "_METADATA"):
                continue
            with open(os.path.join(root, name), "r+b") as f:
                f.truncate(1)
    restored = restore_checkpoint(str(tmp_path),
                                  template={"params": params})
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["final_norm"]["scale"]),
        np.asarray(params["final_norm"]["scale"]))
    # an EXPLICIT step still addresses exactly what was asked for
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path), step=4,
                           template={"params": params})


def test_save_commits_atomically(tmp_path):
    """A crash mid-save must leave no step dir at all (the temp dir is
    the only casualty, swept by the next save) — the commit is the
    final rename."""
    import orbax.checkpoint as ocp

    from tpu_k8s_device_plugin.workloads import checkpoint as ckpt_mod

    _, params, _, _ = _setup()

    real_save = ocp.PyTreeCheckpointer.save
    calls = {"n": 0}

    def exploding_save(self, path, *a, **k):
        calls["n"] += 1
        real_save(self, path, *a, **k)
        raise RuntimeError("SIGKILL stand-in after the tree write")

    ocp.PyTreeCheckpointer.save = exploding_save
    try:
        with pytest.raises(RuntimeError, match="SIGKILL stand-in"):
            save_checkpoint(str(tmp_path), 4, {"params": params})
    finally:
        ocp.PyTreeCheckpointer.save = real_save
    assert calls["n"] == 1
    assert list_steps(str(tmp_path)) == []
    assert not any(
        name.startswith("step_") for name in os.listdir(tmp_path)
    ), "no torn step dir may survive a crashed save"

    # the next save sweeps any leftover temp dir and lands whole
    (tmp_path / f"{ckpt_mod._TMP_PREFIX}orphan").mkdir()
    save_checkpoint(str(tmp_path), 4, {"params": params})
    assert list_steps(str(tmp_path)) == [4]
    assert not any(
        name.startswith(ckpt_mod._TMP_PREFIX)
        for name in os.listdir(tmp_path)
    ), "orphaned temp dirs must be swept"


def test_multihost_save_shares_tmp_and_gates_commit(tmp_path,
                                                    monkeypatch):
    """Multi-host sharded saves (every rank on one shared RWX volume):
    orbax's save is a collective, so every process must write into ONE
    deterministic tmp dir, and only process 0 may sweep orphans, commit
    the rename, and garbage-collect — a non-primary rank doing any of
    those would tear peers' in-flight saves."""
    from tpu_k8s_device_plugin.workloads import checkpoint as ckpt_mod

    _, params, _, _ = _setup()
    barriers = []
    monkeypatch.setattr(ckpt_mod, "_process_count", lambda: 2)
    monkeypatch.setattr(ckpt_mod, "_barrier",
                        lambda name: barriers.append(name))
    orphan = tmp_path / f"{ckpt_mod._TMP_PREFIX}orphan"
    orphan.mkdir()

    # rank 1: writes shards into the shared tmp name, nothing else
    monkeypatch.setattr(ckpt_mod, "_process_index", lambda: 1)
    save_checkpoint(str(tmp_path), 4, {"params": params}, keep_last=1)
    assert (tmp_path / f"{ckpt_mod._TMP_PREFIX}4").is_dir(), \
        "non-primary must write into the deterministic shared tmp dir"
    assert not (tmp_path / "step_4").exists(), \
        "only process 0 commits the rename"
    assert orphan.is_dir(), "only process 0 sweeps orphans"
    assert barriers, "multi-host saves must fence on barriers"

    # rank 0: sweeps, commits, GCs
    monkeypatch.setattr(ckpt_mod, "_process_index", lambda: 0)
    save_checkpoint(str(tmp_path), 4, {"params": params}, keep_last=1)
    assert list_steps(str(tmp_path)) == [4]
    assert not orphan.exists()
    assert not any(
        name.startswith(ckpt_mod._TMP_PREFIX)
        for name in os.listdir(tmp_path)
    )


def test_quantize_after_restore_serves(tmp_path):
    # the serving handoff: restore a trained tree, quantize, decode
    from tpu_k8s_device_plugin.workloads.inference import (
        greedy_generate, quantize_lm_params)

    _, params, _, _ = _setup()
    save_checkpoint(str(tmp_path), 0, {"params": params})
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params})
    qp = quantize_lm_params(restored["params"])
    dec = llama.decoder(CFG, dtype=jnp.float32, quantized=True,
                        max_len=32)
    out, _ = greedy_generate(dec, qp, jnp.asarray([[1, 2, 3]]), 4)
    assert out.shape == (1, 4)
