"""Checkpoint/resume: the restored trajectory must equal the
uninterrupted one, including under sharded restore."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.checkpoint import (
    latest_step,
    list_steps,
    restore_checkpoint,
    save_checkpoint,
)
from tpu_k8s_device_plugin.workloads.transformer import (
    lm_tree_shardings,
    lm_train_step,
    make_lm_mesh,
    synthetic_lm_batch,
)

CFG = llama.TINY_LLAMA


def _setup():
    model = llama.train_model(CFG, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens, labels, positions = synthetic_lm_batch(rng, 4, 16, CFG.vocab)
    params = model.init(rng, tokens, positions)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    import functools

    step = jax.jit(functools.partial(lm_train_step, model, tx))
    return step, params, opt_state, (tokens, labels, positions)


def test_resume_trajectory_identical(tmp_path):
    step, params, opt_state, batch = _setup()
    # uninterrupted: 5 steps
    p, o = params, opt_state
    losses = []
    for _ in range(5):
        p, o, loss = step(p, o, *batch)
        losses.append(float(loss))
    # interrupted: 2 steps, save, "crash", restore, 3 more
    p2, o2 = params, opt_state
    for _ in range(2):
        p2, o2, _ = step(p2, o2, *batch)
    save_checkpoint(str(tmp_path), 2, {"params": p2, "opt_state": o2})
    del p2, o2
    template = {"params": params, "opt_state": opt_state}
    restored = restore_checkpoint(str(tmp_path), template=template)
    p3, o3 = restored["params"], restored["opt_state"]
    resumed = []
    for _ in range(3):
        p3, o3, loss = step(p3, o3, *batch)
        resumed.append(float(loss))
    np.testing.assert_array_equal(np.asarray(losses[2:]),
                                  np.asarray(resumed))


def test_sharded_restore_onto_mesh(tmp_path):
    step, params, opt_state, batch = _setup()
    save_checkpoint(str(tmp_path), 0, {"params": params})
    mesh = make_lm_mesh(seq=1, model=2, expert=1)
    sh = {"params": lm_tree_shardings(mesh, params)}
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params}, shardings=sh)
    leaf = restored["params"]["block_0"]["mlp_gate"]["kernel"]
    assert leaf.sharding.spec == ("model",) or tuple(
        leaf.sharding.spec) == (None, "model")
    np.testing.assert_array_equal(
        np.asarray(leaf),
        np.asarray(params["block_0"]["mlp_gate"]["kernel"]))


def test_latest_and_gc(tmp_path):
    _, params, _, _ = _setup()
    for s in (1, 3, 7):
        save_checkpoint(str(tmp_path), s, {"params": params})
    assert list_steps(str(tmp_path)) == [1, 3, 7]
    assert latest_step(str(tmp_path)) == 7
    save_checkpoint(str(tmp_path), 9, {"params": params}, keep_last=2)
    assert list_steps(str(tmp_path)) == [7, 9]
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params})
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["final_norm"]["scale"]),
        np.asarray(params["final_norm"]["scale"]))


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "empty"))
    _, params, _, _ = _setup()
    save_checkpoint(str(tmp_path), 2, {"params": params})
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path), step=5,
                           template={"params": params})


def test_quantize_after_restore_serves(tmp_path):
    # the serving handoff: restore a trained tree, quantize, decode
    from tpu_k8s_device_plugin.workloads.inference import (
        greedy_generate, quantize_lm_params)

    _, params, _, _ = _setup()
    save_checkpoint(str(tmp_path), 0, {"params": params})
    restored = restore_checkpoint(
        str(tmp_path), template={"params": params})
    qp = quantize_lm_params(restored["params"])
    dec = llama.decoder(CFG, dtype=jnp.float32, quantized=True,
                        max_len=32)
    out, _ = greedy_generate(dec, qp, jnp.asarray([[1, 2, 3]]), 4)
    assert out.shape == (1, 4)
