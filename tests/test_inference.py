"""KV-cache decode engine vs the training model as oracle: the cached
graph must be bit-compatible in structure (params load unchanged) and
numerically equal to recomputing the full forward every step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    decode_throughput,
    greedy_generate,
    init_cache,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.transformer import TransformerLM

CFG = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def trained():
    """Params initialized by the TRAINING model — the decode twin must
    consume them verbatim."""
    rng = jax.random.PRNGKey(3)
    model = TransformerLM(**CFG)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(rng, tokens)["params"]
    return model, params


def test_params_load_unchanged(trained):
    """Identical module trees: every training param lands in the decode
    model with the same path and shape."""
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    dec_params = dec.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
        jnp.zeros((1, 4), jnp.int32),
    )["params"]
    want = jax.tree_util.tree_map(lambda x: x.shape, params)
    got = jax.tree_util.tree_map(lambda x: x.shape, dec_params)
    assert want == got


def test_prefill_logits_match_training_model(trained):
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    rng = jax.random.PRNGKey(1)
    prompt = jax.random.randint(rng, (2, 8), 0, CFG["vocab"])
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (2, 8))
    want = model.apply({"params": params}, prompt, pos)
    got, _ = dec.apply(
        {"params": params, "cache": init_cache(dec, 2)}, prompt, pos,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_cached_decode_matches_recompute_oracle(trained):
    """Greedy generation with the cache == the naive loop that re-runs
    the full training model over the growing sequence each step.  Exact
    token-id agreement over 12 steps."""
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    rng = jax.random.PRNGKey(2)
    B, T_p, steps = 2, 6, 12
    prompt = jax.random.randint(rng, (B, T_p), 0, CFG["vocab"])

    got, _ = greedy_generate(dec, params, prompt, steps)

    # recompute oracle in ONE full-length forward: the model is causal,
    # so logits at position t-1 over [prompt; got] are exactly what the
    # step-by-step regrowing loop would see — the first diverging token
    # fails the argmax check at its own position (a per-step loop would
    # compile `steps` distinct shapes for the same assertion)
    full = jnp.concatenate([prompt, got.astype(prompt.dtype)], axis=1)
    logits = model.apply({"params": params}, full)
    want = jnp.argmax(logits[:, T_p - 1:-1, :], axis=-1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cache_lens_advance(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    prompt = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (1, 4))
    _, mut = dec.apply(
        {"params": params, "cache": init_cache(dec, 1)}, prompt, pos,
        mutable=["cache"],
    )
    assert mut["cache"]["block_0"]["cache_lens"].tolist() == [4]
    _, mut = dec.apply(
        {"params": params, "cache": mut["cache"]},
        jnp.zeros((1, 1), jnp.int32), jnp.full((1, 1), 4, jnp.int32),
        decode=True, mutable=["cache"],
    )
    assert mut["cache"]["block_0"]["cache_lens"].tolist() == [5]


def test_max_len_overflow_rejected(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        greedy_generate(dec, params, prompt, 8)


def test_decode_throughput_smoke(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    stats = decode_throughput(
        dec, params, jnp.zeros((2, 4), jnp.int32), n_steps=4, rounds=1
    )
    assert stats["tokens_per_sec"] > 0


def test_tensor_parallel_decode_matches_single_device(trained):
    """The serving TP claim, proven: params sharded Megatron-style with
    the training side's lm_tree_shardings over a model-axis mesh (cache
    and activations following via jit's sharding propagation) generate
    the same tokens as unsharded single-device decode."""
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from tpu_k8s_device_plugin.workloads.transformer import (
        lm_tree_shardings,
    )

    _, params = trained
    # f32 compute: TP row-splits contractions, which reorders partial
    # sums — at bf16 a near-tie could flip argmax across versions and
    # cascade; f32 makes the exact token assertion robust
    dec = make_decoder(**CFG, max_len=32, dtype=jnp.float32)
    rng = jax.random.PRNGKey(9)
    prompt = jax.random.randint(rng, (2, 6), 0, CFG["vocab"])

    want, want_logits = greedy_generate(dec, params, prompt, 10)

    mesh = Mesh(
        mesh_utils.create_device_mesh((4,), devices=jax.devices()[:4]),
        axis_names=("model",),
    )
    params_sh = jax.device_put(params, lm_tree_shardings(mesh, params))
    prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P()))
    got, got_logits = greedy_generate(dec, params_sh, prompt_sh, 10)

    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits),
        atol=1e-4, rtol=1e-4,
    )
    # the qkv kernel really is model-split, not replicated
    qkv = params_sh["block_0"]["qkv"]["kernel"]
    assert (
        qkv.addressable_shards[0].data.shape[1] == qkv.shape[1] // 4
    ), "qkv kernel not sharded on the model axis"


class TestSampling:
    def test_near_zero_temperature_recovers_greedy(self, trained):
        from tpu_k8s_device_plugin.workloads.inference import (
            sample_generate,
        )

        _, params = trained
        dec = make_decoder(**CFG, max_len=32)
        prompt = jax.random.randint(
            jax.random.PRNGKey(4), (2, 5), 0, CFG["vocab"]
        )
        greedy, _ = greedy_generate(dec, params, prompt, 8)
        sampled = sample_generate(
            dec, params, prompt, 8, jax.random.PRNGKey(0),
            temperature=1e-4,
        )
        np.testing.assert_array_equal(
            np.asarray(sampled), np.asarray(greedy)
        )

    def test_top_k_1_recovers_greedy(self, trained):
        from tpu_k8s_device_plugin.workloads.inference import (
            sample_generate,
        )

        _, params = trained
        dec = make_decoder(**CFG, max_len=32)
        prompt = jax.random.randint(
            jax.random.PRNGKey(5), (1, 4), 0, CFG["vocab"]
        )
        greedy, _ = greedy_generate(dec, params, prompt, 6)
        sampled = sample_generate(
            dec, params, prompt, 6, jax.random.PRNGKey(1), top_k=1
        )
        np.testing.assert_array_equal(
            np.asarray(sampled), np.asarray(greedy)
        )

    def test_reproducible_and_seed_sensitive(self, trained):
        from tpu_k8s_device_plugin.workloads.inference import (
            sample_generate,
        )

        _, params = trained
        dec = make_decoder(**CFG, max_len=32)
        prompt = jax.random.randint(
            jax.random.PRNGKey(6), (2, 4), 0, CFG["vocab"]
        )
        a = sample_generate(
            dec, params, prompt, 8, jax.random.PRNGKey(7), temperature=2.0
        )
        b = sample_generate(
            dec, params, prompt, 8, jax.random.PRNGKey(7), temperature=2.0
        )
        c = sample_generate(
            dec, params, prompt, 8, jax.random.PRNGKey(8), temperature=2.0
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_zero_steps_rejected(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    with pytest.raises(ValueError, match="n_steps"):
        greedy_generate(dec, params, jnp.zeros((1, 4), jnp.int32), 0)


def test_top_k_out_of_range_rejected(trained):
    from tpu_k8s_device_plugin.workloads.inference import sample_generate

    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    with pytest.raises(ValueError, match="top_k"):
        sample_generate(
            dec, params, jnp.zeros((1, 4), jnp.int32), 4,
            jax.random.PRNGKey(0), top_k=CFG["vocab"] + 1,
        )


class TestQuantized:
    def test_quantize_roundtrip_error_bounded(self, trained):
        """Symmetric per-channel int8: dequantized kernels within half a
        quantization step of the original, elementwise."""
        from tpu_k8s_device_plugin.workloads.inference import (
            quantize_lm_params,
        )

        _, params = trained
        qp = quantize_lm_params(params)
        w = np.asarray(params["block_0"]["qkv"]["kernel"], np.float32)
        wq = np.asarray(qp["block_0"]["qkv"]["kernel_int8"], np.float32)
        sc = np.asarray(qp["block_0"]["qkv"]["scale"], np.float32)
        np.testing.assert_allclose(wq * sc, w, atol=float(sc.max()) / 2 + 1e-7)
        # untouched leaves pass through unchanged (norms + embeddings)
        np.testing.assert_array_equal(
            np.asarray(qp["block_0"]["attn_norm"]["scale"]),
            np.asarray(params["block_0"]["attn_norm"]["scale"]),
        )
        np.testing.assert_array_equal(
            np.asarray(qp["embed"]["embedding"]),
            np.asarray(params["embed"]["embedding"]),
        )

    def test_quantized_decode_close_to_bf16(self, trained):
        """int8 weight-only decode tracks the unquantized engine: prefill
        logits within quantization tolerance and generation runs with
        the converted tree (same request API)."""
        from tpu_k8s_device_plugin.workloads.inference import (
            quantize_lm_params,
        )

        _, params = trained
        dec = make_decoder(**CFG, max_len=32)
        qdec = make_decoder(**CFG, max_len=32, quantized=True)
        qparams = quantize_lm_params(params)
        prompt = jax.random.randint(
            jax.random.PRNGKey(11), (2, 6), 0, CFG["vocab"]
        )
        toks, logits = greedy_generate(dec, params, prompt, 8)
        qtoks, qlogits = greedy_generate(qdec, qparams, prompt, 8)
        assert qtoks.shape == toks.shape
        assert bool(jnp.all(jnp.isfinite(qlogits)))
        # int8 error is ~0.4% of each channel's max; logits stay close
        np.testing.assert_allclose(
            np.asarray(qlogits), np.asarray(logits), atol=0.1, rtol=0.1
        )

    def test_quantized_param_structure_matches_init(self, trained):
        """quantize_lm_params produces exactly the tree the quantized
        model initializes — drop-in load, like the bf16 path."""
        from tpu_k8s_device_plugin.workloads.inference import (
            quantize_lm_params,
        )

        _, params = trained
        qdec = make_decoder(**CFG, max_len=32, quantized=True)
        init_q = qdec.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
            jnp.zeros((1, 4), jnp.int32),
        )["params"]
        want = jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)), init_q
        )
        got = jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)),
            quantize_lm_params(params),
        )
        assert want == got


class TestMoEDecode:
    """MoE configs serve through the same cache engine: the decode twin
    reuses the training MoEFFN, so expert stacks and router load
    unchanged.  A dropless capacity factor (cf >= E/k) makes routing
    identical between the growing-sequence oracle and single-token
    decode, so token agreement is exact."""

    # f32 so the exact-token assertion can't flip on an argmax
    # near-tie between the two (differently-contracted) FFN routes
    MOE = dict(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, moe_capacity_factor=4.0,  # dropless: cap >= T
        dtype=jnp.float32,
    )

    @pytest.fixture(scope="class")
    def moe_trained(self):
        rng = jax.random.PRNGKey(12)
        model = TransformerLM(**self.MOE)
        tokens = jnp.zeros((2, 8), jnp.int32)
        params = model.init(rng, tokens)["params"]
        return model, params

    def test_params_load_unchanged(self, moe_trained):
        _, params = moe_trained
        dec = make_decoder(**self.MOE, max_len=32)
        dec_params = dec.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
            jnp.zeros((1, 4), jnp.int32),
        )["params"]
        want = jax.tree_util.tree_map(lambda x: x.shape, params)
        got = jax.tree_util.tree_map(lambda x: x.shape, dec_params)
        assert want == got

    def test_cached_moe_decode_matches_recompute_oracle(self, moe_trained):
        model, params = moe_trained
        dec = make_decoder(**self.MOE, max_len=32)
        B, T_p, steps = 2, 6, 10
        prompt = jax.random.randint(
            jax.random.PRNGKey(13), (B, T_p), 0, self.MOE["vocab"]
        )
        got, _ = greedy_generate(dec, params, prompt, steps)

        # one full-length recompute (see the dense variant above);
        # dropless routing makes per-token MoE outputs length-
        # independent, so the single forward is the same oracle the
        # regrowing loop was
        full = jnp.concatenate(
            [prompt, got.astype(prompt.dtype)], axis=1)
        logits = model.apply({"params": params}, full)
        want = jnp.argmax(logits[:, T_p - 1:-1, :], axis=-1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_flash_prefill_matches_einsum_prefill(trained, monkeypatch):
    """Long prompts prefill through the Pallas flash kernel; lowering
    the threshold forces that path on a short prompt and the logits
    must match the einsum prefill."""
    from tpu_k8s_device_plugin.workloads import inference

    _, params = trained
    dec = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    prompt = jax.random.randint(
        jax.random.PRNGKey(15), (2, 16), 0, CFG["vocab"]
    )
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    want, _ = dec.apply(
        {"params": params, "cache": init_cache(dec, 2)}, prompt, pos,
        mutable=["cache"],
    )
    monkeypatch.setattr(inference, "_FLASH_PREFILL_MIN_T", 8)
    got, _ = dec.apply(
        {"params": params, "cache": init_cache(dec, 2)}, prompt, pos,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


class TestQuantizedMoE:
    MOE = dict(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        n_experts=4, moe_capacity_factor=4.0, dtype=jnp.float32,
    )

    def test_quantized_moe_structure_matches_init(self):
        """quantize_lm_params converts expert stacks too, matching the
        quantized MoE model's init tree exactly."""
        from tpu_k8s_device_plugin.workloads.inference import (
            quantize_lm_params,
        )

        model = TransformerLM(**self.MOE)
        params = model.init(
            jax.random.PRNGKey(17), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        qdec = make_decoder(**self.MOE, max_len=32, quantized=True)
        init_q = qdec.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
            jnp.zeros((1, 4), jnp.int32),
        )["params"]
        want = jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)), init_q
        )
        got = jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)), quantize_lm_params(params)
        )
        assert want == got

    def test_quantized_moe_decode_close_to_unquantized(self):
        from tpu_k8s_device_plugin.workloads.inference import (
            quantize_lm_params,
        )

        model = TransformerLM(**self.MOE)
        params = model.init(
            jax.random.PRNGKey(18), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        dec = make_decoder(**self.MOE, max_len=32)
        qdec = make_decoder(**self.MOE, max_len=32, quantized=True)
        prompt = jax.random.randint(
            jax.random.PRNGKey(19), (2, 6), 0, self.MOE["vocab"]
        )
        toks, logits = greedy_generate(dec, params, prompt, 8)
        qtoks, qlogits = greedy_generate(
            qdec, quantize_lm_params(params), prompt, 8
        )
        assert qtoks.shape == toks.shape
        assert bool(jnp.all(jnp.isfinite(qlogits)))
        np.testing.assert_allclose(
            np.asarray(qlogits), np.asarray(logits), atol=0.1, rtol=0.1
        )
