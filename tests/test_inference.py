"""KV-cache decode engine vs the training model as oracle: the cached
graph must be bit-compatible in structure (params load unchanged) and
numerically equal to recomputing the full forward every step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    decode_throughput,
    greedy_generate,
    init_cache,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.transformer import TransformerLM

CFG = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def trained():
    """Params initialized by the TRAINING model — the decode twin must
    consume them verbatim."""
    rng = jax.random.PRNGKey(3)
    model = TransformerLM(**CFG)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(rng, tokens)["params"]
    return model, params


def test_params_load_unchanged(trained):
    """Identical module trees: every training param lands in the decode
    model with the same path and shape."""
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    dec_params = dec.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
        jnp.zeros((1, 4), jnp.int32),
    )["params"]
    want = jax.tree_util.tree_map(lambda x: x.shape, params)
    got = jax.tree_util.tree_map(lambda x: x.shape, dec_params)
    assert want == got


def test_prefill_logits_match_training_model(trained):
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    rng = jax.random.PRNGKey(1)
    prompt = jax.random.randint(rng, (2, 8), 0, CFG["vocab"])
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (2, 8))
    want = model.apply({"params": params}, prompt, pos)
    got, _ = dec.apply(
        {"params": params, "cache": init_cache(dec, 2)}, prompt, pos,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_cached_decode_matches_recompute_oracle(trained):
    """Greedy generation with the cache == the naive loop that re-runs
    the full training model over the growing sequence each step.  Exact
    token-id agreement over 12 steps."""
    model, params = trained
    dec = make_decoder(**CFG, max_len=32)
    rng = jax.random.PRNGKey(2)
    B, T_p, steps = 2, 6, 12
    prompt = jax.random.randint(rng, (B, T_p), 0, CFG["vocab"])

    got, _ = greedy_generate(dec, params, prompt, steps)

    seq = prompt
    for _ in range(steps):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(seq.dtype)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    want = seq[:, T_p:]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cache_index_advances(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    prompt = jnp.zeros((1, 4), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (1, 4))
    _, mut = dec.apply(
        {"params": params, "cache": init_cache(dec, 1)}, prompt, pos,
        mutable=["cache"],
    )
    assert int(mut["cache"]["block_0"]["cache_index"]) == 4
    _, mut = dec.apply(
        {"params": params, "cache": mut["cache"]},
        jnp.zeros((1, 1), jnp.int32), jnp.full((1, 1), 4, jnp.int32),
        decode=True, mutable=["cache"],
    )
    assert int(mut["cache"]["block_0"]["cache_index"]) == 5


def test_max_len_overflow_rejected(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        greedy_generate(dec, params, prompt, 8)


def test_decode_throughput_smoke(trained):
    _, params = trained
    dec = make_decoder(**CFG, max_len=32)
    stats = decode_throughput(
        dec, params, jnp.zeros((2, 4), jnp.int32), n_steps=4, rounds=1
    )
    assert stats["tokens_per_sec"] > 0
