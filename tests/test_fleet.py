"""Fleet control plane coverage, three layers:

1. Pure decision core (no sockets, no clocks): seeded statz sequences
   fed to :class:`FleetPlanner` replay to DETERMINISTIC action
   sequences — hysteresis/cooldown (no flap), failure replacement
   bypassing cooldown, the degraded-slice rolling drain keyed on
   generation mismatch (never the flag alone), role choice under
   disagg, scale-to-zero, and capacity-bounded placement.
2. Capacity + router surfaces without HTTP: ``--capacity-spec``
   parsing, labeller-style membership files, and the router's
   ``POST /drain`` semantics called as plain methods.
3. One live e2e: the controller brings 2 REAL replica CLIs up behind
   an in-process router, a SIGKILL mid-flight is healed with a
   journaled, metric-counted failure replacement, and a drain takes a
   replica out of rotation without killing its process.

The ``tpu_fleet_*`` families are promlinted here so metrics-lint CI
covers the new exposition.
"""

import json
import os
import signal
import threading
import time

import pytest

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.slice import state as slice_state
from tpu_k8s_device_plugin.workloads import fleet, loadclient
from tpu_k8s_device_plugin.workloads.fleet import (
    Action,
    FleetController,
    FleetMetrics,
    FleetObservation,
    FleetPlanner,
    PlannerConfig,
    ReplicaView,
    SliceCapacity,
)
from tpu_k8s_device_plugin.workloads.router import RouterServer
from tools.promlint import lint

# ---------------------------------------------------------------------------
# layer 1: the pure decision core


CFG = PlannerConfig(min_replicas=1, max_replicas=4,
                    high_watermark=1.5, low_watermark=0.25,
                    up_stable_s=1.0, down_stable_s=5.0,
                    idle_to_zero_s=30.0, cooldown_s=3.0,
                    drain_timeout_s=10.0)
SLICES = (SliceCapacity("s0", 1, 4),)


def _rv(rid, state="ready", q=0, inf=0, cap=2, gen=1, alive=True,
        t0=0.0, role="mixed", dr=0.0, drr=""):
    return ReplicaView(
        rid=rid, role=role, state=state, slice_id="s0",
        generation=gen, alive=alive, healthy=True, queue_depth=q,
        in_flight=inf, capacity=cap, started_at_s=t0,
        drain_started_at_s=dr, drain_reason=drr)


def _obs(now, replicas, slices=SLICES, **kw):
    fleet_caps = sum(r.capacity for r in replicas
                     if r.state != "draining")
    kw.setdefault("capacity", fleet_caps)
    return FleetObservation(now_s=now, replicas=tuple(replicas),
                            slices=slices, **kw)


def test_empty_fleet_spawns_the_floor():
    plan = FleetPlanner(CFG).plan(_obs(0.0, ()))
    assert [(a.kind, a.reason) for a in plan.actions] \
        == [("spawn", "floor")]
    assert plan.actions[0].slice_id == "s0"
    assert plan.desired == 1


def test_hysteresis_pressure_must_sustain_before_scale_up():
    p = FleetPlanner(CFG)
    hot = _rv("fleet-1", q=6, inf=2)
    # first hot cycle: the up timer just started, nothing happens
    assert p.plan(_obs(10.0, (hot,), queue_depth=6, in_flight=2,
                       requests_served=5)).actions == ()
    # still hot 0.5s later: under up_stable_s, still held
    assert p.plan(_obs(10.5, (hot,), queue_depth=6, in_flight=2,
                       requests_served=9)).actions == ()
    # a calm cycle resets the timer entirely
    assert p.plan(_obs(11.0, (_rv("fleet-1"),),
                       requests_served=12)).actions == ()
    assert p.plan(_obs(12.4, (hot,), queue_depth=6, in_flight=2,
                       requests_served=15)).actions == ()
    # sustained past up_stable_s: scale up, reason=pressure
    plan = p.plan(_obs(13.6, (hot,), queue_depth=6, in_flight=2,
                       requests_served=20))
    assert [(a.kind, a.reason) for a in plan.actions] \
        == [("spawn", "pressure")]


def test_cooldown_blocks_back_to_back_scale_ups():
    p = FleetPlanner(CFG)
    hot1 = _rv("fleet-1", q=8, inf=2)
    p.plan(_obs(0.0, (hot1,), queue_depth=8, in_flight=2,
                requests_served=1))
    plan = p.plan(_obs(1.5, (hot1,), queue_depth=8, in_flight=2,
                       requests_served=2))
    assert [a.kind for a in plan.actions] == ["spawn"]
    # still hot immediately after: cooldown holds the loop
    hot2 = (_rv("fleet-1", q=8, inf=2), _rv("fleet-2", q=8, inf=2,
                                            t0=1.5))
    for t in (2.0, 3.0, 4.0):
        assert p.plan(_obs(t, hot2, queue_depth=16, in_flight=4,
                           requests_served=t)).actions == ()
    # cooldown over + pressure sustained: the next step is allowed
    plan = p.plan(_obs(5.0, hot2, queue_depth=16, in_flight=4,
                       requests_served=9))
    assert [a.kind for a in plan.actions] == ["spawn"]


def test_burning_slo_scales_up_with_goodput_reason():
    p = FleetPlanner(CFG)
    calm = _rv("fleet-1", q=0, inf=1)
    goodput = {"interactive": {"goodput_ratio": 0.4,
                               "burn_rate_max": 5.0,
                               "window_total": 20.0}}
    p.plan(_obs(0.0, (calm,), in_flight=1, goodput=goodput,
                requests_served=1))
    plan = p.plan(_obs(1.2, (calm,), in_flight=1, goodput=goodput,
                       requests_served=2))
    assert [(a.kind, a.reason) for a in plan.actions] \
        == [("spawn", "goodput")]
    # an empty window must NOT read as burning (ratio fields default
    # pessimistic in some exporters)
    p2 = FleetPlanner(CFG)
    empty = {"batch": {"goodput_ratio": 0.0, "burn_rate_max": 99.0,
                       "window_total": 0.0}}
    p2.plan(_obs(0.0, (calm,), in_flight=1, goodput=empty,
                 requests_served=1))
    assert p2.plan(_obs(1.2, (calm,), in_flight=1, goodput=empty,
                        requests_served=2)).actions == ()


def test_scale_in_drains_newest_after_sustained_calm():
    p = FleetPlanner(CFG)
    reps = (_rv("fleet-1", t0=0.0), _rv("fleet-2", t0=5.0))
    p.plan(_obs(100.0, reps, requests_served=50))
    assert p.plan(_obs(102.0, reps, requests_served=50)).actions == ()
    plan = p.plan(_obs(106.0, reps, requests_served=50))
    assert [(a.kind, a.reason, a.rid) for a in plan.actions] \
        == [("drain", "pressure", "fleet-2")]  # newest goes first
    # min_replicas=1 floors the shrink: with one left, no more drains
    p2 = FleetPlanner(CFG)
    one = (_rv("fleet-1"),)
    p2.plan(_obs(100.0, one, requests_served=50))
    assert p2.plan(_obs(120.0, one, requests_served=50)).actions == ()


def test_scale_to_zero_needs_min_zero_and_sustained_idle():
    cfg0 = PlannerConfig(min_replicas=0, max_replicas=2,
                         idle_to_zero_s=10.0, cooldown_s=1.0,
                         down_stable_s=60.0)
    p = FleetPlanner(cfg0)
    rep = (_rv("fleet-1"),)
    p.plan(_obs(0.0, rep, requests_served=30))
    # served counter still moving = not idle, timer keeps resetting
    assert p.plan(_obs(5.0, rep, requests_served=31)).actions == ()
    assert p.plan(_obs(11.0, rep, requests_served=32)).actions == ()
    # flat served + empty queues for idle_to_zero_s: drain to zero
    assert p.plan(_obs(15.0, rep, requests_served=32)).actions == ()
    plan = p.plan(_obs(26.0, rep, requests_served=32))
    assert [(a.kind, a.reason, a.rid) for a in plan.actions] \
        == [("drain", "idle", "fleet-1")]


def test_scale_from_zero_on_router_no_replica_pressure():
    cfg0 = PlannerConfig(min_replicas=0, max_replicas=2)
    p = FleetPlanner(cfg0)
    # zero replicas, no demand: stays at zero
    assert p.plan(_obs(0.0, (), no_replica_total=7)).actions == ()
    # the router sheds with no_replicas: the delta is the wake signal
    plan = p.plan(_obs(1.0, (), no_replica_total=9))
    assert [(a.kind, a.reason) for a in plan.actions] \
        == [("spawn", "pressure")]


def test_dead_replica_replaced_immediately_bypassing_cooldown():
    p = FleetPlanner(CFG)
    hot = _rv("fleet-1", q=8, inf=2)
    p.plan(_obs(0.0, (hot,), queue_depth=8, in_flight=2,
                requests_served=1))
    plan = p.plan(_obs(1.5, (hot,), queue_depth=8, in_flight=2,
                       requests_served=2))
    assert [a.kind for a in plan.actions] == ["spawn"]  # cooldown set
    # SIGKILL lands: stop+spawn the same cycle, cooldown irrelevant
    reps = (_rv("fleet-1", alive=False), _rv("fleet-2", t0=1.5))
    plan = p.plan(_obs(2.0, reps, requests_served=3))
    kinds = [(a.kind, a.reason) for a in plan.actions]
    assert ("stop", "failure") in kinds
    assert ("spawn", "failure") in kinds


def test_degraded_drain_keys_on_generation_not_flag():
    p = FleetPlanner(CFG)
    reps = (_rv("fleet-1", t0=0.0), _rv("fleet-2", t0=1.0))
    # the slice flips degraded WITHOUT a generation bump: replicas
    # still match advertised shape — draining here would loop forever
    # (the replacement would land on the same "degraded" generation)
    flagged = (SliceCapacity("s0", 1, 4, degraded=True),)
    assert p.plan(_obs(10.0, reps, slices=flagged,
                       requests_served=1)).actions == ()
    # the reshape lands (generation 2): rolling drain, ONE at a time,
    # oldest first
    reshaped = (SliceCapacity("s0", 2, 4, degraded=True),)
    plan = p.plan(_obs(11.0, reps, slices=reshaped,
                       requests_served=2))
    assert [(a.kind, a.reason, a.rid) for a in plan.actions] \
        == [("drain", "degraded", "fleet-1")]
    # while one drains, the second stale replica WAITS
    reps2 = (_rv("fleet-1", state="draining", dr=11.0,
                 drr="degraded", q=1),
             _rv("fleet-2", t0=1.0))
    assert p.plan(_obs(12.0, reps2, slices=reshaped,
                       requests_served=3)).actions == ()


def test_drain_completion_respawns_on_the_new_generation():
    p = FleetPlanner(CFG)
    reshaped = (SliceCapacity("s0", 2, 4),)
    reps = (_rv("fleet-1", state="draining", dr=10.0, drr="degraded",
                q=0, inf=0),
            _rv("fleet-2", t0=1.0, gen=2))
    plan = p.plan(_obs(12.0, reps, slices=reshaped,
                       requests_served=1))
    acts = [(a.kind, a.reason, a.generation) for a in plan.actions]
    assert ("stop", "degraded", 1) in acts
    assert ("spawn", "degraded", 2) in acts
    # a stuck drain is cut off at drain_timeout_s even with queue
    p2 = FleetPlanner(CFG)
    stuck = (_rv("fleet-1", state="draining", dr=0.0, drr="degraded",
                 q=5, inf=1),)
    plan = p2.plan(_obs(11.0, stuck, slices=reshaped,
                        requests_served=1))
    assert ("stop", "degraded", 1) in [
        (a.kind, a.reason, a.generation) for a in plan.actions]


def test_drain_needs_min_dwell_before_trusting_empty_queues():
    # the statz snapshot behind a drain verdict can be one scrape
    # interval stale: queue==0 at drain age < drain_min_s must NOT
    # complete the drain (stopping then tears live streams), but the
    # same observation past the dwell must
    p = FleetPlanner(CFG)
    reshaped = (SliceCapacity("s0", 2, 4),)
    fresh = (_rv("fleet-1", state="draining", dr=10.0,
                 drr="degraded", q=0, inf=0),
             _rv("fleet-2", t0=1.0, gen=2))
    plan = p.plan(_obs(10.0 + CFG.drain_min_s / 2, fresh,
                       slices=reshaped, requests_served=1))
    assert all(a.kind != "stop" for a in plan.actions)
    plan = p.plan(_obs(10.0 + CFG.drain_min_s, fresh,
                       slices=reshaped, requests_served=2))
    assert ("stop", "degraded") in [
        (a.kind, a.reason) for a in plan.actions]


def test_capacity_bounds_scale_out():
    tight = (SliceCapacity("s0", 1, 2),)  # 2 slots only
    p = FleetPlanner(CFG)
    reps = (_rv("fleet-1", q=9, inf=2), _rv("fleet-2", q=9, inf=2,
                                            t0=1.0))
    p.plan(_obs(0.0, reps, slices=tight, queue_depth=18, in_flight=4,
                requests_served=1))
    # pressure is sustained but every advertised slot is taken
    plan = p.plan(_obs(2.0, reps, slices=tight, queue_depth=18,
                       in_flight=4, requests_served=2))
    assert plan.actions == ()
    # max_replicas also caps even when slots are free
    cfg2 = PlannerConfig(min_replicas=1, max_replicas=2,
                         up_stable_s=1.0, cooldown_s=0.5)
    p2 = FleetPlanner(cfg2)
    p2.plan(_obs(0.0, reps, queue_depth=18, in_flight=4,
                 requests_served=1))
    assert p2.plan(_obs(2.0, reps, queue_depth=18, in_flight=4,
                        requests_served=2)).actions == ()


def test_disagg_role_choice_covers_phases_then_follows_pressure():
    cfg = PlannerConfig(min_replicas=1, max_replicas=4, disagg=True,
                        up_stable_s=0.5, cooldown_s=0.1)
    p = FleetPlanner(cfg)
    # empty fleet: first spawn is prefill (phase coverage first)
    plan = p.plan(_obs(0.0, ()))
    assert plan.actions[0].role == "prefill"
    # prefill exists, no decode: next is decode
    pre = _rv("fleet-1", role="prefill", q=9, inf=2)
    p.plan(_obs(1.0, (pre,), queue_depth=9, in_flight=2,
                requests_served=1))
    plan = p.plan(_obs(2.0, (pre,), queue_depth=9, in_flight=2,
                       requests_served=2))
    assert [a.role for a in plan.actions if a.kind == "spawn"] \
        == ["decode"]
    # both covered: the deeper-queued phase gets the third replica
    both = (_rv("fleet-1", role="prefill", q=1),
            _rv("fleet-2", role="decode", q=9, inf=2, t0=1.0))
    p.plan(_obs(3.0, both, queue_depth=10, in_flight=2,
                requests_served=3))
    plan = p.plan(_obs(4.0, both, queue_depth=10, in_flight=2,
                       requests_served=4))
    spawns = [a.role for a in plan.actions if a.kind == "spawn"]
    assert spawns == ["decode"]
    # scale-in never drains the last replica of a live role
    calm = (_rv("fleet-1", role="prefill", t0=0.0),
            _rv("fleet-2", role="decode", t0=1.0))
    p2 = FleetPlanner(PlannerConfig(
        min_replicas=1, max_replicas=4, disagg=True,
        down_stable_s=1.0, cooldown_s=0.1))
    p2.plan(_obs(10.0, calm, requests_served=9))
    plan = p2.plan(_obs(12.0, calm, requests_served=9))
    # fleet-2 (decode) is newest but is the last decode; fleet-1 is
    # the last prefill — neither is a safe victim, so the fleet holds
    assert all(a.kind != "drain" for a in plan.actions)


def test_planner_is_deterministic_over_a_recorded_sequence():
    hot = _rv("fleet-1", q=6, inf=2)
    seq = [
        _obs(0.0, ()),
        _obs(1.0, (hot,), queue_depth=6, in_flight=2,
             requests_served=3),
        _obs(2.2, (hot,), queue_depth=6, in_flight=2,
             requests_served=8),
        _obs(3.0, (_rv("fleet-1", alive=False),
                   _rv("fleet-2", t0=2.2)), requests_served=9),
        _obs(9.0, (_rv("fleet-2", t0=2.2), _rv("fleet-3", t0=3.0)),
             requests_served=9),
        _obs(15.0, (_rv("fleet-2", t0=2.2), _rv("fleet-3", t0=3.0)),
             requests_served=9),
    ]
    a = [FleetPlanner(CFG).plan(o) for o in [seq[0]]]
    p1, p2 = FleetPlanner(CFG), FleetPlanner(CFG)
    plans1 = [p1.plan(o) for o in seq]
    plans2 = [p2.plan(o) for o in seq]
    assert plans1 == plans2
    assert a[0] == plans1[0]
    # the sequence actually exercises transitions, not just holds
    kinds = [a.kind for pl in plans1 for a in pl.actions]
    assert "spawn" in kinds and "stop" in kinds and "drain" in kinds


def test_planner_config_validation():
    with pytest.raises(ValueError):
        PlannerConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        PlannerConfig(low_watermark=2.0, high_watermark=1.0)
    with pytest.raises(ValueError):
        PlannerConfig(goodput_floor=1.5)


# ---------------------------------------------------------------------------
# layer 2: capacity sources + the router drain surface (no HTTP)


def test_capacity_spec_parses_and_rejects_garbage(tmp_path):
    path = tmp_path / "cap.json"
    path.write_text(json.dumps({"slices": [
        {"slice_id": "s0", "generation": 3, "workers": 2},
        {"slice_id": "s1", "generation": 1, "workers": 4,
         "degraded": True, "max_replicas": 2},
    ]}))
    caps = fleet.load_capacity_spec(str(path))
    assert [c.slice_id for c in caps] == ["s0", "s1"]
    assert caps[0].slots == 2          # defaults to workers
    assert caps[1].slots == 2          # max_replicas overrides
    assert caps[1].degraded
    for bad in ("[]", '{"slices": "no"}',
                '{"slices": [{"generation": 1}]}'):
        path.write_text(bad)
        with pytest.raises(ValueError):
            fleet.load_capacity_spec(str(path))


def test_capacity_from_membership_reads_labeller_state(tmp_path):
    m = slice_state.Membership(
        slice_id="slice-a", generation=4,
        hostnames=("h0", "h1"), coordinator_address="h0:8476",
        degraded=True)
    p = tmp_path / "membership.json"
    slice_state.save_membership(str(p), m)
    caps = fleet.capacity_from_membership(
        [str(p), str(tmp_path / "absent.json")])
    assert len(caps) == 1
    assert caps[0] == SliceCapacity(
        slice_id="slice-a", generation=4, workers=2, degraded=True)


def test_router_drain_takes_replica_out_of_rotation():
    rt = RouterServer(statz_interval_s=60.0, replica_ttl_s=60.0)
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a",
                 "capacity": 4})
    rt.register({"address": "127.0.0.1:9002", "replica_id": "b",
                 "capacity": 4})
    def pick_rid():
        rep, _hit = rt.pick(None)
        return rep.rid if rep is not None else None

    # least-loaded tie-break is deterministic: "a" wins while routable
    assert pick_rid() == "a"
    out = rt.drain({"replica_id": "a"})
    assert out["ok"] and out["draining"]
    # pick() now never lands on the draining replica...
    assert pick_rid() == "b"
    # ...and with both draining, nothing is routable at all
    rt.drain({"replica_id": "b"})
    assert pick_rid() is None
    rt.drain({"replica_id": "b", "draining": False})
    # ...but its row survives (heartbeats keep flowing), flagged
    rows = {r["replica_id"]: r for r in rt.replicas()}
    assert rows["a"]["draining"] and not rows["b"]["draining"]
    per_rep = rt.fleet_statz()["per_replica"]
    assert per_rep["a"]["draining"] is True
    # heartbeat re-registration does not resurrect it into rotation
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a",
                 "capacity": 4})
    assert pick_rid() == "b"
    # undrain puts it back (and the tie-break favors it again)
    rt.drain({"replica_id": "a", "draining": False})
    assert pick_rid() == "a"
    # a ghost is a caller bug (404), a bad body a 400
    with pytest.raises(KeyError):
        rt.drain({"replica_id": "nope"})
    with pytest.raises(ValueError):
        rt.drain({"replica_id": ""})


def test_fleet_metrics_promlint_clean():
    registry = obs.Registry()
    m = FleetMetrics(registry)
    m.scale_events.labels(direction="up", reason="pressure").inc()
    m.decisions.labels(action="spawn").inc()
    m.drain_seconds.observe(1.5)
    m.replicas.set(2.0)
    m.desired.set(3.0)
    for mode in ("prom", "openmetrics"):
        problems = lint(registry.render(mode))
        assert problems == [], problems


# ---------------------------------------------------------------------------
# layer 3: live e2e — the controller drives real replica CLIs


@pytest.mark.slow
def test_controller_heals_sigkill_and_drains_live(tmp_path):
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    rt = RouterServer(statz_interval_s=0.3, replica_ttl_s=5.0,
                      breaker_reset_s=0.5, seed=3,
                      registry=registry)
    rt.start(host="127.0.0.1", port=0)
    cap = tmp_path / "capacity.json"
    cap.write_text(json.dumps({"slices": [
        {"slice_id": "live", "generation": 1, "workers": 2}]}))
    cache = os.environ.get(
        "TPU_DP_COMPILE_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), ".jax_cache"))
    controller = FleetController(
        f"http://127.0.0.1:{rt.port}",
        config=PlannerConfig(min_replicas=2, max_replicas=2,
                             start_grace_s=600.0,
                             down_stable_s=600.0,
                             idle_to_zero_s=600.0),
        server=fleet.ServerSpec(config="tiny", slots=2, max_len=256,
                                max_new_tokens=32,
                                compile_cache_dir=cache),
        capacity_spec=str(cap), interval_s=0.25, seed=3,
        registry=registry, recorder=recorder)
    loop = threading.Thread(target=controller.run, daemon=True)

    def healthy_count():
        return sum(1 for r in rt.replicas() if r.get("healthy"))

    def wait_for(pred, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.2)
        raise AssertionError(f"timed out waiting for {what}")

    try:
        loop.start()
        # the floor rule brings up both replicas (cold start on the
        # shared test compile cache)
        wait_for(lambda: healthy_count() >= 2, 600.0,
                 "2 healthy replicas")
        # traffic routes end to end through the router
        out = loadclient.stream_request(
            "127.0.0.1", rt.port,
            {"tokens": [1, 2, 3], "max_new_tokens": 4},
            timeout_s=120.0)
        assert out.outcome == loadclient.OUTCOME_OK

        # chaos: SIGKILL one managed replica; the reconciler must
        # stop the corpse and spawn a journaled failure replacement
        rid0, proc0 = controller.managed()[0]
        proc0.send_signal(signal.SIGKILL)
        wait_for(
            lambda: any(
                e["attrs"].get("reason") == "failure"
                for e in recorder.events(
                    name="tpu_fleet_replica_spawned")),
            120.0, "failure replacement journaled")
        wait_for(lambda: healthy_count() >= 2, 600.0,
                 "healed back to 2 healthy replicas")
        rids = {rid for rid, _ in controller.managed()}
        assert rid0 not in rids and len(rids) == 2

        # the failure scale-up is metric-backed, not just journaled
        samples = obs.parse_exposition(registry.render())
        up_failure = [
            v for name, labels, v in samples
            if name == "tpu_fleet_scale_events_total"
            and labels.get("direction") == "up"
            and labels.get("reason") == "failure"]
        assert up_failure and up_failure[0] >= 1.0
        assert any(name == "tpu_fleet_replicas" and v == 2.0
                   for name, labels, v in samples)

        # drain one replica directly: out of rotation, process alive
        rid1, proc1 = controller.managed()[0]
        controller._drain(Action(kind="drain", reason="degraded",
                                 rid=rid1))
        wait_for(
            lambda: {r["replica_id"]: r for r in rt.replicas()}
            .get(rid1, {}).get("draining") is True,
            30.0, "router marks the replica draining")
        assert proc1.poll() is None  # drained, NOT killed
        # and pick() avoids it while it drains
        for _ in range(8):
            rep, _hit = rt.pick(None)
            assert rep is not None and rep.rid != rid1
    finally:
        controller.shutdown()
        rt.stop()
