"""Session KV tiering: store policy + byte-identity + degradation.

Two layers of proof.  The SessionStore policy tests run against a fake
engine (pure-Python state dicts) and pin the tiering mechanics: idle
demotion device -> host -> disk, promotion on return, crash-safe disk
files that a fresh store generation inherits, truncation quarantine,
newest-K GC, bounded host RAM, single-owner export/import, and the
kv.promote fault degrading to a cold miss instead of an error.

The byte-identity suite runs the REAL engine and extends the house
invariant to session tiers: a conversation's turn-2 output is
BYTE-IDENTICAL whether its KV record returns from the device tier,
from a host checkpoint, from the migrate codec (the disk / wire
format), or from a second engine (replica crash + respawn) — versus a
cold full re-prefill of the chained prompt on a fresh engine — for
greedy, seeded-sampled, and grammar-constrained turns alike.
"""

import os
import threading
import time

import numpy as np
import pytest

from tpu_k8s_device_plugin.resilience import faults
from tpu_k8s_device_plugin.workloads import kv_tier
from tpu_k8s_device_plugin.workloads.kv_tier import (
    SessionStore,
    empty_tier_stats,
    sid_hash,
)
from tpu_k8s_device_plugin.workloads.migrate import (
    MigrateError,
    dump_payload,
    load_payload,
)


# -- fake engine -----------------------------------------------------------


class FakeEngine:
    """Slot bookkeeping without a model: parked sessions are state
    dicts keyed by slot, matching the four engine methods the store
    drives."""

    def __init__(self, n_slots=4):
        self.n_slots = n_slots
        self.parked = {}
        self.discarded = []

    def demote_session(self, slot):
        return self.parked.pop(slot)

    def resume_session(self, state):
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        self.parked[free[0]] = state
        return free[0]

    def discard_session(self, slot):
        self.parked.pop(slot)
        self.discarded.append(slot)

    def free_slots(self):
        return [s for s in range(self.n_slots) if s not in self.parked]


def _state(sid, n=64):
    return {
        "v": 1, "kind": "session", "session_id": sid,
        "tokens": np.arange(8, dtype=np.int32), "canon": 8,
        "adapter": 0, "kv": np.zeros(n, np.float32),
    }


def _park(store, eng, sid, slot, now_s=0.0):
    eng.parked[slot] = _state(sid)
    store.note_parked(sid, slot, now_s)


# -- store policy (fake engine) --------------------------------------------


def test_idle_demotion_chain_and_stats(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0, host_idle_s=1.0)
    _park(store, eng, "a", 0)
    assert store.stats()["device"] == 1
    store.tick(2.0)  # > 1.1x device_idle: device -> host
    st = store.stats()
    assert st["device"] == 0 and st["host"] == 1
    assert eng.parked == {}  # slot freed
    assert st["host_bytes"] > 0
    store.tick(5.0)  # > host deadline: host -> disk
    st = store.stats()
    assert st["host"] == 0 and st["disk"] == 1
    assert st["host_bytes"] == 0 and st["disk_bytes"] > 0
    files = os.listdir(tmp_path)
    assert len(files) == 1
    assert files[0].startswith(sid_hash("a") + "-")
    assert files[0].endswith(".kvs")
    assert st["demotions"] == 2


def test_prepare_hits_every_tier(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0, host_idle_s=1.0)
    _park(store, eng, "a", 0)
    assert store.prepare("a", 0.5) == "device"
    store.tick(2.0)
    assert store.prepare("a", 2.5) == "host"
    assert 0 in eng.parked  # promoted back onto a device slot
    store.tick(4.5)  # device -> host again
    store.tick(7.0)  # host -> disk
    assert os.listdir(tmp_path)
    assert store.prepare("a", 8.0) == "disk"
    assert not os.listdir(tmp_path)  # delete-on-promote
    assert store.prepare("nope", 9.0) == ""  # cold miss
    hits = store.stats()["hits"]
    assert hits == {"device": 1, "host": 1, "disk": 1}
    assert store.stats()["promotions"] == 2


def test_prepare_can_restore_false_gates_restores(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0)
    _park(store, eng, "a", 0)
    store.tick(2.0)
    assert store.prepare("a", 2.5, can_restore=False) == ""
    assert store.stats()["host"] == 1  # untouched, promotable later
    assert store.prepare("a", 2.5) == "host"


def test_disk_survives_process_death(tmp_path):
    eng1 = FakeEngine()
    store1 = SessionStore(eng1, spill_dir=str(tmp_path))
    _park(store1, eng1, "conv", 0)
    store1.spill_all(0.0)
    assert store1.stats()["disk"] == 1
    # a new generation on the same dir (fresh engine = respawn after
    # SIGKILL) lazily rehydrates from filenames alone
    eng2 = FakeEngine()
    store2 = SessionStore(eng2, spill_dir=str(tmp_path))
    assert store2.stats()["disk"] == 1
    assert store2.prepare("conv", 0.0) == "disk"
    got = eng2.parked[0]
    assert got["session_id"] == "conv"
    np.testing.assert_array_equal(got["tokens"], _state("conv")["tokens"])
    np.testing.assert_array_equal(got["kv"], _state("conv")["kv"])


def test_truncated_spill_quarantined(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path))
    _park(store, eng, "a", 0)
    store.spill_all(0.0)
    (name,) = os.listdir(tmp_path)
    path = os.path.join(tmp_path, name)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) - 5])
    eng2 = FakeEngine()
    store2 = SessionStore(eng2, spill_dir=str(tmp_path))
    assert store2.prepare("a", 0.0) == ""  # degraded, not raised
    assert eng2.parked == {}
    assert not os.listdir(tmp_path)  # poisoned file quarantined
    assert store2.stats()["evictions"] == 1
    assert store2.prepare("a", 1.0) == ""  # never retried


def test_disk_gc_keeps_newest_k(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path), disk_keep=2)
    for i, sid in enumerate(["a", "b", "c", "d"]):
        _park(store, eng, sid, 0)
        store.spill_all(float(i))
    st = store.stats()
    assert st["disk"] == 2 and st["evictions"] == 2
    assert len(os.listdir(tmp_path)) == 2
    # the two newest survive
    assert store.prepare("d", 9.0) == "disk"
    eng.parked.clear()
    assert store.prepare("c", 9.0) == "disk"
    assert store.prepare("a", 9.0) == ""


def test_host_cap_drops_without_spill_dir():
    eng = FakeEngine()
    # each state is ~ (8*4 + 64*4) bytes; cap admits one, not two
    store = SessionStore(eng, spill_dir=None, host_cap_bytes=400,
                         device_idle_s=1.0)
    _park(store, eng, "a", 0)
    _park(store, eng, "b", 1)
    store.tick(2.0)  # both demote; cap evicts the older host entry
    st = store.stats()
    assert st["host"] == 1
    assert st["host_bytes"] <= 400
    assert st["evictions"] == 1
    assert st["disk"] == 0


def test_promote_fault_degrades_to_cold(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0)
    _park(store, eng, "a", 0)
    store.tick(2.0)
    faults.install("kv.promote:error:1", seed=0)
    try:
        assert store.prepare("a", 2.5) == ""  # degraded, no raise
    finally:
        faults.uninstall()
    assert store.stats()["host"] == 1  # still parked in host RAM
    assert store.prepare("a", 3.0) == "host"  # recovers after the fault


def test_export_host_and_disk_single_owner(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0, host_idle_s=1.0)
    _park(store, eng, "a", 0)
    store.tick(2.0)  # -> host
    raw = store.export_session("a")
    assert load_payload(raw)["session_id"] == "a"
    assert store.stats()["host"] == 0  # single owner: local copy gone
    with pytest.raises(KeyError):
        store.export_session("a")
    _park(store, eng, "b", 0)
    store.spill_all(0.0)  # -> disk
    raw = store.export_session("b")
    assert load_payload(raw)["session_id"] == "b"
    assert not os.listdir(tmp_path)
    with pytest.raises(KeyError):
        store.export_session("b")


def test_export_device_via_scheduler_tick(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path))
    _park(store, eng, "a", 0)
    box = {}

    def exporter():
        box["raw"] = store.export_session("a", timeout_s=10.0)

    t = threading.Thread(target=exporter)
    t.start()
    deadline = time.monotonic() + 10.0
    while t.is_alive() and time.monotonic() < deadline:
        store.tick(0.0)  # scheduler services the queued export
        time.sleep(0.01)
    t.join(timeout=1.0)
    assert load_payload(box["raw"])["session_id"] == "a"
    assert eng.parked == {}  # device copy handed off
    assert store.stats()["device"] == 0


def test_import_payload_installs_host_entry(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path))
    sid = store.import_payload(dump_payload(_state("moved")), 0.0)
    assert sid == "moved"
    assert store.stats()["host"] == 1
    assert store.prepare("moved", 0.5) == "host"
    with pytest.raises(MigrateError):
        store.import_payload(dump_payload({"kind": "kv"}), 0.0)


def test_import_supersedes_device_copy_on_next_tick(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path))
    _park(store, eng, "a", 0)
    store.import_payload(dump_payload(_state("a")), 0.0)
    store.tick(0.1)  # stale device slot discarded by the scheduler
    assert eng.discarded == [0]
    assert store.stats()["host"] == 1 and store.stats()["device"] == 0


def test_note_parked_supersedes_older_tiers(tmp_path):
    eng = FakeEngine()
    store = SessionStore(eng, spill_dir=str(tmp_path))
    _park(store, eng, "a", 0)
    _park(store, eng, "a", 1)  # newer turn parked on another slot
    assert eng.discarded == [0]
    assert store.stats()["device"] == 1


def test_demote_for_pages_frees_closest_to_idle():
    eng = FakeEngine()
    store = SessionStore(eng, device_idle_s=1.0)
    assert store.demote_for_pages(0.0) is False  # nothing to give
    _park(store, eng, "a", 0, now_s=0.0)
    _park(store, eng, "b", 1, now_s=5.0)
    assert store.demote_for_pages(6.0) is True
    st = store.stats()
    assert st["host"] == 1 and st["device"] == 1
    assert 1 in eng.parked and 0 not in eng.parked  # oldest went


def test_slot_pressure_tick_demotes():
    eng = FakeEngine(n_slots=1)
    store = SessionStore(eng, device_idle_s=1000.0)
    _park(store, eng, "a", 0)
    store.tick(1.0)  # not idle: stays
    assert store.stats()["device"] == 1
    store.tick(1.0, slot_pressure=True)
    assert store.stats()["device"] == 0 and store.stats()["host"] == 1
    assert eng.free_slots() == [0]


def test_stats_schema_matches_empty():
    store = SessionStore(FakeEngine())
    assert set(store.stats()) == set(empty_tier_stats())
    assert store.stats() == empty_tier_stats()


def test_spill_filenames_newest_seq_wins(tmp_path):
    # two generations of the same session on disk: the rescan keeps
    # the newest seq and deletes the stale prefix file
    h = sid_hash("s")
    state = _state("s")
    for seq in (3, 7):
        with open(os.path.join(tmp_path,
                               f"{h}-{seq:08d}{kv_tier._SPILL_SUFFIX}"),
                  "wb") as f:
            f.write(dump_payload(state))
    store = SessionStore(FakeEngine(), spill_dir=str(tmp_path))
    assert store.stats()["disk"] == 1
    (name,) = os.listdir(tmp_path)
    assert name == f"{h}-{7:08d}{kv_tier._SPILL_SUFFIX}"


# -- byte-identity on the real engine --------------------------------------


jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_k8s_device_plugin.workloads.grammar import (  # noqa: E402
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import make_decoder  # noqa: E402
from tpu_k8s_device_plugin.workloads.serving import ServingEngine  # noqa: E402

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
MAX_LEN = 64
EOS = 0
PATTERN = "(AB|CD)+E"
SID = "conv-1"
P1 = list(range(1, 13))
P2 = [33, 34, 35]


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return model, params, dfa


def _mk(model, params, dfa):
    return ServingEngine(model, params, n_slots=3, chunk=8,
                         max_new_tokens=6, auto_prefix_min=4,
                         grammar=dfa, kv_paging=True)


def _turn(eng, prompt, **kw):
    s = eng.admit(list(prompt), **kw)
    while not eng.finished(s):
        eng.step()
    return s, eng.output(s)


def _turn1_parked(eng):
    """Run turn 1 of the conversation and park it as SID's device
    tier; returns the chained turn-2 prompt."""
    s, out1 = _turn(eng, P1, session=SID)
    eng.park_session(s, SID, len(out1))
    return P1 + out1 + P2


TURN2 = [
    ("greedy", {}),
    ("sampled", dict(temperature=0.8, seed=7)),
    ("grammar", dict(grammar=True)),
]


@pytest.mark.parametrize("name,kw", TURN2, ids=[t[0] for t in TURN2])
def test_resume_byte_identity_all_tiers(setup, name, kw):
    model, params, dfa = setup
    # oracle: cold full re-prefill of the chained prompt, no session
    cold = _mk(model, params, dfa)
    chain = P1 + _turn(cold, P1)[1] + P2
    _, want = _turn(_mk(model, params, dfa), chain, **kw)

    # device tier: parked record answers the next turn in place
    eng = _mk(model, params, dfa)
    chain_d = _turn1_parked(eng)
    assert chain_d == chain
    _, got = _turn(eng, chain, session=SID, **kw)
    assert got == want, f"device tier diverged ({name})"

    # host tier: demote -> resume round-trip through the checkpoint
    eng = _mk(model, params, dfa)
    _turn1_parked(eng)
    slot = eng.session_slots()[SID]
    eng.resume_session(eng.demote_session(slot))
    _, got = _turn(eng, chain, session=SID, **kw)
    assert got == want, f"host tier diverged ({name})"

    # disk tier: the migrate codec is the on-disk / wire format
    eng = _mk(model, params, dfa)
    _turn1_parked(eng)
    slot = eng.session_slots()[SID]
    raw = dump_payload(eng.demote_session(slot))
    eng.resume_session(load_payload(raw))
    _, got = _turn(eng, chain, session=SID, **kw)
    assert got == want, f"disk tier diverged ({name})"

    # replica loss: the checkpoint resumes on a SECOND engine (fresh
    # process after a crash, or the cross-replica move target)
    eng2 = _mk(model, params, dfa)
    eng2.resume_session(load_payload(raw))
    _, got = _turn(eng2, chain, session=SID, **kw)
    assert got == want, f"respawned replica diverged ({name})"


def test_session_record_is_conversation_private(setup):
    model, params, dfa = setup
    eng = _mk(model, params, dfa)
    chain = _turn1_parked(eng)
    # a foreign session sharing the prefix must NOT take the parked
    # record (its rows belong to SID's conversation)...
    _, other = _turn(eng, chain, session="intruder")
    # ...and anonymous traffic must not either
    eng3 = _mk(model, params, dfa)
    _turn1_parked(eng3)
    _, anon = _turn(eng3, chain)
    _, want = _turn(_mk(model, params, dfa), chain)
    assert other == want and anon == want
    assert SID in eng.session_slots()  # record survived the foreigner


def test_store_with_real_engine_full_cycle(setup, tmp_path):
    """SessionStore driving the real engine end to end: park ->
    idle-demote -> spill -> store death -> rehydrate on a fresh
    engine+store -> byte-identical turn 2."""
    model, params, dfa = setup
    cold = _mk(model, params, dfa)
    chain = P1 + _turn(cold, P1)[1] + P2
    _, want = _turn(_mk(model, params, dfa), chain)

    eng = _mk(model, params, dfa)
    store = SessionStore(eng, spill_dir=str(tmp_path),
                         device_idle_s=1.0, host_idle_s=1.0)
    s, out1 = _turn(eng, P1, session=SID)
    eng.park_session(s, SID, len(out1))
    store.note_parked(SID, s, 0.0)
    store.tick(2.0)
    store.tick(5.0)
    assert store.stats()["disk"] == 1
    del store, eng

    eng2 = _mk(model, params, dfa)
    store2 = SessionStore(eng2, spill_dir=str(tmp_path))
    assert store2.prepare(SID, 0.0) == "disk"
    _, got = _turn(eng2, chain, session=SID)
    assert got == want


# -- server surface --------------------------------------------------------


import http.client  # noqa: E402
import json  # noqa: E402

from tpu_k8s_device_plugin.workloads.migrate import (  # noqa: E402
    MIGRATE_CONTENT_TYPE,
)
from tpu_k8s_device_plugin.workloads.server import EngineServer  # noqa: E402


def _post_raw(port, path, body, ctype):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", path, body, {"Content-Type": ctype})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _gen(port, tokens, sid=None):
    payload = {"tokens": list(tokens), "max_new_tokens": 6,
               "stream": False}
    if sid is not None:
        payload["session_id"] = sid
    status, body = _post_raw(port, "/generate",
                             json.dumps(payload), "application/json")
    if status != 200:
        return status, None
    return status, json.loads(body.decode().strip())["tokens"]


def _statz(port):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", "/statz")
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


@pytest.fixture()
def session_servers(setup, tmp_path):
    model, params, dfa = setup
    srvs = []
    for i in range(2):
        eng = ServingEngine(model, params, n_slots=3, chunk=8,
                            auto_prefix_min=4, kv_paging=True)
        srv = EngineServer(eng, max_new_tokens=6, window=4,
                           session_tier=True,
                           session_dir=str(tmp_path / f"r{i}"))
        srv.start(host="127.0.0.1", port=0)
        srvs.append(srv)
    yield srvs
    for s in srvs:
        s.stop()


def test_server_warm_hit_and_promote_fault_stays_200(session_servers):
    a, _ = session_servers
    st, out1 = _gen(a.port, P1, "s1")
    assert st == 200
    chain = P1 + out1 + P2
    st, warm = _gen(a.port, chain, "s1")
    assert st == 200
    tiers = _statz(a.port)["kv_tiers"]
    assert tiers["hits"]["device"] >= 1
    assert tiers["device"] >= 1
    # forced promotion fault: the request must still answer 200 with
    # the same bytes — tiering failure degrades to transparent
    # re-prefill, never a 5xx
    faults.install("kv.promote:error:1", seed=0)
    try:
        st, again = _gen(a.port, chain, "s1")
    finally:
        faults.uninstall()
    assert st == 200
    assert again == warm


def test_server_session_moves_across_replicas(session_servers):
    a, b = session_servers
    st, out1 = _gen(a.port, P1, "mv")
    assert st == 200
    chain = P1 + out1 + P2
    # oracle from the untouched replica before any session lands there
    st, want = _gen(b.port, chain)
    assert st == 200
    # single-owner move: export from a, import into b
    st, payload = _post_raw(a.port, "/session/export",
                            json.dumps({"session_id": "mv"}),
                            "application/json")
    assert st == 200
    st, _ = _post_raw(a.port, "/session/export",
                      json.dumps({"session_id": "mv"}),
                      "application/json")
    assert st == 404  # the local copy moved out
    st, body = _post_raw(b.port, "/session/import", payload,
                         MIGRATE_CONTENT_TYPE)
    assert st == 200
    assert json.loads(body)["session"] == sid_hash("mv")
    assert _statz(b.port)["kv_tiers"]["host"] == 1
    # the moved conversation warm-resumes on b, byte-identically
    st, got = _gen(b.port, chain, "mv")
    assert st == 200
    assert got == want
    assert _statz(b.port)["kv_tiers"]["hits"]["host"] >= 1
    # garbage payload is a 400, not a crash
    st, _ = _post_raw(b.port, "/session/import", b"junk",
                      MIGRATE_CONTENT_TYPE)
    assert st == 400
