"""Elastic slices: degraded-mode reshape instead of demote-all.

State-machine level: with a reshape grace configured, an unhealthy
verdict opens a bounded window — recovery inside it cancels (the
original generation holds, demote-all semantics meanwhile), expiry
evicts the still-unhealthy members and re-forms the survivors into a
smaller valid slice under the next generation, with contiguous ranks in
the same deterministic coords-then-hostname order, a ``reshaped_from``
lineage, and crash-safe persistence.  A returning member joins the NEXT
generation, never resurrecting the old one.  With the default grace of
0, behavior is bit-for-bit the old demote-all (tests/test_slice.py runs
unchanged against it).

Client/gRPC level: the survivor adopts the new generation atomically
and re-emits the TPU_WORKER_ID/TPU_WORKER_HOSTNAMES/JAX_* identity
contract for the new shape; an evicted host answers standalone health
(overlay None) and rejoins the next generation once locally healthy;
the transition is journaled and metered through the obs machinery.
"""

import json
import threading
import time

import pytest

from tools.promlint import lint
from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.slice import (
    SliceClient,
    SliceCoordinator,
    SliceMetrics,
    SliceState,
    load_membership,
)
from tpu_k8s_device_plugin.types import constants
from tpu_k8s_device_plugin.workloads.checkpoint import ReshapeSignal

_JAX_PORT = 8476


def _form_three(state, now=0.0):
    for i, h in enumerate(("host-a", "host-b", "host-c")):
        state.join(h, coords=(i,), chip_count=8, session=f"{h}-s0",
                   now=now)
    assert state.membership is not None
    return state.membership


class TestStateMachine:
    def test_default_grace_preserves_demote_all(self):
        """grace 0 (the default): a member unhealthy forever demotes the
        slice forever — no eviction, no new generation, the bit-for-bit
        pre-reshape contract."""
        s = SliceState(2, _JAX_PORT, heartbeat_timeout_s=5.0)
        s.join("host-a", coords=(0,), now=0.0)
        s.join("host-b", coords=(1,), now=0.0)
        gen1 = s.membership
        for t in range(10, 1000, 50):
            v = s.heartbeat("host-a", healthy=True, now=float(t))
            assert not v.slice_healthy
            assert v.unhealthy_hostnames == ["host-b"]
        assert s.membership == gen1
        assert s.membership.generation == 1

    def test_reshape_after_grace_expiry(self, tmp_path):
        path = str(tmp_path / "membership.json")
        s = SliceState(3, _JAX_PORT, state_path=path,
                       heartbeat_timeout_s=5.0, reshape_grace_s=3.0)
        gen1 = _form_three(s)
        assert not gen1.degraded and gen1.reshaped_from == ()
        # host-c goes silent; the survivors keep beating
        v = s.heartbeat("host-a", True, now=6.0)   # window opens
        assert not v.slice_healthy, "demote-all holds inside the window"
        v = s.heartbeat("host-b", True, now=8.0)   # still inside grace
        assert not v.slice_healthy
        assert v.membership.generation == 1
        v = s.heartbeat("host-a", True, now=9.5)   # grace expired
        assert v.slice_healthy, "survivors re-promoted after the reshape"
        m = s.membership
        assert m.generation == 2
        assert m.hostnames == ("host-a", "host-b")
        assert m.rank_of("host-a") == 0 and m.rank_of("host-b") == 1
        assert m.coordinator_address == f"host-a:{_JAX_PORT}"
        assert m.reshaped_from == (gen1.slice_id,)
        assert m.degraded
        # crash-safe: the state file carries the reshaped generation
        assert load_membership(path) == m

    def test_flap_back_inside_grace_cancels(self):
        metrics = SliceMetrics()
        s = SliceState(2, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=10.0, metrics=metrics)
        s.join("host-a", coords=(0,), now=0.0)
        s.join("host-b", coords=(1,), now=0.0)
        gen1 = s.membership
        v = s.heartbeat("host-a", True, now=6.0)   # b stale, window opens
        assert not v.slice_healthy
        v = s.heartbeat("host-b", True, now=8.0)   # flaps back in grace
        assert v.slice_healthy
        assert s.membership == gen1, "original generation holds"
        samples = obs.parse_exposition(metrics.registry.render())
        cancelled = [val for n, lab, val in samples
                     if n == "tpu_slice_reshape_total"
                     and lab.get("outcome") == "cancelled"]
        assert cancelled == [1.0]
        assert not [val for n, lab, val in samples
                    if n == "tpu_slice_reshape_total"
                    and lab.get("outcome") == "reshaped"]

    def test_evicted_member_rejoins_next_generation(self):
        s = SliceState(3, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=3.0)
        gen1 = _form_three(s)
        s.heartbeat("host-a", True, now=6.0)   # window opens
        s.heartbeat("host-b", True, now=8.0)   # b stays fresh
        s.heartbeat("host-a", True, now=9.5)   # expiry evicts only c
        gen2 = s.membership
        assert gen2.generation == 2 and gen2.degraded
        assert gen2.hostnames == ("host-a", "host-b")
        # the evicted member returns: next generation, not the old one
        res = s.join("host-c", coords=(2,), chip_count=8,
                     session="host-c-reborn", now=12.0)
        assert res.formed and res.rank == 2
        gen3 = s.membership
        assert gen3.generation == 3
        assert gen3.hostnames == ("host-a", "host-b", "host-c")
        assert gen3.reshaped_from == (gen1.slice_id, gen2.slice_id)
        assert not gen3.degraded, "back at full strength"

    def test_no_survivors_keeps_demote_all(self):
        metrics = SliceMetrics()
        s = SliceState(2, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=1.0, metrics=metrics)
        s.join("host-a", coords=(0,), now=0.0)
        s.join("host-b", coords=(1,), now=0.0)
        gen1 = s.membership
        # BOTH report unhealthy: nothing to re-form onto
        s.heartbeat("host-a", False, reason="wedged", now=1.0)
        s.heartbeat("host-b", False, reason="wedged", now=1.5)
        v = s.heartbeat("host-a", False, reason="wedged", now=4.0)
        assert not v.slice_healthy
        assert s.membership == gen1
        samples = obs.parse_exposition(metrics.registry.render())
        assert [val for n, lab, val in samples
                if n == "tpu_slice_reshape_total"
                and lab.get("outcome") == "no_survivors"] == [1.0]

    def test_reshaped_state_recovers_after_coordinator_crash(
        self, tmp_path
    ):
        path = str(tmp_path / "membership.json")
        s = SliceState(3, _JAX_PORT, state_path=path,
                       heartbeat_timeout_s=5.0, reshape_grace_s=3.0)
        _form_three(s)
        s.heartbeat("host-a", True, now=6.0)
        s.heartbeat("host-b", True, now=8.0)
        s.heartbeat("host-a", True, now=9.5)
        gen2 = s.membership
        # coordinator crash: the revived one adopts the RESHAPED slice
        revived = SliceState(3, _JAX_PORT, state_path=path,
                             heartbeat_timeout_s=5.0, reshape_grace_s=3.0)
        assert revived.membership == gen2
        # the evicted set is persisted too: the revived coordinator
        # recognizes the returnee instead of treating it as a stranger
        assert revived._evicted == {"host-c"}
        res = revived.join("host-c", coords=(2,), chip_count=8,
                           session="host-c-reborn", now=0.0)
        assert res.formed and res.rank == 2
        assert revived.membership.generation == gen2.generation + 1
        assert not revived.membership.degraded

    def test_returnee_rejected_when_seat_refilled(self):
        """A replacement host fills the degraded seat; the originally-
        evicted member then returns: it must be rejected (the slice is
        back at full strength) — over-admitting would hand out more
        ranks than the physical topology holds and generation-bump
        (checkpoint-restart) every workload on a healthy slice."""
        s = SliceState(3, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=3.0)
        _form_three(s)
        s.heartbeat("host-a", True, now=6.0)   # window opens on host-c
        s.heartbeat("host-b", True, now=8.0)
        s.heartbeat("host-a", True, now=9.5)   # expiry evicts host-c
        assert s.membership.hostnames == ("host-a", "host-b")
        # a fresh replacement node repairs the degraded seat
        res = s.join("host-z", coords=(2,), chip_count=8,
                     session="z-s0", now=10.0)
        assert res.formed
        gen3 = s.membership
        assert gen3.hostnames == ("host-a", "host-b", "host-z")
        assert not gen3.degraded
        # the evicted original returns to a full slice: rejected, and
        # the running generation holds
        res = s.join("host-c", coords=(2,), chip_count=8,
                     session="host-c-reborn", now=12.0)
        assert res.error and "not a member" in res.error
        assert s.membership == gen3

    def test_late_blip_gets_full_grace(self):
        """Per-member windows: a member that blips just before another
        member's window expires is NOT swept into that eviction — a
        single global window would grant it near-zero individual
        grace."""
        s = SliceState(3, _JAX_PORT, reshape_grace_s=3.0)
        _form_three(s)
        s.heartbeat("host-c", False, reason="wedged", now=0.0)
        s.heartbeat("host-b", False, reason="blip", now=2.5)
        v = s.heartbeat("host-a", True, now=3.5)  # c expires; b survives
        m = s.membership
        assert m.generation == 2
        assert m.hostnames == ("host-a", "host-b"), \
            "the late-blipping member keeps its own full grace window"
        assert not v.slice_healthy
        assert v.unhealthy_hostnames == ["host-b"]
        # b recovers inside ITS window: no second reshape
        v = s.heartbeat("host-b", True, now=4.0)
        assert v.slice_healthy
        assert s.membership.generation == 2

    def test_client_save_preserves_coordinator_keys(self, tmp_path):
        """On the rendezvous host the coordinator's SliceState and the
        local SliceClient share one --slice-state-file: a client-side
        save (no coordinator extras) must preserve member_coords and
        the evicted set, or a post-crash re-form falls back to
        hostname-sorted ranks and forgets returnees."""
        from tpu_k8s_device_plugin.slice.state import (
            load_evicted,
            load_member_coords,
            save_membership,
        )

        path = str(tmp_path / "membership.json")
        s = SliceState(3, _JAX_PORT, state_path=path,
                       heartbeat_timeout_s=5.0, reshape_grace_s=3.0)
        # ICI mesh order is the REVERSE of hostname order
        s.join("host-a", coords=(2,), chip_count=8, now=0.0)
        s.join("host-b", coords=(1,), chip_count=8, now=0.0)
        s.join("host-c", coords=(0,), chip_count=8, now=0.0)
        gen1 = s.membership
        assert gen1.hostnames == ("host-c", "host-b", "host-a")
        coords = load_member_coords(path)
        assert coords == {"host-a": (2,), "host-b": (1,),
                          "host-c": (0,)}
        # the co-located client adopts and persists the SAME membership
        # without coordinator extras: both keys must survive
        save_membership(path, gen1)
        assert load_member_coords(path) == coords
        # coordinator crashes and revives from the (client-rewritten)
        # file; host-a goes silent and the survivors reshape — ranks
        # must still follow the persisted ICI coords, not hostname sort
        revived = SliceState(3, _JAX_PORT, state_path=path,
                             heartbeat_timeout_s=5.0,
                             reshape_grace_s=3.0)
        revived.heartbeat("host-c", True, now=6.0)
        revived.heartbeat("host-b", True, now=8.0)
        revived.heartbeat("host-c", True, now=9.5)
        m = revived.membership
        assert m.generation == gen1.generation + 1
        assert m.hostnames == ("host-c", "host-b"), \
            "re-form after crash must keep physical mesh order"
        # eviction persisted; a client save still must not clobber it
        assert load_evicted(path) == {"host-a"}
        save_membership(path, m)
        assert load_evicted(path) == {"host-a"}
        assert load_member_coords(path) == {"host-b": (1,),
                                            "host-c": (0,)}

    def test_stranger_still_rejected_on_whole_slice(self):
        """Reshape enabled must NOT open the door for strangers: a full
        healthy slice refuses unknown hosts exactly as before."""
        s = SliceState(2, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=3.0)
        s.join("host-a", coords=(0,), now=0.0)
        s.join("host-b", coords=(1,), now=0.0)
        res = s.join("host-z", session="z-s0", now=1.0)
        assert res.error and "not a member" in res.error
        assert s.membership.generation == 1

    def test_reshape_metrics_render_promlint_clean(self):
        metrics = SliceMetrics()
        s = SliceState(2, _JAX_PORT, heartbeat_timeout_s=5.0,
                       reshape_grace_s=1.0, metrics=metrics)
        s.join("host-a", coords=(0,), now=0.0)
        s.join("host-b", coords=(1,), now=0.0)
        s.heartbeat("host-a", True, now=6.0)
        s.heartbeat("host-a", True, now=8.0)
        assert s.membership.generation == 2
        samples = obs.parse_exposition(metrics.registry.render())
        assert [val for n, lab, val in samples
                if n == "tpu_slice_reshape_total"
                and lab.get("outcome") == "reshaped"] == [1.0]
        assert [val for n, lab, val in samples
                if n == "tpu_slice_reshape_seconds_count"] == [1.0]
        assert lint(metrics.registry.render()) == []


@pytest.fixture
def grace_coordinator(tmp_path):
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    c = SliceCoordinator(
        expected_workers=2,
        bind_address="127.0.0.1:0",
        jax_port=_JAX_PORT,
        state_path=str(tmp_path / "coordinator-membership.json"),
        heartbeat_timeout_s=0.3,
        reshape_grace_s=0.4,
        registry=registry,
        recorder=recorder,
    ).start()
    yield c
    c.stop()


def _client(coordinator, tmp_path, name, rank_coord, health=None,
            recorder=None, registry=None):
    return SliceClient(
        rendezvous_address=f"127.0.0.1:{coordinator.port}",
        hostname=name,
        coords=(rank_coord,),
        chip_count=8,
        state_path=str(tmp_path / f"{name}-membership.json"),
        local_health_fn=health,
        recorder=recorder,
        registry=registry,
        join_backoff_initial_s=0.05,
        join_backoff_max_s=0.2,
    )


def _join_pair(a, b):
    with_threads = []
    for c in (b, a):
        t = threading.Thread(target=c.join, args=(15.0,))
        t.start()
        with_threads.append(t)
    for t in with_threads:
        t.join(timeout=20.0)
        assert not t.is_alive()


def _beat_until(client, predicate, timeout_s=10.0, period_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        client.heartbeat_now()
        if predicate():
            return
        time.sleep(period_s)
    raise AssertionError("condition not reached within "
                         f"{timeout_s}s; membership={client.membership}")


def test_grpc_reshape_end_to_end(grace_coordinator, tmp_path):
    """A member dies; the survivor adopts the reshaped generation over
    real gRPC, re-emits the identity contract for the new shape, flips
    back healthy, and every hop is journaled."""
    registry = obs.Registry()
    recorder = obs.FlightRecorder(registry=registry)
    a = _client(grace_coordinator, tmp_path, "host-a", 0,
                recorder=recorder, registry=registry)
    b = _client(grace_coordinator, tmp_path, "host-b", 1)
    signal = ReshapeSignal(str(tmp_path / "host-a-membership.json"),
                           generation=0)
    try:
        _join_pair(a, b)
        gen1 = a.membership
        assert gen1.num_workers == 2
        signal.baseline = gen1.generation
        a.set_reshape_callback(signal.fire)
        a.heartbeat_now()
        b.heartbeat_now()
        assert a.health_overlay() == (True, [])
        env1 = a.slice_env()
        assert env1[constants.ENV_TPU_SLICE_GENERATION] == "1"
        assert env1[constants.ENV_JAX_NUM_PROCESSES] == "2"

        b.stop()     # the member dies: heartbeats cease
        # demote-all first (the member might return), then the reshape
        _beat_until(a, lambda: a.membership.generation > gen1.generation)
        m = a.membership
        assert m.generation == gen1.generation + 1
        assert m.hostnames == ("host-a",)
        assert m.reshaped_from == (gen1.slice_id,)
        assert m.degraded

        # identity contract re-emitted for the new shape
        env2 = a.slice_env()
        assert env2[constants.ENV_TPU_WORKER_ID] == "0"
        assert env2[constants.ENV_TPU_WORKER_HOSTNAMES] == "host-a"
        assert env2[constants.ENV_JAX_NUM_PROCESSES] == "1"
        assert env2[constants.ENV_JAX_PROCESS_ID] == "0"
        assert env2[constants.ENV_TPU_SLICE_GENERATION] == str(
            m.generation)
        assert env2[constants.ENV_JAX_COORDINATOR_ADDRESS] == \
            f"host-a:{_JAX_PORT}"

        # the survivor's devices flip back healthy in the next frame
        _beat_until(a, lambda: a.health_overlay() == (True, []))

        # the workload-side hook fired with the new membership
        assert signal.triggered
        assert signal.check().generation == m.generation

        # journaled on both sides
        coord_events = grace_coordinator.recorder.events(
            name="tpu_slice_reshaped")
        assert coord_events
        assert coord_events[-1]["attrs"]["generation"] == m.generation
        assert coord_events[-1]["attrs"]["degraded"] is True
        adopted = [e for e in recorder.events(
            name="tpu_slice_membership_adopted")
            if e["attrs"].get("generation") == m.generation]
        assert adopted and adopted[-1]["attrs"]["workers"] == 1
        # client-side transition counter moved
        samples = obs.parse_exposition(registry.render())
        assert [v for n, lab, v in samples
                if n == "tpu_slice_membership_transitions_total"
                and lab.get("kind") == "reshape_adopted"] == [1.0]
        # the survivor's local state file carries the new generation
        # (what the labeller and ReshapeSignal read)
        on_disk = load_membership(str(
            tmp_path / "host-a-membership.json"))
        assert on_disk == m
    finally:
        a.stop()
        b.stop()


def test_evicted_client_standalone_then_rejoins(grace_coordinator,
                                                tmp_path):
    """A wedged member is evicted: it learns the eviction on its next
    heartbeat, answers standalone health (overlay None — its devices
    must not inherit a verdict about a slice it left), and rejoins the
    NEXT generation the moment its chips recover."""
    health = {"ok": True}
    a = _client(grace_coordinator, tmp_path, "host-a", 0)
    b = _client(grace_coordinator, tmp_path, "host-b", 1,
                health=lambda: (health["ok"], "" if health["ok"]
                                else "chips wedged"))
    try:
        _join_pair(a, b)
        gen1 = a.membership
        health["ok"] = False       # b's chips wedge
        b.heartbeat_now()
        # survivors beat until the grace window evicts b
        _beat_until(a, lambda: a.membership.generation > gen1.generation)
        gen2 = a.membership
        assert gen2.hostnames == ("host-a",)

        # b keeps beating (still wedged): learns the eviction, stays out
        b.heartbeat_now()
        assert b.membership.rank_of("host-b") is None
        assert b.health_overlay() is None, (
            "evicted host must advertise standalone health, not the "
            "old slice verdict")
        assert b.slice_env() == {}

        # chips recover -> the very next heartbeat rejoins, next gen
        health["ok"] = True
        _beat_until(
            b, lambda: b.membership.rank_of("host-b") is not None)
        gen3 = b.membership
        assert gen3.generation == gen2.generation + 1
        assert gen3.hostnames == ("host-a", "host-b")
        assert gen3.reshaped_from == (gen1.slice_id, gen2.slice_id)
        assert not gen3.degraded
        # the survivor learns the regrown generation on its next beat
        _beat_until(a, lambda: a.membership == gen3)
        assert a.slice_env()[constants.ENV_JAX_NUM_PROCESSES] == "2"
    finally:
        a.stop()
        b.stop()


def test_membership_file_round_trips_lineage(tmp_path):
    """The crash-safe file carries lineage/degraded, and pre-reshape
    files (no such keys) still load — forward compatibility both ways."""
    from tpu_k8s_device_plugin.slice import Membership, save_membership

    path = str(tmp_path / "m.json")
    m = Membership(
        slice_id="abc", generation=4, hostnames=("h0", "h1"),
        coordinator_address="h0:8476",
        reshaped_from=("x1", "x2"), degraded=True,
    )
    save_membership(path, m)
    assert load_membership(path) == m
    # a pre-reshape writer's file: no lineage keys
    with open(path, "w") as f:
        json.dump({"version": 1, "slice_id": "old", "generation": 1,
                   "hostnames": ["h0"],
                   "coordinator_address": "h0:8476"}, f)
    old = load_membership(path)
    assert old is not None
    assert old.reshaped_from == () and old.degraded is False
