"""Pipeline-parallelism tests on the virtual 8-device mesh: exact
forward/backward agreement with the unpipelined oracle, DP×PP
composition, and stage-sharding invariants."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from tpu_k8s_device_plugin.workloads.pipeline import (
    make_pipeline,
    stack_layer_params,
)

N_LAYERS, D = 8, 16


def mlp_layer(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def build_params(rng=0):
    rs = np.random.RandomState(rng)
    per_layer = [
        {
            "w": jnp.asarray(rs.randn(D, D) * 0.3, jnp.float32),
            "b": jnp.asarray(rs.randn(D) * 0.1, jnp.float32),
        }
        for _ in range(N_LAYERS)
    ]
    return per_layer, stack_layer_params(per_layer)


def sequential_apply(stacked, x):
    """The unpipelined oracle: scan the full layer stack."""
    def body(h, p):
        return mlp_layer(p, h), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


def pp_mesh(data=2, pipe=4):
    grid = mesh_utils.create_device_mesh((data, pipe))
    return Mesh(grid, axis_names=("data", "pipe"))


class TestPipelineForward:
    @pytest.mark.parametrize("n_micro", [1, 4, 6])
    def test_matches_sequential_oracle(self, n_micro):
        _, stacked = build_params()
        mesh = pp_mesh()
        x = jnp.asarray(
            np.random.RandomState(1).randn(n_micro, 4, D), jnp.float32
        )
        apply, params_sh, in_sh = make_pipeline(
            mesh, mlp_layer, stacked
        )
        got = apply(params_sh, jax.device_put(x, in_sh))
        want = jax.vmap(functools.partial(sequential_apply, stacked))(x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_stage_params_are_sharded(self):
        _, stacked = build_params()
        mesh = pp_mesh()
        _, params_sh, _ = make_pipeline(mesh, mlp_layer, stacked)
        w = params_sh["w"]
        assert tuple(w.sharding.spec)[0] == "pipe"
        assert (
            w.addressable_shards[0].data.shape[0]
            == N_LAYERS // mesh.shape["pipe"]
        )

    def test_batch_rides_data_axis(self):
        """DP×PP: the microbatch batch dim stays sharded on 'data'."""
        _, stacked = build_params()
        mesh = pp_mesh(data=2, pipe=4)
        x = jnp.asarray(
            np.random.RandomState(2).randn(4, 8, D), jnp.float32
        )
        apply, params_sh, in_sh = make_pipeline(
            mesh, mlp_layer, stacked
        )
        placed = jax.device_put(x, in_sh)
        assert (
            placed.addressable_shards[0].data.shape[1]
            == x.shape[1] // mesh.shape["data"]
        )
        got = apply(params_sh, placed)
        want = jax.vmap(functools.partial(sequential_apply, stacked))(x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_rejects_indivisible_layer_count(self):
        per_layer, _ = build_params()
        stacked = stack_layer_params(per_layer[:6])  # 6 layers, 4 stages
        with pytest.raises(ValueError, match="not divisible"):
            make_pipeline(pp_mesh(), mlp_layer, stacked)


class TestPipelineBackward:
    def test_gradients_match_sequential_oracle(self):
        """jax.grad transposes the forward schedule into the backward
        pipeline; gradients must equal the unpipelined model's exactly."""
        _, stacked = build_params()
        mesh = pp_mesh()
        x = jnp.asarray(
            np.random.RandomState(3).randn(4, 4, D), jnp.float32
        )
        apply, params_sh, in_sh = make_pipeline(
            mesh, mlp_layer, stacked
        )
        placed = jax.device_put(x, in_sh)

        def piped_loss(p):
            return jnp.sum(apply(p, placed) ** 2)

        def seq_loss(p):
            out = jax.vmap(functools.partial(sequential_apply, p))(x)
            return jnp.sum(out ** 2)

        got = jax.grad(piped_loss)(params_sh)
        want = jax.grad(seq_loss)(stacked)
        jax.tree_util.tree_map(
            lambda g, w: np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=1e-4, rtol=1e-4
            ),
            got, want,
        )
