"""Randomized scheduler fuzz: the engine's WHOLE feature surface under
random interleavings.

Every request's output is independent of its neighbors, the admission
order, and which decode APIs the scheduler happened to mix (step /
run_scan / spec rounds / jump rounds) — that is the engine's central
promise, and each feature's tests pin it pairwise.  This fuzz drives
the product of features at once: random admits (greedy, seeded
sampling, grammar constraints, stop tokens, min_tokens, ignore_eos)
into random decode-API interleavings with random releases, then checks
every retired request token-for-token against a SOLO single-slot
engine running the same request alone."""

import os
import random

import jax
import jax.numpy as jnp
import pytest

from tpu_k8s_device_plugin.workloads.grammar import (
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
EOS = 0
MAX_LEN = 64
PATTERN = "(AB|CD)+E"  # bytes < 96


def _init(model, seed):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def models():
    target = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return target, _init(target, 0), dfa


def _mk_engine(model, params, dfa, n_slots, max_new):
    return ServingEngine(model, params, n_slots=n_slots, eos_id=EOS,
                         max_new_tokens=max_new, chunk=4,
                         auto_prefix_min=4, draft="ngram", gamma=3,
                         grammar=dfa, jump_len=4)


def _rand_request(rnd):
    """One random request spec (kwargs for admit) from the feature
    product.  Greedy or SEEDED sampling only — both are solo-
    reproducible by design (a seeded slot's chain ignores neighbors),
    which is exactly the property the fuzz verifies."""
    kw = {}
    prompt = [rnd.randrange(1, CFG["vocab"])
              for _ in range(rnd.randint(2, 8))]
    if rnd.random() < 0.35:
        kw["grammar"] = True
        prompt = [70, 71, 72][:rnd.randint(1, 3)]
    if rnd.random() < 0.4:
        # independent of the grammar draw: constrained SEEDED sampling
        # (grammar mask + Gumbel pick + per-slot seed chain) is part
        # of the product under test
        kw["temperature"] = rnd.choice([0.7, 1.0])
        kw["seed"] = rnd.randrange(1000)
        if rnd.random() < 0.5:
            kw["top_k"] = rnd.choice([8, 32])
    if rnd.random() < 0.3:
        kw["stop"] = [rnd.randrange(1, CFG["vocab"])]
    if rnd.random() < 0.25:
        kw["min_tokens"] = rnd.randint(1, 3)
    if rnd.random() < 0.15:
        kw["ignore_eos"] = True
    return prompt, kw


def test_fused_boundary_fuzz_matches_unfused(models):
    """Mid-window finishes under the fused carry: random mixes of
    eos / stop-set / budget cuts landing INSIDE run_scan windows must
    leave the fused engine byte-identical to the unfused one — same
    outputs, finish reasons, logprob records, and draw chains (the
    key-stream contract a later admission replays)."""
    model, params, dfa = models
    seed = int(os.environ.get("ENGINE_FUZZ_SEED") or 2026)

    def arm(fused, trial):
        rnd = random.Random(seed * 7919 + trial)
        max_new = rnd.randint(4, 7)
        eng = ServingEngine(model, params, n_slots=3, eos_id=EOS,
                            max_new_tokens=max_new, chunk=4,
                            auto_prefix_min=4, grammar=dfa,
                            logprobs_k=3, fused_decode=fused)
        live, done = {}, []
        for _ in range(50):
            op = rnd.random()
            if op < 0.4 and eng.free_slots():
                prompt, kw = _rand_request(rnd)
                if rnd.random() < 0.3:
                    kw["logprobs"] = rnd.randint(1, 3)
                if rnd.random() < 0.5:
                    # widen the stop surface so stop boundaries land
                    # mid-window often (greedy tails repeat tokens)
                    kw["stop"] = sorted(set(
                        (kw.get("stop") or [])
                        + [rnd.randrange(1, CFG["vocab"])
                           for _ in range(3)]))
                s = eng.admit(prompt, **kw)
                live[s] = (prompt, kw)
            elif op < 0.85 and any(eng.active):
                n = rnd.randint(1, 5)
                if all(eng.lens[s] + n <= MAX_LEN
                       for s in range(3) if eng.active[s]):
                    eng.run_scan(n)
            elif op < 0.95 and live:
                s = rnd.choice(list(live))
                del live[s]
                eng.release(s)
            for s in list(live):
                if eng.finished(s):
                    prompt, kw = live.pop(s)
                    done.append((prompt, kw, eng.output(s),
                                 eng.finish_reason(s),
                                 eng.token_logprobs(s)))
        return done, eng._draws, list(eng._slot_draws)

    boundary = retired = 0
    # 2 trials, not 3: each trial is two full 50-op engine runs, and
    # the default seed's first two already retire 30+ requests with
    # mid-window boundaries in both — the third bought tier-1 wall
    # time, not coverage (ENGINE_FUZZ_SEED sweeps buy breadth)
    for trial in range(2):
        base = arm(False, trial)
        got = arm(True, trial)
        assert got == base, f"fused diverged from unfused (trial {trial})"
        retired += len(base[0])
        boundary += sum(1 for d in base[0] if d[3] in ("eos", "stop"))
    # the fuzz must actually have exercised mid-window boundaries, not
    # just end-of-budget cuts (calibrated for the default seed; swept
    # seeds only need SOME retirements)
    if seed == 2026:
        assert retired >= 8 and boundary >= 1, (retired, boundary)
    else:
        assert retired >= 1, retired


def test_random_interleavings_match_solo_oracles(models):
    model, params, dfa = models
    # deterministic in the suite; ENGINE_FUZZ_SEED sweeps new
    # interleavings out-of-band (a standing offline bug hunt)
    seed = int(os.environ.get("ENGINE_FUZZ_SEED") or 2026)
    rnd = random.Random(seed)
    checked = 0
    for trial in range(3):
        max_new = rnd.randint(5, 8)
        eng = _mk_engine(model, params, dfa, n_slots=3, max_new=max_new)
        live = {}     # slot -> (prompt, kwargs)
        done = []     # (prompt, kwargs, output, reason)
        for _ in range(40):
            op = rnd.random()
            if op < 0.35 and eng.free_slots():
                prompt, kw = _rand_request(rnd)
                s = eng.admit(prompt, **kw)
                live[s] = (prompt, kw)
            elif op < 0.5:
                eng.step()
            elif op < 0.7:
                n = rnd.randint(1, 4)
                if all(eng.lens[s] + n <= MAX_LEN
                       for s in range(3) if eng.active[s]) and \
                        any(eng.active):
                    eng.run_scan(n)
            elif op < 0.8 and eng.spec_ready():
                eng.spec_round()
            elif op < 0.9 and eng.forced_pending():
                eng.jump_round()
            elif op < 0.95 and live:
                # abandon a random in-flight request (release path);
                # its slot may be reused immediately
                s = rnd.choice(list(live))
                del live[s]
                eng.release(s)
            # harvest retirements
            for s in list(live):
                if eng.finished(s):
                    prompt, kw = live.pop(s)
                    done.append((prompt, kw, eng.output(s),
                                 eng.finish_reason(s)))
        # drain what's left
        for _ in range(30):
            if not any(eng.active):
                break
            eng.step()
            for s in list(live):
                if eng.finished(s):
                    prompt, kw = live.pop(s)
                    done.append((prompt, kw, eng.output(s),
                                 eng.finish_reason(s)))
        # every retired request must match its SOLO run exactly
        for prompt, kw, out, reason in done:
            solo = ServingEngine(model, params, n_slots=1, eos_id=EOS,
                                 max_new_tokens=max_new, chunk=4,
                                 grammar=dfa)
            s = solo.admit(prompt, **kw)
            solo.run(max_new + 4)
            assert solo.output(s) == out, (prompt, kw, trial)
            assert solo.finish_reason(s) == reason, (prompt, kw)
            checked += 1
    # the fuzz must actually have exercised retirements (calibrated
    # for the default seed; swept seeds only need SOME coverage)
    assert checked >= (10 if seed == 2026 else 1), checked
