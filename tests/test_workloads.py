"""Workload tests on the virtual 8-device CPU mesh (tiny shapes).

Covers what the reference never could (its workloads are opaque container
images, SURVEY.md §2.1 #19): the AlexNet-JAX model trains, the sharded
train step compiles and executes over a data×model mesh, and the driver
entry points stay importable and jittable.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu_k8s_device_plugin.workloads.alexnet import (
    create_train_state,
    space_to_depth,
    synthetic_batch,
    train_step,
)
from tpu_k8s_device_plugin.workloads.parallel import (
    make_mesh,
    make_sharded_train_step,
    tree_shardings,
)

import functools


TINY = dict(image_size=64, num_classes=16)


def test_alexnet_trains_single_device():
    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(rng, batch_size=4, **TINY)
    params, opt_state, tx = state["params"], state["opt_state"], state["tx"]
    images, labels = synthetic_batch(rng, 4, **TINY)
    step = jax.jit(functools.partial(train_step, model, tx))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, images, labels)
        losses.append(float(loss))
    assert all(jnp.isfinite(l) for l in losses)
    # same synthetic batch every step: loss must go down
    assert losses[-1] < losses[0]


def test_space_to_depth_conv_is_exact_oracle():
    """The MXU-friendly formulation is the *same computation*: any
    11x11/stride-4 conv equals a 3x3/stride-1 conv on the space-to-depth
    input with the kernel taps rearranged (zero-padded 12x12 -> blocks).
    Verified against lax.conv directly, f32, VALID padding on both sides
    so the tap alignment is unambiguous."""
    rng = jax.random.PRNGKey(7)
    k1, k2 = jax.random.split(rng)
    B, H, W, C, F = 2, 32, 32, 3, 5
    x = jax.random.normal(k1, (B, H, W, C), jnp.float32)
    w11 = jax.random.normal(k2, (11, 11, C, F), jnp.float32)

    dn = jax.lax.conv_dimension_numbers(
        x.shape, w11.shape, ("NHWC", "HWIO", "NHWC")
    )
    ref = jax.lax.conv_general_dilated(
        x, w11, window_strides=(4, 4), padding="VALID", dimension_numbers=dn
    )

    # rearrange: w3[ki,kj,(i4*4+j4)*C+c,f] = pad12(w11)[ki*4+i4, kj*4+j4, c, f]
    w12 = jnp.pad(w11, ((0, 1), (0, 1), (0, 0), (0, 0)))
    w3 = (
        w12.reshape(3, 4, 3, 4, C, F)        # (ki, i4, kj, j4, c, f)
        .transpose(0, 2, 1, 3, 4, 5)          # (ki, kj, i4, j4, c, f)
        .reshape(3, 3, 16 * C, F)
    )
    xs = space_to_depth(x)
    dn3 = jax.lax.conv_dimension_numbers(
        xs.shape, w3.shape, ("NHWC", "HWIO", "NHWC")
    )
    got = jax.lax.conv_general_dilated(
        xs, w3, window_strides=(1, 1), padding="VALID", dimension_numbers=dn3
    )
    assert ref.shape == got.shape == (B, 6, 6, F)
    assert jnp.allclose(ref, got, atol=1e-4, rtol=1e-4)


def test_alexnet_s2d_trains():
    rng = jax.random.PRNGKey(0)
    model, state = create_train_state(rng, batch_size=4, s2d=True, **TINY)
    params, opt_state, tx = state["params"], state["opt_state"], state["tx"]
    images, labels = synthetic_batch(rng, 4, s2d=True, **TINY)
    assert images.shape == (4, 16, 16, 48)
    step = jax.jit(functools.partial(train_step, model, tx))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, images, labels)
        losses.append(float(loss))
    assert all(jnp.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_make_mesh_shapes():
    mesh = make_mesh(jax.devices())
    assert mesh.shape == {"data": 4, "model": 2}
    dp = make_mesh(jax.devices(), model_parallel=1)
    assert dp.shape == {"data": 8, "model": 1}
    with pytest.raises(ValueError):
        make_mesh(jax.devices()[:6], model_parallel=4)


def test_dense_kernels_are_model_sharded():
    rng = jax.random.PRNGKey(0)
    _, state = create_train_state(rng, batch_size=4, **TINY)
    mesh = make_mesh(jax.devices())
    sh = tree_shardings(mesh, state["params"])
    dense0 = sh["Dense_0"]["kernel"].spec
    conv0 = sh["Conv_0"]["kernel"].spec
    assert tuple(dense0) == (None, "model")
    assert tuple(conv0) == ()


def test_sharded_train_step_runs_and_matches_semantics():
    rng = jax.random.PRNGKey(0)
    mesh = make_mesh(jax.devices())
    batch = mesh.shape["data"] * 2
    model, state = create_train_state(rng, batch_size=batch, **TINY)
    step, params, opt_state, (img_sh, lbl_sh) = make_sharded_train_step(
        model, state["tx"], mesh, state["params"], state["opt_state"]
    )
    images, labels = synthetic_batch(rng, batch, **TINY)
    images = jax.device_put(images, img_sh)
    labels = jax.device_put(labels, lbl_sh)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, images, labels)
        losses.append(float(loss))
    assert all(jnp.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    # params keep their tensor-parallel layout across steps
    k = params["Dense_0"]["kernel"]
    assert tuple(k.sharding.spec) == (None, "model")
    # each shard holds 1/model of the columns
    shard = k.addressable_shards[0].data
    assert shard.shape[1] == k.shape[1] // mesh.shape["model"]


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 1000)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_maybe_init_distributed(monkeypatch):
    """Env-driven multi-host join: no env → no-op; with the
    example/multihost/jobset.yaml env triple set, initialize() gets the
    parsed coordinator/process identity."""
    from tpu_k8s_device_plugin.workloads import bench_main

    for k in (
        "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"
    ):
        monkeypatch.delenv(k, raising=False)
    assert bench_main._maybe_init_distributed() is False

    seen = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: seen.update(kw)
    )
    monkeypatch.setenv(
        "JAX_COORDINATOR_ADDRESS", "alexnet-jax-multihost-0.tpu-slice:8476"
    )
    # partial triple: an actionable error naming the missing vars, not a
    # bare KeyError traceback
    with pytest.raises(SystemExit, match="JAX_NUM_PROCESSES"):
        bench_main._maybe_init_distributed()

    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    assert bench_main._maybe_init_distributed() is True
    assert seen == {
        "coordinator_address": "alexnet-jax-multihost-0.tpu-slice:8476",
        "num_processes": 2,
        "process_id": 1,
    }
