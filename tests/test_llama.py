"""Llama-family (GQA + SwiGLU + big-theta RoPE) through train + serve.

Oracle strategy mirrors test_inference.py: the cached decode engine
must match recompute-from-scratch exactly, and the training/serving
twins must agree on the same parameter tree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    init_cache,
    quantize_lm_params,
)
from tpu_k8s_device_plugin.workloads.transformer import (
    lm_tree_shardings,
    make_lm_mesh,
    repeat_kv,
    split_qkv_heads,
)

CFG = llama.TINY_LLAMA
DT = jnp.float32  # exactness oracles want f32


def _models():
    train = llama.train_model(CFG, dtype=DT)
    serve = llama.decoder(CFG, dtype=DT)
    return train, serve


def _init(model, batch=2, seq=16):
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (batch, seq), 0, CFG.vocab)
    positions = jnp.broadcast_to(
        jnp.arange(seq, dtype=jnp.int32), (batch, seq))
    params = model.init(rng, tokens, positions)["params"]
    return params, tokens, positions


def test_param_tree_has_llama_shapes():
    train, _ = _models()
    params, _, _ = _init(train)
    blk = params["block_0"]
    hd = CFG.head_dim
    assert blk["qkv"]["kernel"].shape == (
        CFG.d_model, (CFG.n_heads + 2 * CFG.n_kv_heads) * hd)
    assert blk["mlp_gate"]["kernel"].shape == (CFG.d_model, CFG.d_ff)
    assert blk["mlp_up"]["kernel"].shape == (CFG.d_model, CFG.d_ff)
    assert blk["mlp_down"]["kernel"].shape == (CFG.d_ff, CFG.d_model)


def test_train_serve_param_trees_identical():
    train, serve = _models()
    p_train, tokens, positions = _init(train)
    p_serve = serve.init(
        jax.random.PRNGKey(0), tokens, positions, decode=False)["params"]
    t1 = jax.tree_util.tree_structure(p_train)
    t2 = jax.tree_util.tree_structure(p_serve)
    assert t1 == t2
    for a, b in zip(jax.tree_util.tree_leaves(p_train),
                    jax.tree_util.tree_leaves(p_serve)):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_prefill_matches_training_forward():
    train, serve = _models()
    params, tokens, positions = _init(train)
    ref = train.apply({"params": params}, tokens, positions)
    got, _ = serve.apply(
        {"params": params, "cache": init_cache(serve, tokens.shape[0])},
        tokens, positions, decode=False, mutable=["cache"],
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_cached_decode_matches_recompute_oracle():
    train, serve = _models()
    params, tokens, _ = _init(train, batch=2, seq=8)
    out, _ = greedy_generate(serve, params, tokens, n_steps=6)
    # recompute oracle in ONE causal full-length forward (per-step
    # regrowing would compile 6 shapes for the same assertion)
    T_p = tokens.shape[1]
    full = jnp.concatenate([tokens, out.astype(tokens.dtype)], axis=1)
    T = full.shape[1]
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32),
                           (full.shape[0], T))
    logits = train.apply({"params": params}, full, pos)
    want = jnp.argmax(logits[:, T_p - 1:-1, :], axis=-1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_gqa_cache_is_compact():
    _, serve = _models()
    cache = init_cache(serve, batch=2)
    k = cache["block_0"]["cached_k"]
    assert k.shape == (2, CFG.max_len, CFG.n_kv_heads, CFG.head_dim)


def test_repeat_kv_and_split_helpers():
    x = jnp.arange(2 * 4 * 2 * 3, dtype=jnp.float32).reshape(2, 4, 2, 3)
    r = repeat_kv(x, 6)
    assert r.shape == (2, 4, 6, 3)
    # each kv head serves a contiguous group of query heads
    np.testing.assert_array_equal(np.asarray(r[:, :, 0]),
                                  np.asarray(r[:, :, 1]))
    qkv = jnp.arange(1 * 2 * (4 + 2 + 2) * 3,
                     dtype=jnp.float32).reshape(1, 2, 24)
    q, k, v = split_qkv_heads(qkv, 4, 2, 3)
    assert q.shape == (1, 2, 4, 3)
    assert k.shape == (1, 2, 2, 3)
    assert v.shape == (1, 2, 2, 3)


def test_quantized_llama_tree_loads_and_decodes():
    train, _ = _models()
    params, tokens, _ = _init(train, batch=1, seq=8)
    qparams = quantize_lm_params(params)
    blk = qparams["block_0"]
    assert "kernel_int8" in blk["mlp_gate"]
    assert "scale" in blk["mlp_gate"]
    qserve = llama.decoder(CFG, dtype=DT, quantized=True)
    out, _ = greedy_generate(qserve, qparams, tokens, n_steps=4)
    assert out.shape == (1, 4)
    # int8 path must agree closely with the bf16/f32 path on logits;
    # greedy tokens can differ in principle, so compare prefill logits
    serve = llama.decoder(CFG, dtype=DT)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    ref, _ = serve.apply(
        {"params": params, "cache": init_cache(serve, 1)},
        tokens, pos, decode=False, mutable=["cache"])
    got, _ = qserve.apply(
        {"params": qparams, "cache": init_cache(qserve, 1)},
        tokens, pos, decode=False, mutable=["cache"])
    err = np.max(np.abs(np.asarray(ref) - np.asarray(got)))
    scale = np.max(np.abs(np.asarray(ref))) + 1e-6
    assert err / scale < 0.05


def test_tp_shardings_cover_llama_params():
    train, _ = _models()
    params, _, _ = _init(train)
    mesh = make_lm_mesh(seq=1, model=2, expert=1)
    sh = lm_tree_shardings(mesh, params)
    gate = sh["block_0"]["mlp_gate"]["kernel"].spec
    assert tuple(gate) == (None, "model")
    qparams = quantize_lm_params(params)
    qsh = lm_tree_shardings(mesh, qparams)
    assert tuple(qsh["block_0"]["mlp_gate"]["scale"].spec) == ("model",)
    assert tuple(
        qsh["block_0"]["mlp_gate"]["kernel_int8"].spec) == (None, "model")


def test_config_param_count_llama3_8b():
    # the 8B config must actually be ~8.03B params — guards the config
    # numbers (a transposed d_ff or head count would show here)
    n = llama.LLAMA3_8B.n_params()
    assert 7.9e9 < n < 8.1e9, n


def test_rope_theta_changes_long_range_behavior():
    # same params, different theta ⇒ different logits (theta is wired)
    a = llama.train_model(CFG, dtype=DT)
    b = llama.train_model(
        dataclasses_replace(CFG, rope_theta=10000.0), dtype=DT)
    params, tokens, positions = _init(a)
    la = a.apply({"params": params}, tokens, positions)
    lb = b.apply({"params": params}, tokens, positions)
    assert float(jnp.max(jnp.abs(la - lb))) > 1e-6


def dataclasses_replace(cfg, **kw):
    import dataclasses

    return dataclasses.replace(cfg, **kw)


def test_random_quantized_tree_matches_quantize_layout():
    # random_quantized_params must produce exactly the tree that
    # quantize_lm_params(train params) produces — same keys, shapes,
    # dtypes — so the 8B bench exercises the real serving path
    train, _ = _models()
    params, tokens, _ = _init(train, batch=1, seq=8)
    ref = quantize_lm_params(params)
    got = llama.random_quantized_params(CFG, dtype=DT)
    rs = jax.tree_util.tree_structure(ref)
    gs = jax.tree_util.tree_structure(got)
    assert rs == gs
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref),
            jax.tree_util.tree_leaves_with_path(got)):
        assert a.shape == b.shape, (pa, a.shape, b.shape)
        assert a.dtype == b.dtype, (pa, a.dtype, b.dtype)
    # and it actually decodes
    qserve = llama.decoder(CFG, dtype=DT, quantized=True)
    out, _ = greedy_generate(qserve, got, jnp.asarray([[1, 2, 3]]), 3)
    assert out.shape == (1, 3)


def test_gqa_ring_attention_matches_local_oracle():
    # GQA K/V rotate the ring GROUPED (H/Hkv less ICI traffic); the
    # result and gradients must still match single-shard attention
    import functools

    from tpu_k8s_device_plugin.workloads.transformer import (
        lm_train_step,
        make_lm_mesh,
        make_lm_train_step,
        synthetic_lm_batch,
    )

    mesh = make_lm_mesh(seq=4, model=2, expert=1)
    # ONE local oracle serves both ring layouts (hoisted: rebuilding it
    # per layout recompiled an identical train step)
    step2, state2, _ = make_lm_train_step(
        mesh, vocab=64, d_model=64, n_heads=8, n_layers=1, d_ff=128,
        seq_axis=None, batch=2, seq_len=32,
        n_kv_heads=2, ffn="swiglu", rope_theta=500000.0,
    )
    oracle_step = jax.jit(functools.partial(
        lm_train_step, state2["model"], state2["tx"]))
    for layout in ("contiguous", "zigzag"):
        step, state, place = make_lm_train_step(
            mesh, vocab=64, d_model=64, n_heads=8, n_layers=1, d_ff=128,
            seq_axis="seq", attn_layout=layout, batch=2, seq_len=32,
            n_kv_heads=2, ffn="swiglu", rope_theta=500000.0,
        )
        tokens, labels, positions = state["batch"]
        params, opt_state, loss_ring = step(
            state["params"], state["opt_state"], *place(
                tokens, labels, positions))
        _, _, loss_local = oracle_step(
            state2["params"], state2["opt_state"], tokens, labels,
            positions)
        np.testing.assert_allclose(
            float(loss_ring), float(loss_local), rtol=2e-5,
            err_msg=layout)


def test_flash_ring_rejects_grouped_kv():
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from tpu_k8s_device_plugin.workloads.ring_attention import (
        make_ring_attention,
    )

    mesh = Mesh(
        mesh_utils.create_device_mesh((4,), devices=jax.devices()[:4]),
        axis_names=("seq",))
    fn, sharding = make_ring_attention(mesh, "seq", causal=True,
                                       impl="flash")
    q = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (1, 32, 4, 16)),
        sharding)
    kv = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (1, 32, 2, 16)),
        sharding)
    with pytest.raises(ValueError, match="equal Q/KV head"):
        fn(q, kv, kv)
