"""Router tier: routing determinism, failover, stream pass-through.

Three layers of coverage:

1. Pure routing units (no sockets, no jax): affinity-key alignment,
   consistent-hash determinism across router restarts and registration
   orders, least-loaded selection off statz snapshots, the overload
   gate, staleness eviction, registration validation, promlint-clean
   metric families.
2. Fake-replica integration (stdlib sockets, no jax): pre-stream
   failover onto the live replica with breaker + failover accounting,
   and the mid-stream death path — the router must terminate the
   stream with a WELL-FORMED in-band error frame (JSON-lines and SSE
   framings both), never a silent truncation.
3. Real-engine equivalence (jax, tiny decoder): JSON-lines and SSE
   streams BYTE-IDENTICAL through the router hop vs direct-to-replica,
   traceparent/X-Trace-Id propagation with the X-Replica stamp, and
   the /statz surface in lock-step with the /metrics families.
"""

import http.client
import json
import socket
import threading
import time

import pytest

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.workloads.router import (
    DEFAULT_PREFIX_CHUNK,
    RouterServer,
    affinity_key,
)

# ---------------------------------------------------------------------------
# layer 1: pure routing units


def test_affinity_key_chunk_alignment():
    chunk = 32
    base = list(range(1, 65))             # 64 tokens = 2 chunks
    k64 = affinity_key({"tokens": base}, chunk)
    # extra tokens INSIDE the last partial chunk do not change the key
    assert affinity_key({"tokens": base + [99, 98]}, chunk) == k64
    assert affinity_key({"tokens": base + [1]}, chunk) == k64
    # a full extra chunk DOES
    assert affinity_key(
        {"tokens": base + list(range(100, 132))}, chunk) != k64
    # sub-chunk prompts hash whole (deterministic, never None)
    short = affinity_key({"tokens": [5, 6, 7]}, chunk)
    assert short == affinity_key({"tokens": [5, 6, 7]}, chunk)
    assert short != affinity_key({"tokens": [5, 6, 8]}, chunk)
    # string prompts hash their text; bools are not token ids
    assert affinity_key({"prompt": "hello"}, chunk) is not None
    assert affinity_key({"tokens": [True, False]}, chunk) is None
    assert affinity_key({}, chunk) is None


def _mk_router(**kw):
    kw.setdefault("statz_interval_s", 60.0)  # poller effectively off
    kw.setdefault("replica_ttl_s", 60.0)
    return RouterServer(**kw)


def test_consistent_hash_stable_across_restart_and_order():
    """Same prompt -> same replica: across fresh RouterServer
    instances (a router restart) and across registration orders (the
    ring depends only on the replica-id set)."""
    reps = [{"address": f"127.0.0.1:{9000 + i}",
             "replica_id": f"replica-{i}"} for i in range(4)]
    keys = [affinity_key({"tokens": [i * 7 + j for j in range(64)]},
                         DEFAULT_PREFIX_CHUNK) for i in range(20)]
    rt1 = _mk_router()
    for r in reps:
        rt1.register(dict(r))
    rt2 = _mk_router()                   # "restarted" router
    for r in reversed(reps):             # different order
        rt2.register(dict(r))
    t1 = [rt1.affinity_target(k) for k in keys]
    t2 = [rt2.affinity_target(k) for k in keys]
    assert t1 == t2
    # the hash actually spreads (not everything on one replica)
    assert len(set(t1)) > 1


def test_pick_prefers_affinity_then_least_loaded():
    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a",
                 "capacity": 4})
    rt.register({"address": "127.0.0.1:9002", "replica_id": "b",
                 "capacity": 4})
    key = next(
        affinity_key({"tokens": [i] * 32}, 32) for i in range(1, 99)
        if rt.affinity_target(
            affinity_key({"tokens": [i] * 32}, 32)) == "a")
    rep, hit = rt.pick(key)
    assert rep is not None and rep.rid == "a" and hit
    # load the affinity target past the overload gate -> least-loaded
    with rt._lock:
        rt._replicas["a"].statz = {
            "queue_depth": 100, "in_flight": 4, "capacity": 4,
            "scheduler_alive": True}
        rt._replicas["b"].statz = {
            "queue_depth": 0, "in_flight": 1, "capacity": 4,
            "scheduler_alive": True}
    rep, hit = rt.pick(key)
    assert rep is not None and rep.rid == "b" and not hit
    # no key at all -> pure least-loaded
    rep, hit = rt.pick(None)
    assert rep is not None and rep.rid == "b" and not hit


def test_pick_skips_dead_scheduler_and_open_breaker():
    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a"})
    rt.register({"address": "127.0.0.1:9002", "replica_id": "b"})
    with rt._lock:
        rt._replicas["a"].statz = {"scheduler_alive": False}
    rep, _ = rt.pick(None)
    assert rep is not None and rep.rid == "b"
    # open b's breaker too -> nothing routable
    with rt._lock:
        brk = rt._replicas["b"].breaker
    for _ in range(rt.breaker_threshold):
        brk.record_failure()
    rep, _ = rt.pick(None)
    assert rep is None
    assert not rt.healthy()


def test_stale_replica_evicted():
    rt = _mk_router(replica_ttl_s=0.2)
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a"})
    assert [r["replica_id"] for r in rt.replicas()] == ["a"]
    time.sleep(0.3)
    rt._poll_once()
    assert rt.replicas() == []
    samples = obs.parse_exposition(rt.registry.render())
    evs = [v for n, lab, v in samples
           if n == "tpu_router_replica_evictions_total"]
    assert evs and evs[0] == 1
    # re-registration resurrects it (fresh breaker, fresh stamp)
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a"})
    assert [r["replica_id"] for r in rt.replicas()] == ["a"]


def test_register_validation():
    rt = _mk_router()
    with pytest.raises(ValueError):
        rt.register({})
    with pytest.raises(ValueError):
        rt.register({"address": "no-port"})
    with pytest.raises(ValueError):
        rt.register({"address": "host:notaport"})
    out = rt.register({"address": "10.0.0.1:8000"})
    assert out["ok"] and out["replica_id"] == "10.0.0.1:8000"


def test_register_role_and_replicas_view():
    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "p",
                 "role": "prefill"})
    rt.register({"address": "127.0.0.1:9002", "replica_id": "d",
                 "role": "decode"})
    rt.register({"address": "127.0.0.1:9003", "replica_id": "m"})
    roles = {r["replica_id"]: r["role"] for r in rt.replicas()}
    assert roles == {"p": "prefill", "d": "decode", "m": "mixed"}
    # re-registration may change the role (a pod restarted with a
    # different flag keeps its identity)
    rt.register({"address": "127.0.0.1:9003", "replica_id": "m",
                 "role": "decode"})
    assert {r["replica_id"]: r["role"] for r in rt.replicas()}["m"] \
        == "decode"
    with pytest.raises(ValueError):
        rt.register({"address": "127.0.0.1:9004", "role": "gpu"})


def test_affinity_target_role_filtered_walk():
    """Role-filtered affinity stays deterministic (same id+role set
    -> same target) and always lands on the requested class."""
    rt = _mk_router()
    for i in range(3):
        rt.register({"address": f"127.0.0.1:{9000 + i}",
                     "replica_id": f"p-{i}", "role": "prefill"})
        rt.register({"address": f"127.0.0.1:{9100 + i}",
                     "replica_id": f"d-{i}", "role": "decode"})
    keys = [affinity_key({"tokens": [i * 3 + j for j in range(64)]},
                         DEFAULT_PREFIX_CHUNK) for i in range(20)]
    pre = [rt.affinity_target(k, role="prefill") for k in keys]
    dec = [rt.affinity_target(k, role="decode") for k in keys]
    assert all(t is not None and t.startswith("p-") for t in pre)
    assert all(t is not None and t.startswith("d-") for t in dec)
    # a restarted router with the same set agrees
    rt2 = _mk_router()
    for i in reversed(range(3)):
        rt2.register({"address": f"127.0.0.1:{9100 + i}",
                      "replica_id": f"d-{i}", "role": "decode"})
        rt2.register({"address": f"127.0.0.1:{9000 + i}",
                      "replica_id": f"p-{i}", "role": "prefill"})
    assert pre == [rt2.affinity_target(k, role="prefill")
                   for k in keys]
    assert dec == [rt2.affinity_target(k, role="decode")
                   for k in keys]
    # unfiltered walk is unchanged by the role machinery
    assert rt.affinity_target(keys[0]) == rt2.affinity_target(keys[0])


def test_pick_role_filter_and_no_cross_class_fallback():
    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "p",
                 "role": "prefill"})
    rt.register({"address": "127.0.0.1:9002", "replica_id": "d",
                 "role": "decode"})
    rep, _ = rt.pick(None, role="prefill")
    assert rep is not None and rep.rid == "p"
    rep, _ = rt.pick(None, role="decode")
    assert rep is not None and rep.rid == "d"
    # the one decode replica excluded -> nothing of that class
    rep, _ = rt.pick(None, role="decode", exclude={"d"})
    assert rep is None


def test_tenant_ring_deterministic_and_pick_pin():
    reps = [{"address": f"127.0.0.1:{9000 + i}",
             "replica_id": f"replica-{i}"} for i in range(4)]
    rt1 = _mk_router()
    for r in reps:
        rt1.register(dict(r))
    rt2 = _mk_router()
    for r in reversed(reps):
        rt2.register(dict(r))
    tenants = [f"tenant-{i}" for i in range(24)]
    t1 = [rt1.tenant_target(t) for t in tenants]
    assert t1 == [rt2.tenant_target(t) for t in tenants]
    assert len(set(t1)) > 1            # the hash actually spreads
    assert rt1.tenant_target("") is None
    # the pin takes precedence over prefix affinity
    pinned = rt1.tenant_target("tenant-0")
    other = next(r for r in t1 if r != pinned)
    key = next(
        affinity_key({"tokens": [i] * 64}, DEFAULT_PREFIX_CHUNK)
        for i in range(1, 200)
        if rt1.affinity_target(
            affinity_key({"tokens": [i] * 64},
                         DEFAULT_PREFIX_CHUNK)) == other)
    rep, hit = rt1.pick(key, pin=pinned)
    assert rep is not None and rep.rid == pinned and not hit
    # without the pin the same key goes to its affinity target
    rep, hit = rt1.pick(key)
    assert rep is not None and rep.rid == other and hit


def test_session_ring_deterministic_and_home_wins():
    reps = [{"address": f"127.0.0.1:{9000 + i}",
             "replica_id": f"replica-{i}"} for i in range(4)]
    rt1 = _mk_router()
    for r in reps:
        rt1.register(dict(r))
    rt2 = _mk_router()
    for r in reversed(reps):
        rt2.register(dict(r))
    sids = [f"user-{i}/chat-{i}" for i in range(24)]
    t1 = [rt1.session_target(s) for s in sids]
    assert t1 == [rt2.session_target(s) for s in sids]
    assert len(set(t1)) > 1            # the hash actually spreads
    assert rt1.session_target("") is None
    # once served somewhere, the recorded home beats the ring
    ring_pick = rt1.session_target("user-0/chat-0")
    other = next(r["replica_id"] for r in reps
                 if r["replica_id"] != ring_pick)
    rt1._note_session_home("user-0/chat-0", other)
    assert rt1.session_target("user-0/chat-0") == other
    # other sessions stay on their ring verdicts
    assert [rt1.session_target(s) for s in sids[1:]] == t1[1:]


def test_session_affinity_off_switch():
    rt = _mk_router(session_affinity=False)
    rt.register({"address": "127.0.0.1:9000", "replica_id": "r0"})
    assert rt.session_target("user-1/c") is None
    rt._note_session_home("user-1/c", "r0")
    assert rt.session_target("user-1/c") is None


def test_session_home_lru_bounded():
    rt = _mk_router(session_home_max=3)
    rt.register({"address": "127.0.0.1:9000", "replica_id": "r0"})
    for i in range(5):
        rt._note_session_home(f"s{i}", "r0")
    assert len(rt._session_home) == 3
    assert set(rt._session_home) == {"s2", "s3", "s4"}
    # re-noting refreshes recency
    rt._note_session_home("s2", "r0")
    rt._note_session_home("s5", "r0")
    assert "s2" in rt._session_home and "s3" not in rt._session_home


def test_session_of_matches_replica_scoping():
    f = RouterServer._session_of
    assert f({"session_id": "abc"}) == "abc"
    assert f({"session": "abc"}) == "abc"
    # native session_id is already fully qualified: tenant present or
    # not, it hashes as-is
    assert f({"session_id": "abc", "tenant": "t"}) == "abc"
    # OpenAI bodies scope session under user — the same string the
    # replica's _openai_to_native builds
    assert f({"session": "chat1", "user": "alice"}) == "alice/chat1"
    assert f({"session_id": "x", "user": "alice"}) == "x"
    assert f({}) == ""
    assert f({"session": ""}) == ""
    assert f({"tokens": [1, 2]}) == ""


def test_router_tenant_quota_charges_and_sheds():
    from tpu_k8s_device_plugin.workloads.qos import (
        parse_tenant_quotas,
    )

    rt = _mk_router(
        tenant_quotas=parse_tenant_quotas(["acme=1:100"]))
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a"})
    # cost = (8 prompt + 42 budget) * 1 = 50: two admits drain the
    # 100-token burst, the third sheds
    body = {"tokens": [1] * 8, "max_new_tokens": 42,
            "tenant": "acme"}
    assert rt._charge_tenant("acme", rt._est_cost(body))
    assert rt._charge_tenant("acme", rt._est_cost(body))
    assert not rt._charge_tenant("acme", rt._est_cost(body))
    # unknown tenants clone the '*' template; absent both, admit
    assert rt._charge_tenant("other", 1e9)
    rt2 = _mk_router(
        tenant_quotas=parse_tenant_quotas(["*=1:10"]))
    assert rt2._charge_tenant("x", 10.0)
    assert not rt2._charge_tenant("x", 1.0)
    assert rt2._charge_tenant("y", 10.0)   # y has its OWN bucket


def test_est_cost_mirrors_server_estimate():
    rt = _mk_router()
    assert rt._est_cost({"tokens": [1] * 10,
                         "max_new_tokens": 5}) == 15.0
    assert rt._est_cost({"tokens": [1] * 10, "max_new_tokens": 5,
                         "n": 3}) == 45.0
    # OpenAI spelling + the string-prompt 4-chars/token heuristic
    assert rt._est_cost({"prompt": "x" * 40, "max_tokens": 6}) == 16.0
    # absent budget falls back to the configured default
    assert rt._est_cost({"tokens": [1] * 4}) \
        == 4.0 + rt.default_budget


def test_prefill_heavy_heuristic():
    rt = _mk_router(prefill_threshold=32)
    assert rt._prefill_heavy({"tokens": [1] * 32})
    assert not rt._prefill_heavy({"tokens": [1] * 31})
    # unary qualifies regardless of length; only an EXPLICIT flag
    assert rt._prefill_heavy({"tokens": [1] * 4, "stream": False})
    assert not rt._prefill_heavy({"tokens": [1] * 4})
    # multi-copy requests never migrate
    assert not rt._prefill_heavy({"tokens": [1] * 64, "n": 2})
    # string prompts use the 4-chars/token heuristic
    assert rt._prefill_heavy({"prompt": "x" * 128})
    assert not rt._prefill_heavy({"prompt": "x" * 64})


def test_disagg_ready_requires_both_classes():
    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "p",
                 "role": "prefill"})
    assert not rt._disagg_ready()
    rt.register({"address": "127.0.0.1:9002", "replica_id": "d",
                 "role": "decode"})
    assert rt._disagg_ready()
    rt.disagg = False
    assert not rt._disagg_ready()


def test_router_metric_families_promlint_clean():
    import sys
    sys.path.insert(0, "tools")
    import promlint

    rt = _mk_router()
    rt.register({"address": "127.0.0.1:9001", "replica_id": "a"})
    rt._m_requests.labels(replica="a", outcome="ok").inc()
    rt._m_route.observe(0.01)
    rt._m_failovers.inc()
    rt._m_affinity.inc()
    rt._m_shed.labels(reason="no_replicas").inc()
    rt._m_shed.labels(reason="tenant_quota").inc()
    rt._m_migrations.labels(outcome="ok").inc()
    rt._m_migrate_s.observe(0.01)
    rt._m_role_requests.labels(role="prefill").inc()
    rt._m_tenant_pins.inc()
    errors = promlint.lint(rt.registry.render())
    assert errors == [], errors


# ---------------------------------------------------------------------------
# layer 2: fake replicas (stdlib sockets, no jax)


class _FakeReplica:
    """A scriptable stand-in replica: answers /statz, and /generate
    with either a complete chunked stream or a deliberate mid-stream
    connection drop (unterminated chunked body)."""

    def __init__(self, frames, die_after=None, content_type=None):
        self.frames = [f if isinstance(f, bytes) else f.encode()
                       for f in frames]
        self.die_after = die_after       # frames sent before dying
        self.content_type = content_type or "application/jsonlines"
        self.requests = 0
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET,
                              socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self):
        return f"127.0.0.1:{self.port}"

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            data = b""
            while b"\r\n\r\n" not in data:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                data += chunk
            head, _, rest = data.partition(b"\r\n\r\n")
            first = head.split(b"\r\n")[0].decode()
            if first.startswith("GET /statz"):
                body = json.dumps({
                    "scheduler_alive": True, "queue_depth": 0,
                    "in_flight": 0, "capacity": 4}).encode()
                conn.sendall(
                    b"HTTP/1.1 200 OK\r\nContent-Type: "
                    b"application/json\r\nContent-Length: %d\r\n\r\n%s"
                    % (len(body), body))
                return
            # POST /generate: drain the body per Content-Length
            length = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    length = int(line.split(b":")[1])
            while len(rest) < length:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                rest += chunk
            self.requests += 1
            conn.sendall(
                b"HTTP/1.1 200 OK\r\nContent-Type: %s\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                % self.content_type.encode())
            for i, frame in enumerate(self.frames):
                if self.die_after is not None and i >= self.die_after:
                    conn.close()        # mid-stream death, no 0-chunk
                    return
                conn.sendall(b"%x\r\n%s\r\n" % (len(frame), frame))
                time.sleep(0.01)
            conn.sendall(b"0\r\n\r\n")
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


def _wait_samples(rt, predicate, timeout_s=5.0):
    """Poll the router registry until *predicate*(samples) is truthy
    (the handler thread increments outcome counters just AFTER the
    terminator byte the client unblocks on — a scrape immediately
    after the response races it)."""
    deadline = time.time() + timeout_s
    while True:
        samples = obs.parse_exposition(rt.registry.render())
        got = predicate(samples)
        if got or time.time() >= deadline:
            return got, samples


def _post_router(port, payload, path="/generate", headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, json.dumps(payload), hdrs)
    resp = conn.getresponse()
    body = resp.read()
    out_headers = dict(resp.headers)
    conn.close()
    return resp.status, out_headers, body


@pytest.fixture()
def live_router():
    # breaker_threshold=1: one observed failure opens the breaker, so
    # the failover/abort assertions below are deterministic instead of
    # racing the statz poller for the second strike
    rt = RouterServer(statz_interval_s=0.2, replica_ttl_s=30.0,
                      breaker_reset_s=30.0, breaker_threshold=1,
                      seed=7)
    rt.start(host="127.0.0.1", port=0)
    yield rt
    rt.stop()


def _key_for(rt, rid, n=64):
    """A token prompt whose affinity target is *rid*."""
    for i in range(1, 500):
        cand = [(i + j) % 1000 + 1 for j in range(n)]
        if rt.affinity_target(
                affinity_key({"tokens": cand}, rt.prefix_chunk)) == rid:
            return cand
    raise AssertionError(f"no prompt hashed to {rid}")


def test_pre_stream_failover_onto_live_replica(live_router):
    """Affinity target dead before any byte: the request retries on
    the live replica, the breaker opens, the failover is counted."""
    rt = live_router
    ok = _FakeReplica(
        ['{"tokens":[1,2]}\n', '{"done": true, "tokens": [1, 2]}\n'])
    # a dead address: bind a port, close it again
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    try:
        rt.register({"address": ok.address, "replica_id": "live"})
        rt.register({"address": f"127.0.0.1:{dead_port}",
                     "replica_id": "dead"})
        prompt = _key_for(rt, "dead")
        status, headers, body = _post_router(
            rt.port, {"tokens": prompt, "max_new_tokens": 2})
        assert status == 200
        assert headers.get("X-Replica") == "live"
        assert body.endswith(b'{"done": true, "tokens": [1, 2]}\n')
        fo, _ = _wait_samples(rt, lambda samples: [
            v for n, lab, v in samples
            if n == "tpu_router_failovers_total" and v >= 1])
        assert fo
        from tpu_k8s_device_plugin import resilience
        with rt._lock:
            state = rt._replicas["dead"].breaker.state
        assert state == resilience.BREAKER_OPEN
        # journal carries the failover + the routed outcome
        names = [e["name"] for e in rt.recorder.events()]
        assert "tpu_router_failover" in names
        assert "tpu_router_routed" in names
    finally:
        ok.stop()


def test_drain_endpoint_takes_replica_out_of_rotation(live_router):
    """POST /drain over HTTP (the fleet reconciler's lever): the
    drained replica stops taking NEW streams but stays registered;
    {"draining": false} puts it back; ghosts 404, junk 400."""
    rt = live_router
    frames = ['{"tokens":[1,2]}\n', '{"done": true, "tokens": [1, 2]}\n']
    a, b = _FakeReplica(frames), _FakeReplica(frames)
    try:
        rt.register({"address": a.address, "replica_id": "a"})
        rt.register({"address": b.address, "replica_id": "b"})
        prompt = _key_for(rt, "a")
        status, _, _ = _post_router(
            rt.port, {"replica_id": "a"}, path="/drain")
        assert status == 200
        st, rows = _raw_get_json(rt.port, "/replicas")
        assert st == 200
        by_rid = {r["replica_id"]: r for r in rows["replicas"]}
        assert by_rid["a"]["draining"] is True
        # draining means not routable: the view says so ...
        assert by_rid["a"]["healthy"] is False
        assert by_rid["b"]["draining"] is False
        # ... and an a-affine request lands on b, no failover needed
        status, headers, _ = _post_router(
            rt.port, {"tokens": prompt, "max_new_tokens": 2})
        assert status == 200
        assert headers.get("X-Replica") == "b"
        # undrain restores the affinity route
        status, _, _ = _post_router(
            rt.port, {"replica_id": "a", "draining": False},
            path="/drain")
        assert status == 200
        status, headers, _ = _post_router(
            rt.port, {"tokens": prompt, "max_new_tokens": 2})
        assert status == 200
        assert headers.get("X-Replica") == "a"
        # caller bugs are loud: unknown replica 404, malformed body 400
        status, _, _ = _post_router(
            rt.port, {"replica_id": "ghost"}, path="/drain")
        assert status == 404
        status, _, _ = _post_router(
            rt.port, {"replica_id": ""}, path="/drain")
        assert status == 400
    finally:
        a.stop()
        b.stop()


def test_unroutable_when_everything_down(live_router):
    rt = live_router
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    rt.register({"address": f"127.0.0.1:{dead_port}",
                 "replica_id": "dead"})
    status, headers, body = _post_router(
        rt.port, {"tokens": [1, 2, 3], "max_new_tokens": 2})
    assert status == 503
    err = json.loads(body)
    assert "error" in err and err["code"] == 503
    # and with NO replicas at all, the other 503 flavor
    with rt._lock:
        rt._replicas.clear()
        rt._rebuild_ring_locked()
    status, _, body = _post_router(
        rt.port, {"tokens": [1, 2, 3], "max_new_tokens": 2})
    assert status == 503
    samples = obs.parse_exposition(rt.registry.render())
    shed = [v for n, lab, v in samples
            if n == "tpu_router_shed_total"
            and lab.get("reason") == "no_replicas"]
    assert shed and shed[0] >= 2


def test_mid_stream_death_emits_wellformed_jsonlines_frame(
        live_router):
    """The replica dies after 2 frames: the client's stream must end
    with a parseable JSON error line and a clean chunked terminator
    (http.client raises on a truncated chunked body — reading to EOF
    without an exception IS the well-formedness proof)."""
    rt = live_router
    fake = _FakeReplica(
        ['{"tokens":[1,2]}\n', '{"tokens":[3,4]}\n',
         '{"tokens":[5,6]}\n', '{"done": true}\n'],
        die_after=2)
    try:
        rt.register({"address": fake.address, "replica_id": "dying"})
        status, headers, body = _post_router(
            rt.port, {"tokens": [9] * 64, "max_new_tokens": 8})
        assert status == 200
        lines = body.strip().split(b"\n")
        # the passed-through frames arrive untouched...
        assert lines[0] == b'{"tokens":[1,2]}'
        assert lines[1] == b'{"tokens":[3,4]}'
        # ...and the terminal line is the router's structured error
        last = json.loads(lines[-1])
        assert last["code"] == 502 and "mid-stream" in last["error"]
        got, _ = _wait_samples(rt, lambda samples: [
            v for n, lab, v in samples
            if n == "tpu_router_requests_total"
            and lab.get("replica") == "dying"
            and lab.get("outcome") == "stream_abort"])
        assert got and got[0] == 1
        names = [e["name"] for e in rt.recorder.events()]
        assert "tpu_router_stream_abort" in names
    finally:
        fake.stop()


def test_mid_stream_death_emits_wellformed_sse_frame(live_router):
    """Same death, SSE framing: the terminal frame is a `data:` event
    carrying the OpenAI error shape."""
    rt = live_router
    fake = _FakeReplica(
        ["data: {\"id\":\"cmpl-1\"}\n\n", "data: {\"x\":2}\n\n",
         "data: [DONE]\n\n"],
        die_after=1, content_type="text/event-stream")
    try:
        rt.register({"address": fake.address, "replica_id": "dying"})
        status, headers, body = _post_router(
            rt.port, {"prompt": "hi", "max_tokens": 4},
            path="/v1/completions")
        assert status == 200
        assert body.startswith(b"data: {\"id\":\"cmpl-1\"}\n\n")
        tail = body.split(b"\n\n")[-2]          # last complete event
        assert tail.startswith(b"data: ")
        err = json.loads(tail[len(b"data: "):])
        assert err["error"]["type"] == "server_error"
    finally:
        fake.stop()


def test_router_statz_poll_updates_load(live_router):
    rt = live_router
    fake = _FakeReplica(['{"done": true}\n'])
    try:
        rt.register({"address": fake.address, "replica_id": "a"})
        deadline = time.time() + 5
        while time.time() < deadline:
            with rt._lock:
                snap = dict(rt._replicas["a"].statz)
            if snap:
                break
            time.sleep(0.05)
        assert snap.get("capacity") == 4
        assert snap.get("scheduler_alive") is True
    finally:
        fake.stop()


# ---------------------------------------------------------------------------
# layer 3: real-engine equivalence (jax)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_k8s_device_plugin.workloads.inference import make_decoder  # noqa: E402
from tpu_k8s_device_plugin.workloads.server import EngineServer  # noqa: E402
from tpu_k8s_device_plugin.workloads.serving import ServingEngine  # noqa: E402

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


class _ByteTok:
    def encode(self, s):
        return list(s.encode("latin-1"))

    def decode(self, ids):
        return bytes(int(t) % 256 for t in ids).decode("latin-1")


@pytest.fixture(scope="module")
def engine_stack():
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=4,
                       tokenizer=_ByteTok())
    srv.start(host="127.0.0.1", port=0)
    rt = RouterServer(statz_interval_s=0.2, replica_ttl_s=30.0,
                      seed=3)
    rt.start(host="127.0.0.1", port=0)
    srv.start_registration(f"http://127.0.0.1:{rt.port}",
                           replica_id="r0", model="test",
                           interval_s=0.3)
    deadline = time.time() + 10
    while time.time() < deadline and not rt.healthy():
        time.sleep(0.05)
    assert rt.healthy()
    yield srv, rt
    rt.stop()
    srv.stop()


def _raw_post(port, payload, path="/generate", headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, json.dumps(payload), hdrs)
    resp = conn.getresponse()
    body = resp.read()
    out = dict(resp.headers)
    conn.close()
    return resp.status, out, body


def test_jsonlines_stream_byte_identical_through_router(engine_stack):
    srv, rt = engine_stack
    payload = {"tokens": [3, 14, 15, 9, 2, 6], "max_new_tokens": 8}
    # warm both paths once (compile + APC donor) so the compared pair
    # are the same cadence: an APC repeat direct vs through the hop
    _raw_post(srv.port, payload)
    st_d, hd_d, body_d = _raw_post(srv.port, payload)
    st_r, hd_r, body_r = _raw_post(rt.port, payload)
    assert st_d == st_r == 200
    assert body_d == body_r          # BYTE-identical, framing included
    assert hd_r.get("X-Replica") == "r0"
    assert hd_d.get("Content-Type") == hd_r.get("Content-Type")


def test_per_token_stream_byte_identical_through_router(engine_stack):
    srv, rt = engine_stack
    payload = {"tokens": [7, 7, 3], "max_new_tokens": 6,
               "per_token": True}
    _raw_post(srv.port, payload)
    _, _, body_d = _raw_post(srv.port, payload)
    _, _, body_r = _raw_post(rt.port, payload)
    assert body_d == body_r


def test_unary_response_byte_identical_through_router(engine_stack):
    srv, rt = engine_stack
    payload = {"tokens": [5, 17, 3], "max_new_tokens": 5,
               "stream": False}
    _, _, body_d = _raw_post(srv.port, payload)
    _, _, body_r = _raw_post(rt.port, payload)
    assert body_d == body_r
    assert json.loads(body_r)["done"] is True


def test_sse_stream_byte_identical_through_router(engine_stack):
    """OpenAI SSE through the hop: byte-identical modulo the fields
    that are EXPECTED to differ per request (the cmpl-<trace-id> id
    and the created stamp) — so the comparison normalizes those and
    then requires byte equality, and separately pins the raw framing
    (data:/[DONE]) untouched."""
    import re

    srv, rt = engine_stack
    payload = {"prompt": "abc", "max_tokens": 6, "stream": True,
               "temperature": 0.0}
    _raw_post(srv.port, payload, path="/v1/completions")

    def norm(b):
        b = re.sub(rb"cmpl-[0-9a-f]+", b"cmpl-X", b)
        return re.sub(rb'"created": \d+', b'"created": 0', b)

    st_d, _, body_d = _raw_post(srv.port, payload,
                                path="/v1/completions")
    st_r, hd_r, body_r = _raw_post(rt.port, payload,
                                   path="/v1/completions")
    assert st_d == st_r == 200
    assert norm(body_d) == norm(body_r)
    assert body_r.rstrip().endswith(b"data: [DONE]")
    assert hd_r.get("X-Replica") == "r0"


def test_traceparent_propagates_through_hop(engine_stack):
    srv, rt = engine_stack
    trace_id = "ab" * 16
    tp = f"00-{trace_id}-{'cd' * 8}-01"
    st, headers, _ = _raw_post(
        rt.port, {"tokens": [4, 4, 4], "max_new_tokens": 2},
        headers={"traceparent": tp})
    assert st == 200
    # the replica continued OUR trace: same trace-id comes back in
    # both echo headers, through the router hop
    assert headers.get("X-Trace-Id") == trace_id
    assert headers.get("traceparent", "").split("-")[1] == trace_id
    assert headers.get("X-Replica") == "r0"
    # and the replica's journal holds the trace (the hop really
    # carried it, not just echoed it)
    evs = srv.recorder.events(trace_id=trace_id)
    assert evs


def test_affinity_deterministic_across_router_restart(engine_stack):
    """Same prompt -> same replica across a router RESTART with the
    same replica set (the ring is id-derived, not session-derived)."""
    srv, rt = engine_stack
    prompt = [9, 9, 8, 7, 1, 5]
    st1, hd1, _ = _raw_post(rt.port, {"tokens": prompt,
                                      "max_new_tokens": 2})
    rt2 = RouterServer(statz_interval_s=0.2, seed=99)  # fresh router
    rt2.start(host="127.0.0.1", port=0)
    try:
        rt2.register({"address": f"127.0.0.1:{srv.port}",
                      "replica_id": "r0"})
        st2, hd2, _ = _raw_post(rt2.port, {"tokens": prompt,
                                           "max_new_tokens": 2})
        assert st1 == st2 == 200
        assert hd1.get("X-Replica") == hd2.get("X-Replica") == "r0"
        key = affinity_key({"tokens": prompt}, DEFAULT_PREFIX_CHUNK)
        assert rt.affinity_target(key) == rt2.affinity_target(key)
    finally:
        rt2.stop()


def test_statz_lockstep_with_metrics(engine_stack):
    """The /statz snapshot must agree with the tpu_serving_* families
    the SAME server renders — the router's load signal and the
    dashboards must never tell different stories."""
    srv, rt = engine_stack
    # some traffic so the counters are non-trivial
    _raw_post(srv.port, {"tokens": [2, 71, 82], "max_new_tokens": 3})
    conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                      timeout=30)
    conn.request("GET", "/statz")
    statz = json.loads(conn.getresponse().read())
    conn.close()
    assert set(statz) == {
        "scheduler_alive", "queue_depth", "in_flight", "capacity",
        "kv_pages", "kv_pages_free", "requests_served", "role",
        "migrations", "shed", "kv_tiers", "goodput", "alerts"}
    assert set(statz["alerts"]) == {"firing", "pending", "firing_page"}
    # session tiering off on this server: the block is the fixed
    # empty schema, never absent (fleet aggregation must not branch)
    assert set(statz["kv_tiers"]) == {
        "device", "host", "host_bytes", "disk", "disk_bytes",
        "hits", "demotions", "promotions", "evictions"}
    assert statz["kv_tiers"]["device"] == 0
    assert set(statz["shed"]) == {"connections", "queue", "quota"}
    assert set(statz["goodput"]) == {"window_s", "classes"}
    assert statz["role"] == "mixed"
    assert set(statz["migrations"]) == {"out", "in"}
    samples = obs.parse_exposition(srv.render_metrics())

    def metric(name):
        vals = [v for n, lab, v in samples if n == name]
        return vals[0] if vals else None

    assert statz["scheduler_alive"] is True
    assert statz["queue_depth"] == metric(
        "tpu_serving_pending_requests")
    assert statz["capacity"] == metric("tpu_serving_n_slots")
    # contiguous engine: the kv bridge gauges only exist under
    # --kv-paging, but the tpu_serve_* pool family renders 0 always
    assert statz["kv_pages"] == (metric("tpu_serving_kv_pages") or 0)
    assert statz["kv_pages_free"] == metric(
        "tpu_serve_kv_pages_free")
    assert statz["requests_served"] == metric(
        "tpu_serving_requests_served_total")
    assert statz["in_flight"] == (
        metric("tpu_serving_running_copies")
        + metric("tpu_serving_admitting_copies"))
    shed = {lab.get("reason"): v for n, lab, v in samples
            if n == "tpu_serve_shed_total"}
    for reason in ("connections", "queue", "quota"):
        assert statz["shed"][reason] == shed.get(reason, 0)
    # disagg migration ledger in lock-step with the metric family
    # (both children render from boot — role notwithstanding)
    mig = {lab.get("direction"): v for n, lab, v in samples
           if n == "tpu_serve_migrations_total"}
    assert set(mig) == {"out", "in"}
    for direction in ("out", "in"):
        assert statz["migrations"][direction] == mig[direction]


def test_router_429_passthrough_not_failover(engine_stack):
    """A replica 429 (queue shed) is POLICY, not failure: it passes
    through with its Retry-After instead of being retried onto another
    replica (which would amplify load exactly when shedding)."""
    srv, rt = engine_stack
    old_max = srv.max_queue
    srv.max_queue = 0                      # everything sheds
    try:
        st, headers, body = _raw_post(
            rt.port, {"tokens": [1, 2, 3], "max_new_tokens": 2})
        assert st == 429
        assert "Retry-After" in headers
        assert headers.get("X-Replica") == "r0"
        err = json.loads(body)
        assert err["code"] == 429
    finally:
        srv.max_queue = old_max
    shed, _ = _wait_samples(rt, lambda samples: [
        v for n, lab, v in samples
        if n == "tpu_router_requests_total"
        and lab.get("replica") == "r0"
        and lab.get("outcome") == "shed"])
    assert shed and shed[0] >= 1


def _raw_get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    return resp.status, out


def test_router_stitches_trace_across_processes(engine_stack):
    """PR 12 acceptance: ONE traceparent driven through router ->
    replica -> scheduler window must come back from the ROUTER's
    /debug/traces as a single stitched span tree — the router's
    route/proxy events as the parent span, the replica's admit/window
    events as its child, in causal order."""
    srv, rt = engine_stack
    trace = obs.new_trace()
    st, headers, _ = _raw_post(
        rt.port, {"tokens": [11, 12, 13], "max_new_tokens": 4},
        headers={"traceparent": trace.to_traceparent()})
    assert st == 200
    st, stitched = _raw_get_json(
        rt.port, f"/debug/traces?trace_id={trace.trace_id}")
    assert st == 200
    assert stitched["trace_id"] == trace.trace_id
    tree = stitched["tree"]
    assert len(tree) == 1                    # ONE root: the router hop
    root = tree[0]
    assert root["source"] == "router"
    root_names = [e["name"] for e in root["events"]]
    assert "tpu_router_routed" in root_names
    assert "tpu_router_proxy" in root_names
    # the replica's span is a CHILD of the router's (the traceparent
    # hop made it so), tagged with the replica id by the stitcher
    assert len(root["children"]) == 1
    kid = root["children"][0]
    assert kid["source"] == "r0"
    assert kid["parent_id"] == root["span_id"]
    kid_names = [e["name"] for e in kid["events"]]
    assert "tpu_serve_admit" in kid_names
    assert "tpu_serve_window" in kid_names
    # causal order in the depth-first flatten: route decision before
    # the replica's admit, admit before its first decode window
    flat = [e["name"] for e in obs.flatten(tree)]
    assert flat.index("tpu_router_routed") \
        < flat.index("tpu_serve_admit") \
        < flat.index("tpu_serve_window")
    # without ?trace_id= the router serves its own recent-trace index
    st, index = _raw_get_json(rt.port, "/debug/traces")
    assert st == 200
    assert any(t["trace_id"] == trace.trace_id
               for t in index["traces"])


def test_fleet_statz_aggregates_replicas(engine_stack):
    """/fleet/statz: per-replica statz plus fleet-level sums and
    goodput re-derived from summed met/total counts."""
    srv, rt = engine_stack
    # traffic so the goodput block is non-trivial, then wait for the
    # poller to refresh the cached statz past it
    _raw_post(srv.port, {"tokens": [21, 22], "max_new_tokens": 2,
                         "slo_class": "interactive"})
    served = srv.statz()["goodput"]["classes"]["interactive"]["met"]
    assert served >= 1
    deadline = time.time() + 10
    fleet = {}
    while time.time() < deadline:
        st, fleet = _raw_get_json(rt.port, "/fleet/statz")
        assert st == 200
        cls = fleet["fleet"]["goodput"].get("interactive", {})
        if cls.get("met", 0) >= served:
            break
        time.sleep(0.1)
    assert fleet["replicas"] == 1
    assert fleet["healthy"] == 1
    assert set(fleet["per_replica"]) == {"r0"}
    assert fleet["per_replica"]["r0"]["healthy"] is True
    # the aggregate re-states the one replica's statz
    statz = srv.statz()
    assert fleet["fleet"]["capacity"] == statz["capacity"]
    assert fleet["fleet"]["requests_served"] <= \
        statz["requests_served"]
    cls = fleet["fleet"]["goodput"]["interactive"]
    assert cls["met"] >= served
    assert 0.0 <= cls["goodput_ratio"] <= 1.0
    assert "burn_rate_max" in cls
