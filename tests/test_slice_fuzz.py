"""Randomized rendezvous fuzz: the slice state machine under random
join/leave/restart orderings.

The rendezvous promise is order-independence: whatever interleaving of
worker joins, pre-formation departures, worker restarts (new session,
same hostname) and coordinator crashes (reload from the crash-safe state
file) actually happens, the slice that forms is THE slice — ranks are
the pure sorted-by-(coords, hostname) function of the member set, the
membership survives coordinator restarts bit-for-bit, and slice health
is exactly the conjunction of member health.  CI sweeps this with
several ENGINE_FUZZ_SEED values (see .github/workflows/test.yml).
"""

import json
import os
import random

from tpu_k8s_device_plugin.slice import SliceState

SEED = int(os.environ.get("ENGINE_FUZZ_SEED", "0"))
ROUNDS = int(os.environ.get("SLICE_FUZZ_ROUNDS", "30"))
_JAX_PORT = 8476


def _expected_ranks(specs):
    """The documented rank function, computed independently of the
    implementation: coordinate-holders first by coordinate, the rest by
    hostname."""
    ordered = sorted(
        specs.items(),
        key=lambda kv: (0, kv[1], kv[0]) if kv[1] else (1, (), kv[0]),
    )
    return [h for h, _ in ordered]


def test_rendezvous_fuzz(tmp_path):
    rnd = random.Random(SEED)
    for round_i in range(ROUNDS):
        n = rnd.randint(2, 6)
        hosts = [f"host-{i:02d}" for i in range(n)]
        # a random subset knows its ICI coordinate (tpu-env metadata);
        # shuffled values so coordinate order != hostname order
        coord_vals = list(range(n))
        rnd.shuffle(coord_vals)
        specs = {
            h: ((coord_vals[i],) if rnd.random() < 0.7 else ())
            for i, h in enumerate(hosts)
        }
        sessions = {h: f"{h}-s0" for h in hosts}
        state_path = str(tmp_path / f"round-{round_i}.json")
        state = SliceState(n, _JAX_PORT, state_path)
        now = 0.0

        # -- formation phase: random joins/leaves/restarts ------------------
        ops = 0
        while state.membership is None:
            ops += 1
            assert ops < 2000, "rendezvous failed to converge"
            # leaves and crashes get rarer as the op budget burns down, so
            # convergence is guaranteed while early orderings stay chaotic
            roll = rnd.random() if ops < 500 else 1.0
            if roll < 0.15:
                state.leave(rnd.choice(hosts))
            elif roll < 0.25:
                # coordinator crash pre-formation: nothing persisted yet,
                # the fresh incarnation starts from zero members
                state = SliceState(n, _JAX_PORT, state_path)
            else:
                h = rnd.choice(hosts)
                if rnd.random() < 0.1:  # worker restart: new session
                    sessions[h] = f"{h}-s{ops}"
                now += 1.0
                res = state.join(
                    h, coords=specs[h], chip_count=8,
                    session=sessions[h], now=now,
                )
                assert res.expected == n
                assert res.joined <= n

        expected = _expected_ranks(specs)
        membership = state.membership
        assert list(membership.hostnames) == expected
        assert membership.coordinator_address == f"{expected[0]}:{_JAX_PORT}"

        # every member, re-polling in any order, gets its deterministic rank
        for h in rnd.sample(hosts, n):
            res = state.join(h, coords=specs[h], chip_count=8,
                             session=sessions[h], now=now)
            assert res.formed and res.rank == expected.index(h)

        # a stranger can't slip into a formed slice
        res = state.join("host-zz", session="zz-s0", now=now)
        assert res.error and res.membership is membership

        # -- post-formation phase: health + crash recovery ------------------
        model_unhealthy = set()
        for _ in range(rnd.randint(10, 40)):
            now += 1.0
            roll = rnd.random()
            if roll < 0.15:
                # coordinator crash: reload from the state file — same
                # slice id, same generation, same ranks, health resets to
                # the optimistic default until members heartbeat again
                state = SliceState(n, _JAX_PORT, state_path)
                assert state.membership == membership
                model_unhealthy.clear()
            elif roll < 0.25:
                h = rnd.choice(hosts)
                state.leave(h)
                model_unhealthy.add(h)
            else:
                h = rnd.choice(hosts)
                healthy = rnd.random() < 0.7
                view = state.heartbeat(h, healthy=healthy,
                                       reason="" if healthy else "fuzzed",
                                       now=now)
                model_unhealthy.discard(h)
                if not healthy:
                    model_unhealthy.add(h)
                assert view.membership == membership
                assert view.unhealthy_hostnames == sorted(model_unhealthy)
                assert view.slice_healthy == (not model_unhealthy)

        # restarted workers recover their ranks to the very end
        h = rnd.choice(hosts)
        res = state.join(h, coords=specs[h], chip_count=8,
                         session=f"{h}-reborn", now=now)
        assert res.formed and res.rank == expected.index(h)
        assert state.membership == membership


def test_reshape_determinism_fuzz(tmp_path):
    """Reshape must be a pure function of WHO died, never of heartbeat
    interleaving: several independent coordinator replicas see the same
    formation and the same member death, but drive survivor heartbeats
    in different random orders — every replica (including one
    crash-recovered from its state file mid-flight) must converge on a
    byte-identical reshaped Membership: same ranks, same generation,
    same lineage."""
    rnd = random.Random(SEED ^ 0x5E5A9E)
    for round_i in range(ROUNDS):
        n = rnd.randint(2, 6)
        hosts = [f"host-{i:02d}" for i in range(n)]
        coord_vals = list(range(n))
        rnd.shuffle(coord_vals)
        specs = {
            h: ((coord_vals[i],) if rnd.random() < 0.7 else ())
            for i, h in enumerate(hosts)
        }
        join_order = list(hosts)
        rnd.shuffle(join_order)
        victim = rnd.choice(hosts)
        survivors = [h for h in hosts if h != victim]
        grace, timeout = 3.0, 5.0

        replicas = []
        for j in range(3):
            st = SliceState(
                n, _JAX_PORT,
                state_path=str(tmp_path / f"r{round_i}-c{j}.json"),
                heartbeat_timeout_s=timeout, reshape_grace_s=grace)
            # identical formation on every replica
            for h in join_order:
                st.join(h, coords=specs[h], chip_count=8,
                        session=f"{h}-s0", now=0.0)
            assert st.membership is not None
            replicas.append(st)
        gen1 = replicas[0].membership
        assert all(r.membership == gen1 for r in replicas)

        # replica 2 additionally crashes and recovers mid-flight: the
        # reshaped result must still match (coords persisted)
        replicas[2] = SliceState(
            n, _JAX_PORT,
            state_path=str(tmp_path / f"r{round_i}-c2.json"),
            heartbeat_timeout_s=timeout, reshape_grace_s=grace)
        assert replicas[2].membership == gen1

        # the victim dies at t=0; survivors heartbeat at the SAME
        # timestamps on every replica but in per-replica random order
        for t in (6.0, 8.0, 9.5):
            for st in replicas:
                order = list(survivors)
                rnd.shuffle(order)
                for h in order:
                    st.heartbeat(h, healthy=True, now=t)

        dumps = []
        for st in replicas:
            m = st.membership
            assert m is not None
            if len(survivors) >= 1:
                assert m.generation == gen1.generation + 1, (
                    round_i, victim, m)
                assert set(m.hostnames) == set(survivors)
                assert m.reshaped_from == (gen1.slice_id,)
                assert m.degraded
                # ranks contiguous in the documented order over the
                # surviving member set
                expected = sorted(
                    survivors,
                    key=lambda h: (0, specs[h], h) if specs[h]
                    else (1, (), h))
                assert list(m.hostnames) == expected
            dumps.append(json.dumps(m.to_dict(), sort_keys=True))
        assert len(set(dumps)) == 1, (
            f"round {round_i}: replicas diverged:\n" + "\n".join(dumps))
