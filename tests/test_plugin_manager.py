"""End-to-end plugin + manager tests against the fake kubelet.

Covers the paths the reference never tested (SURVEY.md §4): registration
flow, ListAndWatch over the wire, Allocate responses, kubelet-restart
re-registration, and resource-list diffing.
"""

import functools
import os
import queue
import shutil
import time

import pytest

from tpu_k8s_device_plugin.health import TpuHealthServer, get_tpu_health
from tpu_k8s_device_plugin.manager import PluginManager
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.types import constants
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

from fake_kubelet import FakeKubelet, ListAndWatchConsumer


def addr(i):
    return f"0000:00:{4 + i:02x}.0"


@pytest.fixture
def impl(testdata):
    root = os.path.join(testdata, "v5e-8")
    return TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )


@pytest.fixture
def kubelet(tmp_path):
    k = FakeKubelet(str(tmp_path / "device-plugins")).start()
    yield k
    k.stop()


@pytest.fixture
def manager(impl, kubelet):
    m = PluginManager(
        impl,
        pulse_seconds=0,
        kubelet_dir=kubelet.dir,
        kubelet_watch_interval_s=0.1,
    )
    m.run(block=False)
    yield m
    m.stop()


def test_registration_request_shape(kubelet, manager):
    assert kubelet.wait_for_registration()
    [reg] = kubelet.registrations
    assert reg.version == "v1beta1"
    assert reg.resource_name == "google.com/tpu"
    assert reg.endpoint == "google.com_tpu"
    assert reg.options.get_preferred_allocation_available
    assert os.path.exists(os.path.join(kubelet.dir, reg.endpoint))


def test_list_and_watch_and_allocate_over_wire(kubelet, manager):
    assert kubelet.wait_for_registration()
    stub = kubelet.plugin_stub("google.com_tpu")

    consumer = ListAndWatchConsumer(stub)
    frame = consumer.next_frame()
    assert len(frame.devices) == 8
    assert all(d.health == constants.HEALTHY for d in frame.devices)

    pref = stub.GetPreferredAllocation(
        pluginapi.PreferredAllocationRequest(
            container_requests=[
                pluginapi.ContainerPreferredAllocationRequest(
                    available_deviceIDs=[addr(i) for i in range(8)],
                    allocation_size=2,
                )
            ]
        )
    )
    chosen = list(pref.container_responses[0].deviceIDs)
    assert chosen == [addr(0), addr(1)]

    alloc = stub.Allocate(
        pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(devices_ids=chosen)
            ]
        )
    )
    car = alloc.container_responses[0]
    assert car.envs[constants.ENV_TPU_VISIBLE_CHIPS] == "0,1"
    assert len(car.devices) == 2
    consumer.cancel()


def test_heartbeat_triggers_resend(kubelet, impl):
    m = PluginManager(
        impl, pulse_seconds=0, kubelet_dir=kubelet.dir,
        kubelet_watch_interval_s=0.1,
    )
    m.run(block=False)
    try:
        assert kubelet.wait_for_registration()
        stub = kubelet.plugin_stub("google.com_tpu")
        consumer = ListAndWatchConsumer(stub)
        consumer.next_frame()
        # manual beat (the pulse thread calls exactly this)
        for sp in m._plugins.values():
            sp.plugin.beat()
        frame = consumer.next_frame()
        assert len(frame.devices) == 8
        consumer.cancel()
    finally:
        m.stop()


def test_kubelet_restart_triggers_reregistration(kubelet, manager):
    assert kubelet.wait_for_registration()
    assert len(kubelet.registrations) == 1
    kubelet.restart()
    assert kubelet.wait_for_registration(timeout=10.0)
    assert len(kubelet.registrations) == 2


def assert_wipe_restart_recovers(kubelet, n_devices=8):
    """Wipe-restart the kubelet, then assert the plugin re-registers,
    re-creates its endpoint socket, and answers ListAndWatch."""
    kubelet.register_event.clear()
    kubelet.restart(wipe_dir=True)
    assert kubelet.wait_for_registration(timeout=10.0)
    sock = os.path.join(kubelet.dir, "google.com_tpu")
    deadline = time.time() + 5.0
    while not os.path.exists(sock) and time.time() < deadline:
        time.sleep(0.05)
    assert os.path.exists(sock)
    stub = kubelet.plugin_stub("google.com_tpu")
    devs = next(iter(stub.ListAndWatch(pluginapi.Empty()))).devices
    assert len(devs) == n_devices


def test_kubelet_restart_wiping_dp_dir_reserves_sockets(kubelet, manager):
    """Real kubelet clears the device-plugin dir on startup; the plugin must
    re-create its endpoint socket before re-registering, or the kubelet's
    dial to the advertised endpoint fails and capacity drops to 0."""
    assert kubelet.wait_for_registration()
    assert os.path.exists(os.path.join(kubelet.dir, "google.com_tpu"))
    assert_wipe_restart_recovers(kubelet)


def test_resource_diffing_stops_removed_plugins(kubelet, manager):
    assert kubelet.wait_for_registration()
    sock = os.path.join(kubelet.dir, "google.com_tpu")
    assert os.path.exists(sock)
    manager.update_resources([])
    assert not os.path.exists(sock)
    manager.update_resources(["tpu"])
    assert kubelet.wait_for_registration()
    assert os.path.exists(sock)


def test_stop_removes_sockets(kubelet, impl):
    m = PluginManager(impl, kubelet_dir=kubelet.dir)
    m.run(block=False)
    sock = os.path.join(kubelet.dir, "google.com_tpu")
    assert os.path.exists(sock)
    m.stop()
    assert not os.path.exists(sock)


def test_concurrent_lifecycle_stress(kubelet, impl):
    """Race-detector analog (SURVEY §5: the reference never runs -race;
    its concurrent surface is the plugin map + channels).  Hammer the
    manager's three mutating surfaces — resource diffing, kubelet
    restarts, pulse beats — from concurrent threads and assert the
    manager ends consistent and serving."""
    import threading

    m = PluginManager(
        impl, pulse_seconds=1, kubelet_dir=kubelet.dir,
        kubelet_watch_interval_s=0.05,
    )
    try:
        m.run(block=False)
        assert kubelet.wait_for_registration()
        errors = []

        def diff_loop():
            try:
                for _ in range(10):
                    m.update_resources([])
                    m.update_resources(["tpu"])
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def restart_loop():
            try:
                for _ in range(5):
                    kubelet.restart(wipe_dir=True)
                    time.sleep(0.05)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=diff_loop),
            threading.Thread(target=restart_loop),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert not errors, errors
        # watch thread must still be alive (no dict-changed-during-
        # iteration death) and the endpoint must end up served + answering
        assert_wipe_restart_recovers(kubelet)
    finally:
        m.stop()


def wait_for_frame(consumer, predicate, timeout=15.0):
    """Drain ListAndWatch frames until one satisfies *predicate*."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = consumer.next_frame(timeout=max(0.1, deadline - time.time()))
        except queue.Empty:
            break
        if predicate(last):
            return last
    raise AssertionError(f"no matching frame within {timeout}s; last: {last}")


def test_health_transition_observed_over_wire(testdata, tmp_path, kubelet):
    """The reference's core health loop, end-to-end (VERDICT r1 #1b /
    BASELINE config #5): exporter daemon probes sysfs → pulse → plugin's
    next ListAndWatch frame to the kubelet flips the device Unhealthy,
    then back to Healthy on recovery — all over real gRPC sockets.
    Matches plugin.go:146-170 + amdgpu.go:954-974 + exporter/health.go."""
    tree = str(tmp_path / "v5e-8")
    shutil.copytree(os.path.join(testdata, "v5e-8"), tree, symlinks=True)
    sysr, devr = os.path.join(tree, "sys"), os.path.join(tree, "dev")
    exporter_sock = str(tmp_path / "exporter.sock")
    exporter = TpuHealthServer(exporter_sock, sysr, devr).start()
    impl = TpuContainerImpl(
        sysfs_root=sysr, dev_root=devr,
        tpu_env_path=os.path.join(tree, "run", "tpu", "tpu-env"),
        health_fn=functools.partial(get_tpu_health, exporter_sock),
    )
    m = PluginManager(impl, pulse_seconds=1, kubelet_dir=kubelet.dir,
                      kubelet_watch_interval_s=0.1)
    m.run(block=False)
    sick = addr(3)
    attr = os.path.join(sysr, "devices", "pci0000:00", sick,
                        constants.SYSFS_CHIP_STATE)
    try:
        assert kubelet.wait_for_registration()
        consumer = ListAndWatchConsumer(kubelet.plugin_stub("google.com_tpu"))
        first = consumer.next_frame()
        assert all(d.health == constants.HEALTHY for d in first.devices)

        with open(attr, "w") as f:
            f.write("dead\n")
        frame = wait_for_frame(
            consumer,
            lambda fr: any(d.ID == sick and d.health == constants.UNHEALTHY
                           for d in fr.devices),
        )
        # only the wedged chip is demoted — no collateral flapping
        assert sum(d.health == constants.HEALTHY for d in frame.devices) == 7

        with open(attr, "w") as f:
            f.write("alive\n")
        wait_for_frame(
            consumer,
            lambda fr: all(d.health == constants.HEALTHY for d in fr.devices),
        )
        consumer.cancel()
    finally:
        m.stop()
        exporter.stop()


def test_partition_mode_change_readvertised_without_restart(
    testdata, tmp_path, kubelet
):
    """Runtime rediscovery e2e (VERDICT r1 #2): flipping the host's
    partition mode re-advertises resources through the running manager —
    no process restart — including the new resource's socket, registration,
    and a working allocation path."""
    tree = str(tmp_path / "v5p-8")
    shutil.copytree(os.path.join(testdata, "v5p-8"), tree, symlinks=True)
    env_path = os.path.join(tree, "run", "tpu", "tpu-env")
    base_env = open(env_path).read()
    impl = TpuContainerImpl(
        resource_naming_strategy=constants.RESOURCE_NAMING_STRATEGY_MIXED,
        sysfs_root=os.path.join(tree, "sys"),
        dev_root=os.path.join(tree, "dev"),
        tpu_env_path=env_path,
    )
    assert impl.get_resource_names() == ["tpu"]
    m = PluginManager(impl, pulse_seconds=1, kubelet_dir=kubelet.dir,
                      kubelet_watch_interval_s=0.1)
    m.run(block=False)
    try:
        assert kubelet.wait_for_registration()
        assert kubelet.registrations[-1].resource_name == "google.com/tpu"

        with open(env_path, "w") as f:
            f.write(base_env + "TPU_PARTITION_MODE: 'core'\n")

        deadline = time.time() + 15.0
        core_sock = os.path.join(kubelet.dir, "google.com_tpucore")
        while time.time() < deadline and not os.path.exists(core_sock):
            time.sleep(0.1)
        assert os.path.exists(core_sock), "tpucore endpoint never served"
        assert not os.path.exists(os.path.join(kubelet.dir, "google.com_tpu")), \
            "stale tpu endpoint still served after mode change"
        while time.time() < deadline and not any(
            r.resource_name == "google.com/tpucore"
            for r in kubelet.registrations
        ):
            time.sleep(0.1)
        assert any(r.resource_name == "google.com/tpucore"
                   for r in kubelet.registrations), "tpucore never registered"

        # the new resource answers: 4 chips x 2 TensorCores = 8 devices
        stub = kubelet.plugin_stub("google.com_tpucore")
        devs = next(iter(stub.ListAndWatch(pluginapi.Empty()))).devices
        assert len(devs) == 8
        chosen = [devs[0].ID, devs[1].ID]
        alloc = stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(devices_ids=chosen)
            ]
        ))
        car = alloc.container_responses[0]
        assert "TPU_VISIBLE_CORES" in car.envs
    finally:
        m.stop()


def test_rediscover_no_change_is_noop(impl):
    assert impl.rediscover() is False


def test_rediscover_device_count_change_single_strategy(testdata, tmp_path):
    """Under single naming the resource name is stable but the device count
    changes (4 whole chips -> 8 cores) — enumerate must follow."""
    tree = str(tmp_path / "v5p-8")
    shutil.copytree(os.path.join(testdata, "v5p-8"), tree, symlinks=True)
    env_path = os.path.join(tree, "run", "tpu", "tpu-env")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(tree, "sys"),
        dev_root=os.path.join(tree, "dev"),
        tpu_env_path=env_path,
    )
    from tpu_k8s_device_plugin.types import DevicePluginContext
    ctx = DevicePluginContext("tpu")
    assert len(impl.enumerate(ctx)) == 4
    with open(env_path, "a") as f:
        f.write("TPU_PARTITION_MODE: 'core'\n")
    assert impl.rediscover() is True
    assert impl.get_resource_names() == ["tpu"]
    assert len(impl.enumerate(ctx)) == 8
    assert impl.rediscover() is False  # idempotent


def test_kubelet_socket_flap_stress(kubelet, impl):
    """Rapid kubelet delete/recreate cycles: exactly one
    re-registration per recreate, and no leaked endpoint sockets or
    plugin threads across the churn (PR 5 satellite)."""
    import threading

    m = PluginManager(
        impl, pulse_seconds=0, kubelet_dir=kubelet.dir,
        kubelet_watch_interval_s=0.05,
    )
    try:
        m.run(block=False)
        assert kubelet.wait_for_registration()
        baseline_threads = threading.active_count()
        cycles = 6
        for i in range(cycles):
            kubelet.register_event.clear()
            kubelet.restart(wipe_dir=True)
            assert kubelet.wait_for_registration(timeout=10.0), \
                f"no re-registration after recreate {i + 1}"
        # exactly one registration per recreate (plus the initial one):
        # no duplicate storms, no missed cycles
        assert len(kubelet.registrations) == cycles + 1
        # no leaked sockets: the dp dir holds kubelet.sock + our one
        # endpoint, nothing else
        deadline = time.time() + 5.0
        while time.time() < deadline:
            entries = sorted(os.listdir(kubelet.dir))
            if entries == ["google.com_tpu", "kubelet.sock"]:
                break
            time.sleep(0.05)
        assert sorted(os.listdir(kubelet.dir)) == \
            ["google.com_tpu", "kubelet.sock"]
        # no thread growth: the watch loop re-serves in place instead
        # of spawning per flap (grpc's internal pool may wobble by a
        # thread or two; a per-cycle leak would add >= cycles)
        assert threading.active_count() <= baseline_threads + cycles - 1
        # and the endpoint still answers
        stub = kubelet.plugin_stub("google.com_tpu")
        devs = next(iter(stub.ListAndWatch(pluginapi.Empty()))).devices
        assert len(devs) == 8
    finally:
        m.stop()
    # stop() joins its threads (PR 5 satellite): nothing it spawned
    # may outlive it
    assert m._threads == []


def test_registration_survives_kubelet_downtime(impl, tmp_path):
    """Plugin comes up before the kubelet: retries fail, then the watch loop
    registers once the socket appears."""
    dp_dir = str(tmp_path / "device-plugins")
    os.makedirs(dp_dir)
    m = PluginManager(
        impl, kubelet_dir=dp_dir, kubelet_watch_interval_s=0.1,
    )
    # shrink retry delay for the test
    import tpu_k8s_device_plugin.manager.manager as mgr_mod
    old = mgr_mod._REGISTER_RETRY_DELAY_S
    mgr_mod._REGISTER_RETRY_DELAY_S = 0.05
    try:
        m.run(block=False)
        time.sleep(0.3)
        k = FakeKubelet(dp_dir).start()
        try:
            assert k.wait_for_registration(timeout=10.0)
        finally:
            k.stop()
    finally:
        mgr_mod._REGISTER_RETRY_DELAY_S = old
        m.stop()


def test_multihost_slice_env_coherent_over_wire(testdata, tmp_path):
    """The JobSet example (example/multihost/jobset.yaml) depends on
    BOTH hosts of a multi-host slice handing their full-host pods a
    COHERENT slice identity: identical accelerator type / topology /
    per-host bounds / process bounds, and distinct worker ids covering
    [0, num_workers).  Drive the two v5e-16 fixture hosts through two
    fake kubelets simultaneously — the full registration + preferred
    allocation + Allocate path over real gRPC sockets — and assert the
    pair of responses libtpu would see (VERDICT r4 #7)."""
    cars = {}
    stack = []
    try:
        for host in ("v5e-16-host0", "v5e-16-host1"):
            root = os.path.join(testdata, host)
            impl = TpuContainerImpl(
                sysfs_root=os.path.join(root, "sys"),
                dev_root=os.path.join(root, "dev"),
                tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
            )
            k = FakeKubelet(str(tmp_path / host)).start()
            stack.append(k.stop)
            m = PluginManager(
                impl, pulse_seconds=0, kubelet_dir=k.dir,
                kubelet_watch_interval_s=0.1,
            )
            m.run(block=False)
            stack.append(m.stop)
            assert k.wait_for_registration()
            stub = k.plugin_stub("google.com_tpu")
            # a full-host pod asks for every advertised chip; the
            # preferred allocator must grant the whole host
            pref = stub.GetPreferredAllocation(
                pluginapi.PreferredAllocationRequest(
                    container_requests=[
                        pluginapi.ContainerPreferredAllocationRequest(
                            available_deviceIDs=[
                                addr(i) for i in range(8)],
                            allocation_size=8,
                        )
                    ]
                )
            )
            chosen = list(pref.container_responses[0].deviceIDs)
            assert sorted(chosen) == [addr(i) for i in range(8)]
            alloc = stub.Allocate(
                pluginapi.AllocateRequest(
                    container_requests=[
                        pluginapi.ContainerAllocateRequest(
                            devices_ids=chosen)
                    ]
                )
            )
            cars[host] = alloc.container_responses[0]
    finally:
        for fn in reversed(stack):
            fn()
    e0, e1 = (cars[h].envs for h in ("v5e-16-host0", "v5e-16-host1"))
    # slice-global identity: identical on every host
    for key in (constants.ENV_TPU_ACCELERATOR_TYPE,
                constants.ENV_TPU_TOPOLOGY,
                constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS,
                constants.ENV_TPU_PROCESS_BOUNDS):
        assert e0[key] == e1[key], key
    assert e0[constants.ENV_TPU_ACCELERATOR_TYPE] == "v5litepod-16"
    assert e0[constants.ENV_TPU_PROCESS_BOUNDS] == "2,1,1"
    # per-host identity: worker ids are distinct and cover the slice
    ids = {e[constants.ENV_TPU_WORKER_ID] for e in (e0, e1)}
    assert ids == {"0", "1"}
    # every host mounts its full 8 local chips
    for host in cars:
        assert len(cars[host].devices) == 8
        assert cars[host].envs[constants.ENV_TPU_VISIBLE_CHIPS] == \
            ",".join(str(i) for i in range(8))
