"""Per-request logit_bias (OpenAI semantics): a plain add before every
pick, per-slot data on the one compiled step.

Oracles: +100 (the OpenAI range cap) on one token forces it
deterministically against O(1)-scale random-init logits (even
sampled); banning the greedy winner with -100 yields the runner-up;
run_scan,
step-wise decode, and spec rounds agree token-for-token on a biased
engine; an unbiased neighbor's tokens are untouched by a biased slot."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DRAFT_CFG = dict(vocab=96, d_model=32, n_heads=2, n_layers=1, d_ff=64)


def _init(model, seed):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=64, dtype=jnp.float32)
    return model, _init(model, 0)


def _oracle(model, params, prompt, n):
    out, _ = greedy_generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0].tolist()


def test_force_token_even_when_sampled(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit([5, 17, 3], temperature=1.0, top_k=32,
                  logit_bias={42: 100.0})
    eng.run(5)
    assert eng.output(s)[:5] == [42] * 5


def test_ban_greedy_winner_yields_runner_up(setup):
    model, params = setup
    plain = _oracle(model, params, [5, 17, 3], 1)
    banned = plain[0]
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit([5, 17, 3], logit_bias={banned: -100.0})
    tok = eng.output(s)[0]
    assert tok != banned
    # the runner-up of the true first-step distribution
    from tpu_k8s_device_plugin.workloads.inference import (
        init_cache, extend_step)
    cache = init_cache(model, 1)
    pos = jnp.arange(3, dtype=jnp.int32)[None, :]
    logits, _ = extend_step(model, params, cache,
                            jnp.asarray([[5, 17, 3]], jnp.int32), pos)
    row = np.asarray(logits[0, -1]).copy()
    row[banned] = -np.inf
    assert tok == int(np.argmax(row))


def test_scan_step_and_spec_agree_biased(setup):
    model, params = setup
    draft = make_decoder(**DRAFT_CFG, max_len=64, dtype=jnp.float32)
    dparams = _init(draft, 1)
    bias = {7: 5.0, 11: -100.0}

    def mk(**kw):
        e = ServingEngine(model, params, n_slots=1,
                          max_new_tokens=8, **kw)
        return e, e.admit([5, 17, 3], logit_bias=bias)

    a, sa = mk()
    for _ in range(10):
        a.step()
    b, sb = mk()
    b.run_scan(8)
    c, sc = mk(draft=(draft, dparams), gamma=3)
    c.run_spec(10)
    assert a.output(sa) == b.output(sb) == c.output(sc)
    assert 11 not in a.output(sa)


def test_unbiased_neighbor_untouched(setup):
    model, params = setup
    solo = _oracle(model, params, [3, 14, 15], 6)
    eng = ServingEngine(model, params, n_slots=2, max_new_tokens=6)
    su = eng.admit([3, 14, 15])
    eng.admit([5, 17, 3], logit_bias={42: 100.0})
    eng.run(8)
    assert eng.output(su) == solo


def test_stale_bias_cleared_on_reuse(setup):
    model, params = setup
    solo = _oracle(model, params, [3, 14, 15], 5)
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=5)
    s = eng.admit([5, 17, 3], logit_bias={42: 100.0})
    eng.run(7)
    assert eng.output(s) == [42] * 5
    eng.release(s)
    s2 = eng.admit([3, 14, 15])  # unbiased reuse of the same slot
    eng.run(7)
    assert eng.output(s2) == solo


def test_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    with pytest.raises(ValueError, match="vocab"):
        eng.admit([1, 2], logit_bias={CFG["vocab"]: 1.0})
    with pytest.raises(ValueError, match="finite"):
        eng.admit([1, 2], logit_bias={3: float("nan")})
    # OpenAI clamps the range to [-100, 100]; out-of-range values are
    # rejected so a bias can never overpower the -1e9 min_tokens /
    # grammar constraint masks (ADVICE r4)
    with pytest.raises(ValueError, match=r"\[-100, 100\]"):
        eng.admit([1, 2], logit_bias={3: 101.0})
    with pytest.raises(ValueError, match=r"\[-100, 100\]"):
        eng.admit([1, 2], logit_bias={3: -1e12})
    with pytest.raises(ValueError, match="non-empty"):
        eng.admit([1, 2], logit_bias={})
    # a rejected admit leaves the engine reusable
    s = eng.admit([1, 2])
    eng.run(2)
    assert len(eng.output(s)) >= 1


def test_logit_bias_over_http(setup):
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    import http.client
    import json

    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                       timeout=120)
        # JSON object keys are strings, as OpenAI clients send them
        c.request("POST", "/generate", json.dumps(
            {"tokens": [5, 17, 3], "stream": False,
             "logit_bias": {"42": 100.0}}),
            {"Content-Type": "application/json"})
        r = c.getresponse()
        ev = json.loads(r.read().decode().strip().splitlines()[0])
        c.close()
        assert ev["tokens"] == [42] * 4
    finally:
        srv.stop()


# -- min_tokens (vLLM): eos/stop floor -----------------------------------

def test_min_tokens_defers_forced_eos(setup):
    """+100 bias makes eos win every pick; min_tokens must suppress
    it for exactly the floor, then let it fire with reason 'eos'."""
    model, params = setup
    eos = 33
    eng = ServingEngine(model, params, n_slots=1, eos_id=eos)
    s = eng.admit([5, 17, 3], logit_bias={eos: 100.0}, min_tokens=3)
    eng.run(8)
    out = eng.output(s)
    assert len(out) == 4
    assert eos not in out[:3] and out[3] == eos
    assert eng.finish_reason(s) == "eos"


def test_min_tokens_defers_stop_ids_too(setup):
    model, params = setup
    t = 44
    eng = ServingEngine(model, params, n_slots=1)
    s = eng.admit([5, 17, 3], logit_bias={t: 100.0}, stop=[t],
                  min_tokens=2)
    eng.run(6)
    out = eng.output(s)
    assert t not in out[:2] and out[2] == t
    assert eng.finish_reason(s) == "stop"


def test_min_tokens_scan_step_spec_agree(setup):
    model, params = setup
    draft = make_decoder(**DRAFT_CFG, max_len=64, dtype=jnp.float32)
    dparams = _init(draft, 1)
    eos = 33

    def mk(**kw):
        e = ServingEngine(model, params, n_slots=1, eos_id=eos,
                          max_new_tokens=8, **kw)
        return e, e.admit([5, 17, 3], logit_bias={eos: 100.0},
                          min_tokens=5)

    a, sa = mk()
    for _ in range(10):
        a.step()
    b, sb = mk()
    b.run_scan(3)   # crossing happens MID-window on the next scan
    b.run_scan(5)
    c, sc = mk(draft=(draft, dparams), gamma=3)
    c.run_spec(10)
    assert a.output(sa) == b.output(sb) == c.output(sc)
    assert a.output(sa)[5] == eos


def test_min_tokens_zero_is_noop(setup):
    model, params = setup
    a = ServingEngine(model, params, n_slots=1, max_new_tokens=5)
    sa = a.admit([3, 14, 15])
    a.run(7)
    b = ServingEngine(model, params, n_slots=1, max_new_tokens=5)
    sb = b.admit([3, 14, 15], min_tokens=0)
    b.run(7)
    assert a.output(sa) == b.output(sb)


def test_min_tokens_validation(setup):
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=4)
    with pytest.raises(ValueError, match="min_tokens"):
        eng.admit([1, 2], min_tokens=-1)
    with pytest.raises(ValueError, match="exceeds"):
        eng.admit([1, 2], min_tokens=9)


def test_min_tokens_over_http(setup):
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    import http.client
    import json

    model, params = setup
    eng = ServingEngine(model, params, n_slots=1, eos_id=33)
    srv = EngineServer(eng, max_new_tokens=6, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                       timeout=120)
        c.request("POST", "/generate", json.dumps(
            {"tokens": [5, 17, 3], "stream": False,
             "logit_bias": {"33": 100.0}, "min_tokens": 3}),
            {"Content-Type": "application/json"})
        r = c.getresponse()
        ev = json.loads(r.read().decode().strip().splitlines()[0])
        c.close()
        assert len(ev["tokens"]) == 4 and ev["tokens"][3] == 33
        assert ev["finish_reason"] == "eos"
        # min > max is a 400, as in vLLM
        c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                       timeout=60)
        c.request("POST", "/generate", json.dumps(
            {"tokens": [1, 2], "max_new_tokens": 2, "min_tokens": 5}),
            {"Content-Type": "application/json"})
        assert c.getresponse().status == 400
        c.close()
    finally:
        srv.stop()
