"""Paged-KV equivalence suite + QoS engine mechanics.

The paged engine's correctness bar is the house invariant extended to
storage: with a fixed request trace, token streams are BYTE-IDENTICAL
between the contiguous cache and the page pool — greedy, seeded
sampled, penalized, grammar-constrained, LoRA mixes, APC hits (exact
and partial, shared pages and CoW), and spec-decode alike; and the
pool must beat contiguous where it claims to: strictly more requests
in flight than full-length reservations would allow, on a
shared-prefix workload.  (int8 pool storage is the one documented
lossy opt-out — asserted running, not bit-equal.)
"""

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.grammar import (
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import (
    attach_lora,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.kv_pool import PagePoolExhausted
from tpu_k8s_device_plugin.workloads.scheduler import IterationScheduler
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
EOS = 0
MAX_LEN = 64
PATTERN = "(AB|CD)+E"


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return model, params, dfa


def _mk(model, params, paged, dfa=None, draft=None, **kw):
    return ServingEngine(
        model, params, n_slots=kw.pop("n_slots", 3), chunk=8,
        eos_id=kw.pop("eos_id", None), max_new_tokens=kw.pop("max_new", 6),
        auto_prefix_min=4, grammar=dfa, draft=draft,
        kv_paging=paged, **kw)


def _drain(eng, trace):
    """Run a trace of admit-kwargs dicts through the raw engine with
    slot recycling; returns outputs in trace order."""
    out = [None] * len(trace)
    live = {}
    i = 0
    while i < len(trace) or live:
        while i < len(trace) and eng.free_slots():
            s = eng.admit(**trace[i])
            live[s] = i
            i += 1
        eng.step()
        for s in list(live):
            if eng.finished(s):
                out[live.pop(s)] = eng.output(s)
    return out


TRACE = [
    dict(prompt=list(range(1, 13))),
    dict(prompt=list(range(40, 60)), temperature=0.8, seed=7),
    dict(prompt=[5, 6, 7, 8, 9], temperature=0.5, seed=3,
         presence_penalty=0.4, frequency_penalty=0.2),
    dict(prompt=list(range(1, 13))),                  # exact repeat
    dict(prompt=list(range(40, 56)) + [88, 89, 90]),  # partial prefix
    dict(prompt=[11] * 9, repetition_penalty=1.3, temperature=0.6,
         seed=5),
    dict(prompt=list(range(1, 13)), logit_bias={4: 5.0, 9: -4.0}),
    dict(prompt=[70, 71, 72, 73], min_tokens=3, stop=[71]),
]


def test_equivalence_step_paths(setup):
    model, params, _ = setup
    a = _drain(_mk(model, params, False), TRACE)
    b = _drain(_mk(model, params, True), TRACE)
    assert a == b


def test_equivalence_run_scan_windows(setup):
    model, params, _ = setup

    def scan_drain(paged):
        eng = _mk(model, params, paged, max_new=16, n_slots=2)
        s1 = eng.admit(list(range(1, 10)))
        s2 = eng.admit(list(range(20, 28)), temperature=0.9, seed=11,
                       top_p=0.9)
        outs = [dict(eng.run_scan(4)) for _ in range(3)]
        return outs, eng.output(s1), eng.output(s2)

    assert scan_drain(False) == scan_drain(True)


def test_equivalence_grammar(setup):
    model, params, dfa = setup

    def run(paged):
        eng = _mk(model, params, paged, dfa=dfa, eos_id=EOS,
                  max_new=10)
        s = eng.admit([65, 66], grammar=True)
        while any(eng.active):
            eng.step()
        return eng.output(s), eng.finish_reason(s)

    assert run(False) == run(True)


def test_equivalence_lora_mixed_batch(setup):
    _, params, _ = setup
    lmodel = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32,
                          n_adapters=2)
    lparams = attach_lora(params, lmodel, jax.random.PRNGKey(3))

    def run(paged):
        eng = ServingEngine(lmodel, lparams, n_slots=2, chunk=8,
                            max_new_tokens=6, auto_prefix_min=4,
                            kv_paging=paged)
        a = eng.admit(list(range(1, 10)), adapter=0)
        b = eng.admit(list(range(1, 10)), adapter=1)
        while any(eng.active):
            eng.step()
        return eng.output(a), eng.output(b)

    assert run(False) == run(True)


def test_equivalence_spec_decode_ngram(setup):
    model, params, _ = setup

    def run(paged):
        eng = ServingEngine(model, params, n_slots=2, chunk=8,
                            max_new_tokens=10, draft="ngram", gamma=3,
                            auto_prefix_min=4, kv_paging=paged)
        a = eng.admit([7, 8, 9, 7, 8, 9, 7, 8])
        b = eng.admit(list(range(30, 40)))
        while any(eng.active):
            eng.spec_round()
        return eng.output(a), eng.output(b)

    assert run(False) == run(True)


def test_equivalence_interleaved_scheduler(setup):
    """The PR-6 equivalence harness, third axis: paged vs contiguous
    under the iteration scheduler with mid-window admissions."""
    model, params, _ = setup

    def drive(paged):
        eng = ServingEngine(model, params, n_slots=2, chunk=4,
                            max_new_tokens=6, auto_prefix_min=4,
                            kv_paging=paged)
        intake = deque()
        tickets, live, results = {}, {}, {}

        def pull():
            if not intake:
                return None
            key, kwargs = intake.popleft()
            t = sched.begin(**kwargs)
            tickets[t] = key
            return t

        sched = IterationScheduler(eng, window=4, interleave=True,
                                   prefill_budget=2, pull=pull,
                                   sync_dwell_s=0.0)
        trace = [
            (0, "a", dict(prompt=list(range(1, 10)))),
            (0, "b", dict(prompt=list(range(1, 10)), temperature=0.7,
                          seed=9)),
            (2, "c", dict(prompt=list(range(1, 8)) + [80, 81])),
            (4, "d", dict(prompt=list(range(1, 10)))),
        ]
        ai = 0
        for i in range(200):
            while ai < len(trace) and trace[ai][0] <= i:
                intake.append(trace[ai][1:])
                ai += 1
            res = sched.iterate()
            for t in res.admitted:
                live[t.slot] = tickets.pop(t)
            for slot in list(live):
                if eng.finished(slot):
                    results[live.pop(slot)] = eng.output(slot)
            if ai == len(trace) and not intake and not live \
                    and not sched.busy():
                break
        assert len(results) == len(trace)
        return results

    assert drive(False) == drive(True)


def test_oversubscription_beats_full_reservation(setup):
    """THE acceptance claim: a pool sized for 2 full-length
    reservations holds 4 concurrent shared-prefix requests, with
    outputs bit-identical to the contiguous engine."""
    model, params, _ = setup
    pool_pages = 16          # 16 * 8 rows = 2 * max_len
    eng = _mk(model, params, True, n_slots=4, max_new=8,
              kv_pages=pool_pages)
    ref = _mk(model, params, False, n_slots=4, max_new=8)
    prefix = list(range(1, 33))
    slots = [eng.admit(prefix + [60 + i, 70 + i]) for i in range(4)]
    refs = [ref.admit(prefix + [60 + i, 70 + i]) for i in range(4)]
    assert sum(eng.active) == 4          # > the 2 reservations allow
    st = eng.stats()
    assert st["kv_pages_shared"] > 0
    eng.run(12)
    ref.run(12)
    for s, r in zip(slots, refs):
        assert eng.output(s) == ref.output(r)
    eng._pool.check()


def test_exact_repeat_shares_pages_and_cow_fires(setup):
    """A busy donor's exact repeat maps the donor's pages by reference
    (zero-copy admission); the repeat's first append past the shared
    rows pays exactly one CoW page copy."""
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=3, max_new=6)
    ref = _mk(model, params, False, n_slots=3, max_new=6)
    p = list(range(1, 12))   # t_p=11: partial tail page -> CoW on append
    a = eng.admit(p)
    ra = ref.admit(p)
    # donor stays BUSY so prefix-affinity cannot reuse its slot
    b = eng.admit(p)
    rb = ref.admit(p)
    st = eng.stats()
    assert st["kv_pages_shared"] > 0, "exact repeat did not share"
    cow_before = eng._pool.cow_copies
    eng.step()
    ref.step()
    assert eng._pool.cow_copies > cow_before, "append into shared page must CoW"
    eng.run(10)
    ref.run(10)
    assert eng.output(a) == ref.output(ra)
    assert eng.output(b) == ref.output(rb)
    eng._pool.check()


def test_preempt_resume_bit_exact(setup):
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=2, max_new=12)
    ref = _mk(model, params, False, n_slots=2, max_new=12)
    a, b = list(range(1, 10)), list(range(30, 40))
    sa, sb = eng.admit(a), eng.admit(b, temperature=0.7, seed=13,
                                     repetition_penalty=1.2)
    ra, rb = ref.admit(a), ref.admit(b, temperature=0.7, seed=13,
                                     repetition_penalty=1.2)
    for _ in range(3):
        eng.step()
        ref.step()
    state = eng.preempt(sb)
    assert eng.stats()["kv_preemptions"] == 1
    for _ in range(2):
        eng.step()
        ref.step()
    sb2 = eng.resume(state)
    while any(eng.active):
        eng.step()
    while any(ref.active):
        ref.step()
    assert eng.output(sa) == ref.output(ra)
    # the seeded+penalized stream continues exactly where it left off
    assert eng.output(sb2) == ref.output(rb)
    eng._pool.check()


def test_pool_exhaustion_raises_at_begin(setup):
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=3, max_new=4, kv_pages=8)
    eng.admit(list(range(1, 30)))        # 4 pages prompt (+1 growth)
    eng.admit(list(range(40, 64)))       # 3 pages
    with pytest.raises(PagePoolExhausted):
        eng.admit(list(range(60, 90)))   # nothing reclaimable
    # both originals still healthy
    eng.run(6)
    eng._pool.check()


def test_full_pool_still_shares_exact_repeats(setup):
    """With the pool completely spoken for, a cold admission 429s —
    but an exact repeat of the resident prompt still admits, because
    sharing needs ZERO new pages.  429s become policy, and the policy
    knows about sharing."""
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=3, max_new=4, kv_pages=8)
    eng.admit(list(range(1, 60)))        # 8 pages: the whole pool
    eng.admit(list(range(1, 60)))        # shares all 8 by reference
    assert sum(eng.active) == 2
    assert eng.stats()["kv_pages_shared"] == 8
    with pytest.raises(PagePoolExhausted):
        eng.admit(list(range(2, 61)))    # cold: no pages left
    eng._pool.check()


def test_parked_donor_pages_reclaimed_under_pressure(setup):
    """release() keeps donor pages (APC), but pool pressure evicts the
    LRU parked record instead of failing admission — the bounded
    answer to release-survives-forever donor rows."""
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=2, max_new=4, kv_pages=10)
    s1 = eng.admit(list(range(1, 25)))    # 3 prompt pages (+ growth)
    eng.run(8)
    eng.release(s1)
    assert eng._pool.used_pages() > 0     # parked donor pins pages
    # a fat admission (7 pages > what's free) forces the reclaim
    s2 = eng.admit(list(range(5, 60)))
    assert eng.stats()["prefix_evictions"] >= 1
    eng.run(6)
    eng._pool.check()


def test_prefix_registry_lru_cap(setup):
    model, params, _ = setup
    eng = ServingEngine(model, params, n_slots=2, chunk=8,
                        max_new_tokens=4, prefix_registry_max=2)
    h1 = eng.register_prefix(list(range(1, 9)))
    h2 = eng.register_prefix(list(range(10, 18)))
    # touch h1 so h2 is the LRU
    eng.admit(list(range(1, 9)) + [50], prefix=h1)
    h3 = eng.register_prefix(list(range(20, 28)))
    st = eng.stats()
    assert st["registered_prefixes"] == 2
    assert st["prefix_evictions"] == 1
    assert h2 not in eng._prefixes          # LRU went
    assert h1 in eng._prefixes and h3 in eng._prefixes
    with pytest.raises(ValueError):
        eng.admit(list(range(10, 18)) + [51], prefix=h2)


def test_int8_pool_runs_and_stays_close(setup):
    """kv_dtype=int8 is the documented lossy mode: it must run every
    path and keep the same shape of output, not the same bits."""
    model, params, _ = setup
    eng = _mk(model, params, True, max_new=6, kv_dtype="int8")
    s1 = eng.admit(list(range(1, 12)))
    s2 = eng.admit(list(range(1, 12)))    # share + CoW on int8 pages
    eng.run(10)
    assert len(eng.output(s1)) == 6 and len(eng.output(s2)) == 6
    # exact repeats share quantized pages bit-for-bit: both streams
    # read identical storage, so they agree with each other
    assert eng.output(s1) == eng.output(s2)
    eng._pool.check()


def test_paged_ctor_validation(setup):
    model, params, _ = setup
    with pytest.raises(ValueError):
        _mk(model, params, True, kv_page_size=7)      # 7 !| 64
    with pytest.raises(ValueError):
        _mk(model, params, True, kv_page_size=16)     # 16 !| chunk 8
    with pytest.raises(ValueError):
        _mk(model, params, True, kv_dtype="fp8")
    with pytest.raises(ValueError):
        _mk(model, params, True, kv_pages=3)          # < one sequence


def test_engine_trace_fuzz_pool_integrity(setup):
    """A longer mixed trace through the paged engine, then the
    allocator oracle: nothing leaked, nothing double-freed, and a
    full drain returns every page."""
    import os

    seed = int(os.environ.get("ENGINE_FUZZ_SEED", "0") or 0)
    rng = np.random.RandomState(777 + seed)
    model, params, _ = setup
    eng = _mk(model, params, True, n_slots=3, max_new=4, kv_pages=18)
    live = []
    for _ in range(60):
        op = rng.randint(3)
        if op == 0 and eng.free_slots():
            base = int(rng.randint(1, 50))
            n = int(rng.randint(4, 20))
            try:
                live.append(eng.admit(list(range(base, base + n)),
                                      temperature=float(rng.rand()),
                                      seed=int(rng.randint(100))))
            except PagePoolExhausted:
                pass
        elif op == 1 and any(eng.active):
            eng.step()
        elif op == 2 and live:
            s = live.pop(int(rng.randint(len(live))))
            eng.release(s)
        for s in list(live):
            if eng.finished(s):
                live.remove(s)
        eng._pool.check()
    for s in list(live):
        eng.release(s)
    eng._pool.check()
