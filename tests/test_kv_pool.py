"""Page-pool allocator unit tests + free-list fuzz.

The allocator is pure host state (no JAX), so these run at C speed and
the fuzz can afford thousands of random admit/release/share/CoW/
preempt sequences.  The oracle is ``PagePool.check()``: refcounts
equal table occurrences, the free list is exactly the zero-ref pages,
nothing leaks and nothing double-frees — seeded via ENGINE_FUZZ_SEED
like the other engine fuzz suites.
"""

import os

import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.kv_pool import (
    PagePool,
    PagePoolExhausted,
)


def test_ctor_validation():
    with pytest.raises(ValueError):
        PagePool(8, 7, 2, 64)       # page must divide max_len
    with pytest.raises(ValueError):
        PagePool(3, 16, 2, 64)      # < one full-length sequence
    with pytest.raises(ValueError):
        PagePool(8, 0, 2, 64)


def test_alloc_map_unmap_roundtrip():
    p = PagePool(8, 8, 2, 64)
    assert p.free_pages() == 8
    a = p.alloc()
    p.map(0, 0, a)
    assert p.free_pages() == 7
    assert p.entry(0, 0) == a
    assert p.writable(0, 0)
    p.unmap(0, 0)
    assert p.free_pages() == 8
    assert p.entry(0, 0) == p.scratch
    p.check()


def test_alloc_order_is_deterministic():
    p = PagePool(8, 8, 2, 64)
    got = [p.alloc() for _ in range(8)]
    assert got == list(range(8))
    with pytest.raises(PagePoolExhausted):
        p.alloc()
    for g in got:
        p.give_back(g)
    assert p.free_pages() == 8


def test_share_refcounts_and_cow():
    p = PagePool(8, 8, 2, 64)
    for idx in range(3):
        p.map(0, idx, p.alloc())
    shared = p.share(0, 2)
    p.map_shared(1, shared)
    assert p.shared_pages() == 2
    assert not p.writable(1, 0)          # shared: CoW before write
    assert not p.writable(0, 0)          # the donor side too
    assert p.writable(0, 2)              # unshared suffix stays
    new = p.alloc()
    old = p.cow(1, 0, new)
    assert old == shared[0]
    assert p.writable(1, 0)
    assert p.cow_copies == 1
    assert p.shared_pages() == 1
    p.check()


def test_clear_slot_frees_only_last_reference():
    p = PagePool(8, 8, 2, 64)
    for idx in range(2):
        p.map(0, idx, p.alloc())
    p.map_shared(1, p.share(0, 2))
    free_before = p.free_pages()
    p.clear_slot(0)
    # slot 1 still references both pages: nothing freed
    assert p.free_pages() == free_before
    p.clear_slot(1)
    assert p.free_pages() == 8
    p.check()


def test_self_share_survives_clear():
    # the begin-time incref / finish-time clear+reinstall dance, with
    # the donor slot being the destination itself
    p = PagePool(8, 8, 2, 64)
    for idx in range(2):
        p.map(0, idx, p.alloc())
    pages = p.share(0, 2)     # refs 2
    p.clear_slot(0)           # refs 1, NOT freed
    assert p.free_pages() == 6
    p.map_shared(0, pages)    # refs stay 1, table re-installed
    assert p.writable(0, 0) and p.writable(0, 1)
    p.check()


def test_unshare_rolls_back_aborted_share():
    p = PagePool(8, 8, 2, 64)
    p.map(0, 0, p.alloc())
    pages = p.share(0, 1)
    p.unshare(pages)
    assert p.writable(0, 0)
    p.clear_slot(0)
    assert p.free_pages() == 8
    p.check()


def test_double_free_and_underflow_raise():
    p = PagePool(8, 8, 2, 64)
    a = p.alloc()
    p.map(0, 0, a)
    with pytest.raises(RuntimeError):
        p.map(0, 0, a)            # remap without unmap
    with pytest.raises(RuntimeError):
        p.give_back(a)            # still referenced
    with pytest.raises(RuntimeError):
        p.cow(0, 0, 7)            # not shared: write in place
    p.unmap(0, 0)                 # last ref: auto-freed
    assert p.free_pages() == 8
    b = p.alloc()
    p.give_back(b)                # never mapped: explicit return
    assert p.free_pages() == 8
    p.check()


def test_pages_for():
    p = PagePool(8, 8, 2, 64)
    assert list(p.pages_for(0, 8)) == [0]
    assert list(p.pages_for(0, 9)) == [0, 1]
    assert list(p.pages_for(7, 17)) == [0, 1, 2]
    assert list(p.pages_for(8, 8)) == []


def test_fuzz_never_leaks_or_double_frees():
    """Random admit/release/share/CoW/preempt sequences against the
    integrity oracle.  Deterministic per ENGINE_FUZZ_SEED (CI sweeps
    several)."""
    seed = int(os.environ.get("ENGINE_FUZZ_SEED", "0") or 0)
    rng = np.random.RandomState(1234 + seed)
    n_slots, n_tables = 6, 8
    p = PagePool(24, 8, n_slots, 64)
    # per-slot logical fill level (next unmapped index)
    fill = [0] * n_slots

    for step in range(4000):
        op = rng.randint(5)
        s = int(rng.randint(n_slots))
        if op == 0 and fill[s] < n_tables:          # grow
            try:
                p.map(s, fill[s], p.alloc())
                fill[s] += 1
            except PagePoolExhausted:
                pass
        elif op == 1 and fill[s] > 0:               # release
            p.clear_slot(s)
            fill[s] = 0
        elif op == 2:                               # prefix share
            d = int(rng.randint(n_slots))
            if d != s and fill[s] > 0:
                n = int(rng.randint(1, fill[s] + 1))
                pages = p.share(s, n)
                if rng.rand() < 0.2:
                    p.unshare(pages)                # aborted admission
                else:
                    p.clear_slot(d)
                    p.map_shared(d, pages)
                    fill[d] = n
        elif op == 3 and fill[s] > 0:               # CoW a shared page
            idx = int(rng.randint(fill[s]))
            if not p.writable(s, idx) \
                    and p.entry(s, idx) != p.scratch:
                try:
                    p.cow(s, idx, p.alloc())
                except PagePoolExhausted:
                    pass
        elif op == 4 and fill[s] > 0:               # preempt (free all)
            p.clear_slot(s)
            fill[s] = 0
        if step % 97 == 0:
            p.check()
    p.check()
    # drain everything: the pool must come back whole
    for s in range(n_slots):
        p.clear_slot(s)
    p.check()
    assert p.free_pages() == 24
