"""Iteration-scheduler equivalence and mid-window admission.

The scheduler's correctness bar is the house invariant: with a fixed
request trace, token streams are BYTE-IDENTICAL with interleaving on
vs. off — AND with ragged packed prefill and dispatch-ahead overlap
toggled in every combination — greedy, seeded sampled,
grammar-constrained, APC hit and miss, paged KV alike.  (Unseeded
sampling depends on the global key stream by design; per-request
seeds exist precisely to opt out — same posture as the engine fuzz.)
Plus the split-admission API itself: begin/step/finish must be the
one-shot admit, packed admit_step_packed must be the serial chunks,
and the exact-repeat fast paths (zero-extend full-prompt APC,
prefix-affinity inplace placement, cached greedy first token) must
change nothing but the work done.
"""

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.grammar import (
    regex_to_dfa,
    token_dfa,
)
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.scheduler import IterationScheduler
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
EOS = 0
MAX_LEN = 64
PATTERN = "(AB|CD)+E"  # bytes < 96


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    dfa = token_dfa(regex_to_dfa(PATTERN), tb, eos_id=EOS)
    return model, params, dfa


def _solo(model, params, prompt, n_steps):
    out, _ = greedy_generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None, :], n_steps)
    return np.asarray(out)[0].tolist()


def _drive(model, params, dfa, trace, interleave, max_new=6,
           n_slots=2, window=4, grammar=False, packed=False,
           overlap=False, kv_paging=False, fused=False, lp_out=None,
           logprobs_k=0):
    """Run *trace* — a list of ``(arrival_iteration, key, kwargs)`` —
    through an IterationScheduler and return {key: tokens}.  Fully
    deterministic: arrivals keyed to iteration indices, dwell off.
    *lp_out* (optional dict) collects each key's logprob records at
    retirement, for the fused logprob-harvest equivalence check."""
    eng = ServingEngine(model, params, n_slots=n_slots, chunk=4,
                        eos_id=EOS if grammar else None,
                        max_new_tokens=max_new, auto_prefix_min=4,
                        grammar=dfa if grammar else None,
                        kv_paging=kv_paging, fused_decode=fused,
                        logprobs_k=logprobs_k)
    intake: deque = deque()
    tickets = {}
    live = {}
    results = {}

    def pull():
        if not intake:
            return None
        key, kwargs = intake.popleft()
        t = sched.begin(**kwargs)
        tickets[t] = key
        return t

    sched = IterationScheduler(eng, window=window, interleave=interleave,
                               prefill_budget=2, pull=pull,
                               packed_prefill=packed, overlap=overlap,
                               sync_dwell_s=0.0)
    arrivals = sorted(trace, key=lambda a: a[0])
    ai = 0
    for i in range(200):
        while ai < len(arrivals) and arrivals[ai][0] <= i:
            intake.append(arrivals[ai][1:])
            ai += 1
        res = sched.iterate()
        for t in res.admitted:
            live[t.slot] = tickets.pop(t)
        for slot in list(live):
            if eng.finished(slot):
                key = live.pop(slot)
                results[key] = eng.output(slot)
                if lp_out is not None:
                    lp_out[key] = eng.token_logprobs(slot)
        if (ai == len(arrivals) and not intake and not live
                and not sched.busy()):
            break
    assert len(results) == len(trace), "trace did not drain"
    return results


def _assert_equivalent(model, params, dfa, trace, **kw):
    on = _drive(model, params, dfa, trace, interleave=True, **kw)
    off = _drive(model, params, dfa, trace, interleave=False, **kw)
    assert on == off
    return on


def _assert_packed_overlap_equivalent(model, params, dfa, trace,
                                      **kw):
    """The FULL toggle matrix: every (packed, overlap) combination —
    with interleave on and off — must produce the serial baseline's
    exact streams."""
    base = _assert_equivalent(model, params, dfa, trace, **kw)
    for packed in (False, True):
        for overlap in (False, True):
            for interleave in (True, False):
                got = _drive(model, params, dfa, trace,
                             interleave=interleave, packed=packed,
                             overlap=overlap, **kw)
                assert got == base, (
                    f"streams diverged at packed={packed} "
                    f"overlap={overlap} interleave={interleave}")
    return base


def test_equivalence_greedy_apc_hit_and_miss(setup):
    # distinct prompts (APC miss), an exact repeat (full-prompt hit,
    # the zero-extend path), and a shared-prefix prompt (partial
    # chunk-floored hit) — all mid-trace, slots recycling throughout
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]    # 2 chunks of 4
    pb = [2, 71, 82, 81, 82]                # miss vs pa
    trace = [
        (0, "a0", dict(prompt=pa)),
        (0, "b0", dict(prompt=pb)),
        (1, "a1", dict(prompt=pa)),          # exact repeat -> full hit
        (2, "ash", dict(prompt=pa[:4] + [9, 9])),   # shared chunk
        (4, "b1", dict(prompt=pb)),
        (5, "a2", dict(prompt=pa)),
    ]
    on = _assert_equivalent(model, params, dfa, trace)
    # and every stream equals the solo oracle (the scheduler can never
    # bend tokens, only schedule them)
    for key, prompt in (("a0", pa), ("a1", pa), ("a2", pa), ("b0", pb)):
        assert on[key] == _solo(model, params, prompt, 6)


def test_equivalence_seeded_sampled(setup):
    # seeded sampling is scheduling-invariant by design (a seeded
    # slot's chain ignores neighbors and admission order) — the
    # interleave must preserve that bit-for-bit
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65]
    pb = [2, 71, 82]
    trace = [
        (0, "s1", dict(prompt=pa, temperature=1.0, seed=7)),
        (0, "g0", dict(prompt=pb)),
        (1, "s2", dict(prompt=pa, temperature=0.7, top_k=8, seed=41)),
        (3, "s3", dict(prompt=pa, temperature=1.0, seed=7)),
    ]
    on = _assert_equivalent(model, params, dfa, trace)
    # same seed, same prompt -> same stream, wherever it was scheduled
    assert on["s1"] == on["s3"]


def test_equivalence_grammar_constrained(setup):
    model, params, dfa = setup
    trace = [
        (0, "g1", dict(prompt=[65, 66], grammar=True)),
        (0, "u1", dict(prompt=[2, 71, 82])),
        (2, "g2", dict(prompt=[67, 68], grammar=True)),
    ]
    _assert_equivalent(model, params, dfa, trace, grammar=True,
                       max_new=8)


def test_mid_window_admission_prefills_inside_open_window(setup):
    # a request that arrives while a decode window is OPEN must begin
    # prefilling before that window closes — the whole point of
    # iteration-level scheduling (window-boundary admission was the
    # r6 gap)
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=12, auto_prefix_min=4)
    seen = {}
    pb = [2, 71, 82, 81, 82, 44, 9, 1]

    def pull():
        # a is available from the start; b only materializes while a
        # decode window is OPEN (scan dispatched, not yet harvested) —
        # exactly the mid-window arrival the r6 loop made wait for the
        # window to close
        if "a" not in seen:
            seen["a"] = sched.begin(prompt=[3, 14, 15, 92, 65])
            return seen["a"]
        if "b" not in seen and eng.scan_inflight:
            seen["b"] = sched.begin(prompt=pb)
            return seen["b"]
        return None

    sched = IterationScheduler(eng, window=8, interleave=True,
                               prefill_budget=8, pull=pull,
                               sync_dwell_s=0.0)
    res1 = sched.iterate()           # admits a + first window: b
    res2 = sched.iterate()           # arrives while it is open
    assert "b" in seen
    assert res1.steps > 0            # a window ran
    tb = seen["b"]
    assert tb.mid_window, "b was not admitted inside the open window"
    assert tb.chunks_done == tb.chunks_total > 0
    # finalized before that window's harvest (same-iteration admit)
    assert tb in res1.admitted + res2.admitted
    assert eng.active[tb.slot]
    # and the stream is still the oracle's
    out_b = None
    for _ in range(30):
        sched.iterate()
        if eng.finished(tb.slot):
            out_b = eng.output(tb.slot)
            break
    assert out_b == _solo(model, params, pb, 12)


def test_begin_step_finish_equals_one_shot_admit(setup):
    model, params, dfa = setup
    prompt = [3, 14, 15, 92, 65, 35, 89]   # 2 chunks
    one = ServingEngine(model, params, n_slots=2, chunk=4)
    split = ServingEngine(model, params, n_slots=2, chunk=4)
    s1 = one.admit(prompt)
    st = split.begin_admit(prompt)
    assert split.free_slots() == [1]       # slot 0 reserved
    steps = 0
    while split.admit_step(st):
        steps += 1
    assert st.chunks_total == 2
    s2 = split.finish_admit(st)
    assert s1 == s2
    one.run(6)
    split.run(6)
    assert one.output(s1) == split.output(s2)


def test_abort_admit_frees_the_reserved_slot(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, chunk=4)
    st = eng.begin_admit([3, 14, 15, 92, 65])
    with pytest.raises(RuntimeError):
        eng.begin_admit([1, 2])            # engine full (reserved)
    eng.abort_admit(st)
    s = eng.admit([1, 2])                  # slot is back
    assert s == 0


def test_full_prompt_apc_admits_with_zero_extends(setup):
    # an exact repeat of a resident prompt is pure data movement: no
    # prefill extends run at all (prefill_tokens frozen), the donor's
    # free slot is reused in place, and tokens stay bit-identical
    model, params, dfa = setup
    prompt = [3, 14, 15, 92, 65, 35, 89, 79]
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix_min=4)
    s0 = eng.admit(prompt)
    eng.run(6)
    first_run = eng.output(s0)
    eng.release(s0)
    before = eng.stats()["prefill_tokens"]
    s1 = eng.admit(prompt)
    assert s1 == s0                        # prefix-affinity placement
    assert eng.stats()["prefill_tokens"] == before   # ZERO extends
    assert eng.stats()["prefix_reused_tokens"] >= len(prompt)
    eng.run(6)
    assert eng.output(s1) == first_run
    assert eng.output(s1)[:6] == _solo(model, params, prompt, 6)


def test_full_prompt_apc_copy_path_when_donor_slot_busy(setup):
    # the donor is still ACTIVE: the repeat admits into another slot
    # via the row-copy path, still with zero extends, still oracle-
    # exact
    model, params, dfa = setup
    prompt = [3, 14, 15, 92, 65, 35, 89, 79]
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        auto_prefix_min=4)
    s0 = eng.admit(prompt)
    before = eng.stats()["prefill_tokens"]
    s1 = eng.admit(prompt)
    assert s1 != s0
    assert eng.stats()["prefill_tokens"] == before
    eng.run(6)
    assert eng.output(s0) == eng.output(s1)
    assert eng.output(s0)[:6] == _solo(model, params, prompt, 6)


def test_prefix_chunk_knob(setup):
    model, params, dfa = setup
    # auto: the APC grid caps at 32 (max_len 64 -> 32, as before)
    assert ServingEngine(model, params, n_slots=1).chunk == 32
    # explicit grid
    assert ServingEngine(model, params, n_slots=1,
                         prefix_chunk=16).chunk == 16
    # prefix_chunk must divide max_len (padding may never overflow)
    with pytest.raises(ValueError, match="divide"):
        ServingEngine(model, params, n_slots=1, prefix_chunk=24)
    # an explicit chunk already pins the grid
    with pytest.raises(ValueError, match="not both"):
        ServingEngine(model, params, n_slots=1, chunk=8,
                      prefix_chunk=16)
    # None keeps the coarse (128-cap) grid
    assert ServingEngine(model, params, n_slots=1,
                         prefix_chunk=None).chunk == 32  # 64//2
    # ... and the finer default grid changes nothing about tokens
    prompt = [3, 14, 15, 92, 65, 35, 89, 79, 12, 44]
    fine = ServingEngine(model, params, n_slots=1, prefix_chunk=8)
    sf = fine.admit(prompt)
    fine.run(6)
    assert fine.output(sf)[:6] == _solo(model, params, prompt, 6)


def test_supersede_aborts_pending_tickets(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, chunk=4)
    sched = IterationScheduler(eng, window=4, sync_dwell_s=0.0)
    t = sched.begin(prompt=[3, 14, 15, 92, 65])
    assert sched.busy() and not eng.free_slots()
    sched.supersede()
    assert not sched.busy()
    assert eng.free_slots() == [0]         # reservation released
    # the superseded generation raises out of a stale iterate
    from tpu_k8s_device_plugin.workloads.scheduler import (
        SchedulerSuperseded,
    )
    with pytest.raises(SchedulerSuperseded):
        sched._check(sched._gen - 1)
    assert t.state.result is None


def test_packed_overlap_equivalence_greedy_apc(setup):
    # the full toggle matrix over the APC-heavy greedy trace: distinct
    # prompts, an exact repeat (zero-extend full hit), a shared-chunk
    # partial hit — slots recycling, admissions packing where they
    # coincide.  Streams must be byte-identical in EVERY combination.
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]    # 2 chunks of 4
    pb = [2, 71, 82, 81, 82]                # miss vs pa
    pc = [44, 9, 1, 7, 60, 61]              # third concurrent stream
    trace = [
        (0, "a0", dict(prompt=pa)),
        (0, "b0", dict(prompt=pb)),
        (0, "c0", dict(prompt=pc)),
        (1, "a1", dict(prompt=pa)),          # exact repeat -> full hit
        (2, "ash", dict(prompt=pa[:4] + [9, 9])),   # shared chunk
        (4, "b1", dict(prompt=pb)),
        (5, "a2", dict(prompt=pa)),
    ]
    on = _assert_packed_overlap_equivalent(model, params, dfa, trace,
                                           n_slots=3)
    for key, prompt in (("a0", pa), ("b0", pb), ("c0", pc)):
        assert on[key] == _solo(model, params, prompt, 6)


def test_packed_overlap_equivalence_seeded(setup):
    # seeded sampling is scheduling-invariant by design; packing must
    # not bend the admission draw order (FIFO splices) and overlap
    # must FALL BACK to the serial cadence while sampled knobs are
    # live — either way the bytes cannot move
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65]
    pb = [2, 71, 82]
    pc = [44, 9, 1, 7]
    trace = [
        (0, "s1", dict(prompt=pa, temperature=1.0, seed=7)),
        (0, "g0", dict(prompt=pb)),
        (0, "s2", dict(prompt=pc, temperature=0.7, top_k=8, seed=41)),
        (3, "s3", dict(prompt=pa, temperature=1.0, seed=7)),
    ]
    on = _assert_packed_overlap_equivalent(model, params, dfa, trace,
                                           n_slots=3)
    assert on["s1"] == on["s3"]


def test_packed_overlap_equivalence_grammar(setup):
    model, params, dfa = setup
    trace = [
        (0, "g1", dict(prompt=[65, 66], grammar=True)),
        (0, "u1", dict(prompt=[2, 71, 82])),
        (0, "g2", dict(prompt=[67, 68], grammar=True)),
        (2, "g3", dict(prompt=[65, 66, 67, 68], grammar=True)),
    ]
    _assert_packed_overlap_equivalent(model, params, dfa, trace,
                                      grammar=True, max_new=8,
                                      n_slots=3)


def test_packed_overlap_equivalence_kv_paging(setup):
    # the paged pool under packing + overlap: packed prefill runs on
    # B=1 minis and lands through _paged_land exactly as serial
    # admission does, so paged streams must equal the contiguous
    # serial baseline bit-for-bit
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]
    pb = [2, 71, 82, 81, 82]
    trace = [
        (0, "a0", dict(prompt=pa)),
        (0, "b0", dict(prompt=pb)),
        (1, "a1", dict(prompt=pa)),          # paged zero-page repeat
        (3, "ash", dict(prompt=pa[:4] + [9, 9])),   # CoW shared chunk
    ]
    base = _assert_equivalent(model, params, dfa, trace)
    for packed in (False, True):
        for overlap in (False, True):
            got = _drive(model, params, dfa, trace, interleave=True,
                         packed=packed, overlap=overlap,
                         kv_paging=True)
            assert got == base, (
                f"paged streams diverged at packed={packed} "
                f"overlap={overlap}")


def test_admit_step_packed_equals_serial_chunks(setup):
    # engine-level: K admissions advanced through batched extends must
    # land byte-identical to chunk-serial admission, and the packed
    # counters must account the work
    model, params, dfa = setup
    prompts = ([3, 14, 15, 92, 65, 35, 89, 79, 11],   # 3 chunks
               [2, 71, 82, 81, 82],                   # 2 chunks
               [44, 9, 1, 7, 60, 61, 2])              # 2 chunks
    eng = ServingEngine(model, params, n_slots=3, chunk=4,
                        max_new_tokens=6, auto_prefix=False)
    sts = [eng.begin_admit(p) for p in prompts]
    while any(st.gen is not None for st in sts):
        group = [st for st in sts if st.gen is not None]
        if len(group) >= 2:
            eng.admit_step_packed(group)
        else:
            eng.admit_step(group[0])
    slots = [eng.finish_admit(st) for st in sts]
    eng.run(6)
    for s, p in zip(slots, prompts):
        assert eng.output(s) == _solo(model, params, p, 6)
    st = eng.stats()
    assert st["packed_prefill_extends"] >= 2
    assert st["packed_prefill_requests"] == 3
    assert st["packed_prefill_rows"] >= 2 * st["packed_prefill_extends"]
    # tail-chunk grid padding DISPATCHED THROUGH PACKS: round 2 packs
    # pb's tail (+3) and pc's tail (+1); pa's tail chunk runs serial
    # (last job standing) so its padding is not packed waste
    assert st["packed_prefill_pad_tokens"] == 4


def test_abort_during_packed_prefill(setup):
    # one admission of a packed pair is cancelled mid-pack: its slot
    # frees, the survivor's stream is untouched, and the engine stays
    # reusable (the chaos episode drives the same path over HTTP)
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79, 11]   # 3 chunks
    pb = [2, 71, 82, 81, 82, 44, 9]            # 2 chunks
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=6, auto_prefix=False)
    sa = eng.begin_admit(pa)
    sb = eng.begin_admit(pb)
    eng.admit_step_packed([sa, sb])            # one packed round
    eng.abort_admit(sb)                        # client went away
    assert eng.free_slots() == [sb.slot]
    while eng.admit_step(sa):
        pass
    slot_a = eng.finish_admit(sa)
    eng.run(6)
    assert eng.output(slot_a) == _solo(model, params, pa, 6)
    # the freed slot admits fresh work
    slot_b = eng.admit(pb)
    eng.run(6)
    assert eng.output(slot_b) == _solo(model, params, pb, 6)


def test_scheduler_cancel_during_packed_prefill(setup):
    # the scheduler surface of the same story: two tickets packing,
    # one cancelled between iterations — the other drains oracle-exact
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79, 11]
    pb = [2, 71, 82, 81, 82, 44, 9]
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=6, auto_prefix=False)
    sched = IterationScheduler(eng, window=4, packed_prefill=True,
                               overlap=True, sync_dwell_s=0.0)
    ta = sched.begin(prompt=pa)
    tb = sched.begin(prompt=pb)
    sched.cancel(tb)
    assert tb.state.result is None
    done = None
    for _ in range(40):
        res = sched.iterate()
        for t in res.admitted:
            assert t is ta
        if eng.finished(ta.slot):
            done = eng.output(ta.slot)
            break
    assert done == _solo(model, params, pa, 6)


def test_overlap_dispatches_ahead_and_falls_back_when_sampled(setup):
    # greedy steady state: after a harvested window the next one is
    # already on the device (the double-buffer).  The moment a sampled
    # request is live, dispatch-ahead must stand down (draw-chain
    # safety) — and resume once it retires.
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=16, auto_prefix_min=4)
    sched = IterationScheduler(eng, window=4, packed_prefill=True,
                               overlap=True, sync_dwell_s=0.0)
    sched.begin(prompt=[3, 14, 15, 92, 65])
    sched.iterate()
    assert sched._ahead is not None, "greedy window not dispatched ahead"
    assert eng.scan_inflight
    sched.iterate()                      # harvests + re-dispatches
    assert sched._ahead is not None
    # drain to idle: no window may be left hanging
    for _ in range(30):
        sched.iterate()
        if not any(eng.active) and not sched.busy():
            break
    assert sched._ahead is None and not eng.scan_inflight
    # sampled request -> serial cadence
    sched.begin(prompt=[2, 71, 82], temperature=1.0, seed=3)
    sched.iterate()
    assert sched._ahead is None, "sampled window was dispatched ahead"


def test_supersede_abandons_ahead_window(setup):
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=1, chunk=4,
                        max_new_tokens=16)
    sched = IterationScheduler(eng, window=4, overlap=True,
                               sync_dwell_s=0.0)
    sched.begin(prompt=[3, 14, 15, 92, 65])
    sched.iterate()
    assert sched._ahead is not None and eng.scan_inflight
    sched.supersede()                    # crash-supervisor path
    assert sched._ahead is None and not eng.scan_inflight
    eng.release(0)
    # the engine is reusable after the abandon
    s = eng.admit([2, 71, 82])
    eng.run_scan(4)
    assert len(eng.output(s)) >= 4


def test_packing_conflict_defers_shared_prefix(setup):
    # the owner-side APC guard: while a prompt's leading chunk is
    # mid-prefill, a sibling/repeat prompt reports a conflict (the
    # server defers the pull so the repeat still hits the warm donor)
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=4, auto_prefix_min=4)
    sched = IterationScheduler(eng, window=4, packed_prefill=True,
                               sync_dwell_s=0.0)
    t = sched.begin(prompt=pa)
    assert sched.packing_conflict(pa)                 # exact repeat
    assert sched.packing_conflict(pa[:4] + [9, 9])    # shared chunk
    assert not sched.packing_conflict([2, 71, 82, 81])  # distinct
    assert not sched.packing_conflict([3, 14])        # below the grid
    sched.cancel(t)
    assert not sched.packing_conflict(pa)             # nothing pending


def _assert_fused_equivalent(model, params, dfa, trace, **kw):
    """The fused-decode axis of the toggle matrix: every (packed,
    overlap, interleave) combination WITH the fused loop must produce
    the serial UNFUSED baseline's exact streams — on-device boundary
    detection and the columnar harvest may change the work, never the
    bytes."""
    base = _drive(model, params, dfa, trace, interleave=False, **kw)
    for packed in (False, True):
        for overlap in (False, True):
            for interleave in (True, False):
                got = _drive(model, params, dfa, trace, fused=True,
                             interleave=interleave, packed=packed,
                             overlap=overlap, **kw)
                assert got == base, (
                    f"fused streams diverged at packed={packed} "
                    f"overlap={overlap} interleave={interleave}")
    return base


def test_fused_equivalence_greedy_apc_and_stops(setup):
    # greedy + APC hit/miss + a stop-set request: the device boundary
    # carry must cut exactly where the host column re-scan did, with
    # slots recycling through the zero-extend repeat paths
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]    # 2 chunks of 4
    pb = [2, 71, 82, 81, 82]                # miss vs pa
    trace = [
        (0, "a0", dict(prompt=pa)),
        (0, "b0", dict(prompt=pb, stop=[22])),
        (1, "a1", dict(prompt=pa)),          # exact repeat -> full hit
        (2, "ash", dict(prompt=pa[:4] + [9, 9])),   # shared chunk
        (4, "b1", dict(prompt=pb)),
        (5, "a2", dict(prompt=pa)),
    ]
    on = _assert_fused_equivalent(model, params, dfa, trace,
                                  n_slots=3)
    for key, prompt in (("a0", pa), ("a1", pa), ("a2", pa)):
        assert on[key] == _solo(model, params, prompt, 6)


def test_fused_equivalence_seeded_sampled(setup):
    # the fused loop LIFTS the sampled dispatch-ahead stand-down, so
    # this is the combination PR 11 could not overlap: seeded sampled
    # windows dispatched ahead must still replay each seed's own
    # chain bit-for-bit, admissions and retirements notwithstanding
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65]
    pb = [2, 71, 82]
    pc = [44, 9, 1, 7]
    trace = [
        (0, "s1", dict(prompt=pa, temperature=1.0, seed=7)),
        (0, "g0", dict(prompt=pb)),
        (0, "s2", dict(prompt=pc, temperature=0.7, top_k=8, seed=41)),
        (3, "s3", dict(prompt=pa, temperature=1.0, seed=7)),
    ]
    on = _assert_fused_equivalent(model, params, dfa, trace,
                                  n_slots=3)
    assert on["s1"] == on["s3"]


def test_fused_equivalence_grammar(setup):
    # the columnar DFA walk vs the per-token host walk, mid-trace
    # admissions included
    model, params, dfa = setup
    trace = [
        (0, "g1", dict(prompt=[65, 66], grammar=True)),
        (0, "u1", dict(prompt=[2, 71, 82])),
        (0, "g2", dict(prompt=[67, 68], grammar=True)),
        (2, "g3", dict(prompt=[65, 66, 67, 68], grammar=True)),
    ]
    _assert_fused_equivalent(model, params, dfa, trace, grammar=True,
                             max_new=8, n_slots=3)


def test_fused_equivalence_logprobs(setup):
    # the bulk logprob harvest must reproduce the per-step records
    # exactly — values AND count (records stop at the finish boundary)
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65]
    pb = [2, 71, 82, 81]
    trace = [
        (0, "l1", dict(prompt=pa, logprobs=3)),
        (0, "g0", dict(prompt=pb)),
        (2, "l2", dict(prompt=pb, logprobs=2, temperature=0.9,
                       seed=13)),
    ]
    lp_base: dict = {}
    base = _drive(model, params, dfa, trace, interleave=False,
                  n_slots=3, lp_out=lp_base, logprobs_k=4)
    for interleave in (True, False):
        lp_got: dict = {}
        got = _drive(model, params, dfa, trace, fused=True,
                     interleave=interleave, packed=True, overlap=True,
                     n_slots=3, lp_out=lp_got, logprobs_k=4)
        assert got == base
        assert lp_got == lp_base
    assert all(len(lp_base[k]) == len(base[k]) for k in ("l1", "l2"))


def test_fused_equivalence_kv_paging(setup):
    # the paged pool under the fused loop: boundary cuts and the
    # columnar harvest ride block-tabled caches identically
    model, params, dfa = setup
    pa = [3, 14, 15, 92, 65, 35, 89, 79]
    pb = [2, 71, 82, 81, 82]
    trace = [
        (0, "a0", dict(prompt=pa)),
        (0, "b0", dict(prompt=pb, stop=[22])),
        (1, "a1", dict(prompt=pa)),          # paged zero-page repeat
        (3, "ash", dict(prompt=pa[:4] + [9, 9])),   # CoW shared chunk
    ]
    base = _drive(model, params, dfa, trace, interleave=False)
    for packed in (False, True):
        for overlap in (False, True):
            got = _drive(model, params, dfa, trace, interleave=True,
                         packed=packed, overlap=overlap,
                         kv_paging=True, fused=True)
            assert got == base, (
                f"fused paged streams diverged at packed={packed} "
                f"overlap={overlap}")


def test_fused_overlap_dispatches_ahead_when_sampled(setup):
    # the tentpole's scheduling payoff: with fused_decode the sampled
    # stand-down lifts — a live seeded request no longer forces the
    # serial cadence, and the double-buffered window is on the device
    # between iterations (PR 11 never got this; see the non-fused
    # fallback test above)
    model, params, dfa = setup
    eng = ServingEngine(model, params, n_slots=2, chunk=4,
                        max_new_tokens=16, auto_prefix_min=4,
                        fused_decode=True)
    sched = IterationScheduler(eng, window=4, packed_prefill=True,
                               overlap=True, sync_dwell_s=0.0)
    sched.begin(prompt=[2, 71, 82], temperature=1.0, seed=3)
    sched.iterate()
    assert sched._ahead is not None, (
        "fused sampled window was not dispatched ahead")
    assert eng.scan_inflight
    # drain clean: the overlapped sampled stream must still finish
    for _ in range(30):
        sched.iterate()
        if not any(eng.active) and not sched.busy():
            break
    assert sched._ahead is None and not eng.scan_inflight
    assert eng.stats()["fused_windows"] > 0


def test_scheduler_metrics_families_render(setup):
    # the new obs families land on the caller's registry and render
    # promlint-clean alongside everything else (the metrics-lint job
    # re-checks the full serving surface)
    from tpu_k8s_device_plugin import obs
    from tools import promlint

    model, params, dfa = setup
    reg = obs.Registry()
    eng = ServingEngine(model, params, n_slots=1, chunk=4,
                        max_new_tokens=3)
    done = []
    intake = deque([("r", dict(prompt=[3, 14, 15, 92, 65]))])

    def pull():
        if not intake:
            return None
        key, kwargs = intake.popleft()
        t = sched.begin(**kwargs)
        done.append(t)
        return t

    sched = IterationScheduler(eng, window=4, pull=pull,
                               sync_dwell_s=0.0, registry=reg)
    for _ in range(6):
        sched.iterate()
    body = reg.render()
    assert "tpu_serve_prefill_chunk_seconds" in body
    assert "tpu_serve_admit_to_first_step_seconds" in body
    assert 'tpu_serve_scheduler_queue_depth{kind="decode"}' in body
    assert "tpu_serve_overlap_idle_seconds" in body
    assert "tpu_serve_overlap_windows_total" in body
    assert promlint.lint(body) == []
