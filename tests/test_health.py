"""Health subsystem tests: probe server + client over a unix socket."""

import os

from tpu_k8s_device_plugin.health import TpuHealthServer, get_tpu_health
from tpu_k8s_device_plugin.health.server import probe_chip_states
from tpu_k8s_device_plugin.types import constants


def roots(testdata, name):
    root = os.path.join(testdata, name)
    return os.path.join(root, "sys"), os.path.join(root, "dev")


def test_probe_chip_states(testdata):
    sys_root, dev_root = roots(testdata, "v5e-8")
    states = probe_chip_states(sys_root, dev_root)
    assert len(states) == 8
    s = states["0000:00:04.0"]
    assert s.health == "Healthy" and s.accel_index == 0
    assert s.device.endswith("accel0")


def test_probe_detects_missing_dev_node(testdata, tmp_path):
    sys_root, _ = roots(testdata, "v5e-8")
    # empty dev root: every chip's node is missing -> Unhealthy
    states = probe_chip_states(sys_root, str(tmp_path))
    assert all(s.health == "Unhealthy" for s in states.values())


def test_client_server_roundtrip(testdata, tmp_path):
    sys_root, dev_root = roots(testdata, "v5e-8")
    sock = str(tmp_path / "exporter.sock")
    server = TpuHealthServer(sock, sys_root, dev_root).start()
    try:
        health = get_tpu_health(sock, timeout_s=5.0)
        assert len(health) == 8
        assert all(v == constants.HEALTHY for v in health.values())
    finally:
        server.stop()


def test_client_missing_socket_returns_empty(tmp_path):
    assert get_tpu_health(str(tmp_path / "nope.sock")) == {}


def test_client_dead_socket_returns_empty(tmp_path):
    sock = str(tmp_path / "dead.sock")
    open(sock, "w").close()  # a plain file, not a listening socket
    assert get_tpu_health(sock, timeout_s=0.5) == {}
