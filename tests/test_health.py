"""Health subsystem tests: probe server + client over a unix socket."""

import errno
import os
import shutil

import pytest

from tpu_k8s_device_plugin.health import TpuHealthServer, get_tpu_health
from tpu_k8s_device_plugin.health import server as health_server
from tpu_k8s_device_plugin.health.server import probe_chip_states
from tpu_k8s_device_plugin.types import constants


def roots(testdata, name):
    root = os.path.join(testdata, name)
    return os.path.join(root, "sys"), os.path.join(root, "dev")


@pytest.fixture
def v5e8_copy(testdata, tmp_path):
    """Mutable copy of the v5e-8 tree (symlinks preserved — they're
    relative, so the copied sysfs stays internally consistent)."""
    dst = tmp_path / "v5e-8"
    shutil.copytree(os.path.join(testdata, "v5e-8"), dst, symlinks=True)
    return str(dst)


def test_probe_chip_states(testdata):
    sys_root, dev_root = roots(testdata, "v5e-8")
    states = probe_chip_states(sys_root, dev_root)
    assert len(states) == 8
    s = states["0000:00:04.0"]
    assert s.health == "Healthy" and s.accel_index == 0
    assert s.device.endswith("accel0")


def test_probe_detects_missing_dev_node(testdata, tmp_path):
    sys_root, _ = roots(testdata, "v5e-8")
    # empty dev root: every chip's node is missing -> Unhealthy
    states = probe_chip_states(sys_root, str(tmp_path))
    assert all(s.health == "Unhealthy" for s in states.values())


def test_probe_detects_wedged_chip_via_sysfs_state(v5e8_copy):
    """A chip whose chardev still opens but whose driver reports it dead
    must go Unhealthy — the state open(2) can't see (VERDICT 'health probe
    depth')."""
    attr = os.path.join(
        v5e8_copy, "sys", "devices", "pci0000:00", "0000:00:06.0",
        constants.SYSFS_CHIP_STATE,
    )
    with open(attr, "w") as f:
        f.write("dead\n")
    states = probe_chip_states(
        os.path.join(v5e8_copy, "sys"), os.path.join(v5e8_copy, "dev")
    )
    assert states["0000:00:06.0"].health == "Unhealthy"
    healthy = [s for s in states.values() if s.health == "Healthy"]
    assert len(healthy) == 7


def test_probe_detects_uncorrectable_errors(v5e8_copy):
    attr = os.path.join(
        v5e8_copy, "sys", "devices", "pci0000:00", "0000:00:09.0",
        constants.SYSFS_UE_COUNT,
    )
    with open(attr, "w") as f:
        f.write("3\n")
    states = probe_chip_states(
        os.path.join(v5e8_copy, "sys"), os.path.join(v5e8_copy, "dev")
    )
    assert states["0000:00:09.0"].health == "Unhealthy"
    assert sum(s.health == "Healthy" for s in states.values()) == 7


def test_missing_health_attrs_is_no_verdict(v5e8_copy):
    """Older drivers expose neither attr: absence must not demote."""
    for chip in range(8):
        base = os.path.join(
            v5e8_copy, "sys", "devices", "pci0000:00", f"0000:00:{4+chip:02x}.0"
        )
        os.remove(os.path.join(base, constants.SYSFS_CHIP_STATE))
        os.remove(os.path.join(base, constants.SYSFS_UE_COUNT))
    states = probe_chip_states(
        os.path.join(v5e8_copy, "sys"), os.path.join(v5e8_copy, "dev")
    )
    assert all(s.health == "Healthy" for s in states.values())


class TestNodeOpenableErrnoPolicy:
    """ADVICE (high): the TPU accel driver is single-open — a busy chip
    returns EBUSY from the probe's open(2) and MUST stay Healthy, or health
    flaps on every pulse exactly when chips are in use."""

    def _probe_with_rc(self, monkeypatch, rc):
        class FakeProbe:
            @staticmethod
            def probe_device_node(path):
                return rc
        monkeypatch.setattr(health_server, "_tpuprobe", FakeProbe)
        return health_server._node_openable("/dev/accel0")

    def test_busy_chip_is_healthy(self, monkeypatch):
        assert self._probe_with_rc(monkeypatch, -errno.EBUSY) is True

    def test_permission_denied_is_healthy(self, monkeypatch):
        # probe lacking privilege says nothing about the silicon
        assert self._probe_with_rc(monkeypatch, -errno.EACCES) is True

    @pytest.mark.parametrize(
        "err", [errno.ENOENT, errno.ENXIO, errno.ENODEV, errno.EIO]
    )
    def test_gone_chip_is_unhealthy(self, monkeypatch, err):
        assert self._probe_with_rc(monkeypatch, -err) is False

    def test_openable_is_healthy(self, monkeypatch):
        assert self._probe_with_rc(monkeypatch, 0) is True


def test_client_server_roundtrip(testdata, tmp_path):
    sys_root, dev_root = roots(testdata, "v5e-8")
    sock = str(tmp_path / "exporter.sock")
    server = TpuHealthServer(sock, sys_root, dev_root).start()
    try:
        health = get_tpu_health(sock, timeout_s=5.0)
        assert len(health) == 8
        assert all(v == constants.HEALTHY for v in health.values())
    finally:
        server.stop()


def test_client_missing_socket_returns_empty(tmp_path):
    assert get_tpu_health(str(tmp_path / "nope.sock")) == {}


def test_client_dead_socket_returns_empty(tmp_path):
    sock = str(tmp_path / "dead.sock")
    open(sock, "w").close()  # a plain file, not a listening socket
    assert get_tpu_health(sock, timeout_s=0.5) == {}
