"""End-to-end request tracing + flight recorder (PR 4).

Covers the traceparent contract (parse/format round trip, malformed
fallback), HTTP→engine propagation over a real socket (header echo,
span breadcrumbs in /debug/traces, OpenMetrics exemplars, plain-text
exposition staying exemplar-free), slice-client→coordinator propagation
over real gRPC metadata, recorder ring overflow accounting, the
SIGTERM flight-record dump (readable JSON-lines from a real subprocess),
and the slow-span WARNING escalation.
"""

import json
import logging
import math
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tools.promlint import lint
from tpu_k8s_device_plugin import obs

pytestmark = pytest.mark.filterwarnings("ignore")


# -- traceparent contract ----------------------------------------------------

def test_traceparent_roundtrip():
    ctx = obs.new_trace()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = obs.parse_traceparent(ctx.to_traceparent())
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled


def test_traceparent_child_links_parent():
    ctx = obs.new_trace()
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-span-01",
    "00-" + "0" * 32 + "-1234567890abcdef-01",   # zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",   # version ff
    "00-" + "A" * 32 + "-" + "b" * 16 + "-01",   # uppercase hex
])
def test_malformed_traceparent_falls_back_to_new_root(bad):
    assert obs.parse_traceparent(bad) is None
    ctx = obs.trace_from_header(bad)  # always yields a usable root
    assert len(ctx.trace_id) == 32 and ctx.parent_id is None


def test_wellformed_header_continues_the_trace():
    root = obs.new_trace()
    cont = obs.trace_from_header(root.to_traceparent())
    assert cont.trace_id == root.trace_id
    assert cont.parent_id == root.span_id


# -- flight recorder ---------------------------------------------------------

def test_recorder_ring_overflow_and_dropped_accounting():
    reg = obs.Registry()
    rec = obs.FlightRecorder(capacity=8, registry=reg)
    ctx = obs.new_trace()
    for i in range(20):
        rec.record("ev", trace=ctx, i=i)
    assert rec.recorded == 20
    assert rec.dropped == 12
    evs = rec.events()
    assert len(evs) == 8
    # drop-oldest: the survivors are the 8 NEWEST events
    assert [e["attrs"]["i"] for e in evs] == list(range(12, 20))
    samples = obs.parse_exposition(reg.render())
    by = {n: v for n, ls, v in samples}
    assert by["tpu_flight_events_total"] == 20
    assert by["tpu_flight_dropped_events_total"] == 12


def test_recorder_filters_and_trace_index():
    rec = obs.FlightRecorder(capacity=64)
    a, b = obs.new_trace(), obs.new_trace()
    t_mid = None
    rec.record("x", trace=a)
    t_mid = time.time()
    time.sleep(0.01)
    rec.record("y", trace=b)
    rec.record("x", trace=b)
    assert {e["name"] for e in rec.events(trace_id=b.trace_id)} == \
        {"x", "y"}
    assert all(e["t_wall"] > t_mid for e in rec.events(since=t_mid))
    idx = rec.trace_ids()
    assert idx[0]["trace_id"] == b.trace_id  # most recent first
    assert idx[0]["events"] == 2


def test_sigterm_dump_is_readable_jsonlines(tmp_path):
    """A real subprocess: install the dump handlers, record traced
    events, SIGTERM it, and assert the dump parses as JSON-lines with
    the trace id intact."""
    dump_dir = tmp_path / "flight"
    prog = f"""
import os, signal, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from tpu_k8s_device_plugin import obs
rec = obs.FlightRecorder(capacity=16)
rec.install_dump_handlers({str(dump_dir)!r})
ctx = obs.TraceContext(trace_id="ab" * 16, span_id="cd" * 8)
rec.record("tpu_serve_request", trace=ctx, outcome="ok")
rec.record("tpu_device_demoted", device="0000:00:04.0")
print("READY", flush=True)
time.sleep(30)
"""
    proc = subprocess.Popen([sys.executable, "-c", prog],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 128 + signal.SIGTERM
    dumps = [p for p in os.listdir(dump_dir)
             if p.startswith("flight-") and p.endswith(".jsonl")]
    assert len(dumps) == 1, dumps
    lines = [json.loads(line) for line in
             open(dump_dir / dumps[0], encoding="utf-8")]
    assert lines[0]["flight_record"] is True
    assert lines[0]["events"] == 2
    by_name = {rec["name"]: rec for rec in lines[1:]}
    assert by_name["tpu_serve_request"]["trace_id"] == "ab" * 16
    assert by_name["tpu_device_demoted"]["attrs"]["device"] == \
        "0000:00:04.0"


# -- span integration --------------------------------------------------------

def test_span_logs_trace_and_feeds_recorder(caplog):
    reg = obs.Registry()
    rec = obs.FlightRecorder(registry=reg)
    h = reg.histogram("tpu_tr_seconds", "T.", buckets=(1.0,))
    ctx = obs.new_trace()
    logger = logging.getLogger("test.trace.span")
    with caplog.at_level(logging.DEBUG, logger="test.trace.span"):
        obs.Span("op", histogram=h, trace=ctx, recorder=rec,
                 logger=logger).end()
    line = next(r.message for r in caplog.records
                if "span=op" in r.message)
    assert f"trace_id={ctx.trace_id}" in line
    assert f"span_id={ctx.span_id}" in line
    (ev,) = rec.events(name="op")
    assert ev["trace_id"] == ctx.trace_id
    assert ev["attrs"]["outcome"] == "ok"


def test_slow_span_escalates_to_warning(caplog):
    """The satellite bugfix: a pathological span must not vanish at
    default (INFO+) log levels — past the threshold it logs WARNING."""
    logger = logging.getLogger("test.trace.slow")
    ctx = obs.new_trace()
    with caplog.at_level(logging.INFO, logger="test.trace.slow"):
        sp = obs.Span("slow_op", trace=ctx, logger=logger,
                      slow_threshold_s=1e-9)
        time.sleep(0.002)
        sp.end()
        # under the threshold: still DEBUG, invisible at INFO
        fast = obs.Span("fast_op", logger=logger, slow_threshold_s=60.0)
        fast.end()
    warn = [r for r in caplog.records if r.levelno == logging.WARNING]
    assert len(warn) == 1 and "span=slow_op" in warn[0].message
    assert f"trace_id={ctx.trace_id}" in warn[0].message
    assert "slow_threshold_s=" in warn[0].message
    assert not any("span=fast_op" in r.message for r in caplog.records)


def test_slow_threshold_defaults_to_5x_top_bucket():
    reg = obs.Registry()
    h = reg.histogram("tpu_thr_seconds", "T.", buckets=(0.5, 2.0))
    sp = obs.Span("op", histogram=h)
    assert sp.slow_threshold_s == pytest.approx(10.0)
    assert obs.Span("op2").slow_threshold_s == 0.0  # no histogram


# -- promlint exemplar rules -------------------------------------------------

def test_promlint_exemplar_rules():
    base = ("# HELP h H.\n# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 1 # {trace_id="ab"} 0.5 1.0\n'
            "h_sum 0.5\nh_count 1\n")
    # plain-text exposition: the exemplar itself is the violation
    assert any("X1" in e for e in lint(base, openmetrics=False))
    # OpenMetrics (autodetected via # EOF): clean
    assert lint(base + "# EOF\n") == []
    # exemplar on a gauge line: wrong sample kind
    bad_kind = ("# HELP g G.\n# TYPE g gauge\n"
                'g 1 # {trace_id="ab"} 0.5\n# EOF\n')
    assert any("X2" in e for e in lint(bad_kind))
    # oversized exemplar label set
    big = "x" * 200
    bad_len = ("# HELP h H.\n# TYPE h histogram\n"
               f'h_bucket{{le="+Inf"}} 1 # {{trace_id="{big}"}} 0.5\n'
               "h_sum 0.5\nh_count 1\n# EOF\n")
    assert any("X3" in e for e in lint(bad_len))
    # unparseable exemplar value
    bad_val = ("# HELP h H.\n# TYPE h histogram\n"
               'h_bucket{le="+Inf"} 1 # {trace_id="ab"} notanumber\n'
               "h_sum 0.5\nh_count 1\n# EOF\n")
    assert any("X4" in e for e in lint(bad_val))


def test_registry_renders_exemplars_only_in_openmetrics():
    reg = obs.Registry()
    h = reg.histogram("tpu_ex_seconds", "E.", buckets=(1.0,))
    ctx = obs.new_trace()
    h.observe(0.5, trace_id=ctx.trace_id)
    plain = reg.render()
    om = reg.render(openmetrics=True)
    assert "# {" not in plain and lint(plain) == []
    assert f'trace_id="{ctx.trace_id}"' in om
    assert om.rstrip().endswith("# EOF")
    assert lint(om) == []
    # the exemplar sits on the bucket the observation landed in
    line = next(ln for ln in om.splitlines()
                if ln.startswith('tpu_ex_seconds_bucket{le="1"}'))
    assert "# {" in line


# -- HTTP -> engine propagation over a real socket ---------------------------

@pytest.fixture(scope="module")
def traced_server():
    jnp = pytest.importorskip("jax.numpy")
    import jax

    from tpu_k8s_device_plugin.workloads.inference import make_decoder
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    from tpu_k8s_device_plugin.workloads.serving import ServingEngine

    model = make_decoder(vocab=128, d_model=64, n_heads=4, n_layers=2,
                         d_ff=128, max_len=64, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=4, window=2)
    srv.start(host="127.0.0.1", port=0)
    yield srv
    srv.stop()


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read().decode()


def test_http_trace_propagates_to_engine_and_debug(traced_server):
    srv = traced_server
    root = obs.new_trace()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/generate",
        data=json.dumps({"tokens": [1, 2, 3]}).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": root.to_traceparent()})
    with urllib.request.urlopen(req, timeout=120) as resp:
        # the trace-id comes back in BOTH header forms
        assert resp.headers["X-Trace-Id"] == root.trace_id
        echoed = obs.parse_traceparent(resp.headers["traceparent"])
        assert echoed.trace_id == root.trace_id
        resp.read()
    # the whole server-side path left breadcrumbs under the ONE id:
    # admission -> queue wait -> run_scan windows -> stream writes
    _, _, body = _get(srv.port,
                      f"/debug/traces?trace_id={root.trace_id}")
    events = json.loads(body)["events"]
    names = {e["name"] for e in events}
    for want in ("tpu_serve_queue_wait", "tpu_serve_admit",
                 "tpu_serve_ttft", "tpu_serve_window",
                 "tpu_serve_stream_write", "tpu_serve_request"):
        assert want in names, (want, names)
    assert all(e["trace_id"] == root.trace_id for e in events)
    # terminal span records the outcome
    done = [e for e in events if e["name"] == "tpu_serve_request"]
    assert done and done[-1]["attrs"]["outcome"] == "ok"
    # the index view lists the trace
    _, _, body = _get(srv.port, "/debug/traces")
    assert any(t["trace_id"] == root.trace_id
               for t in json.loads(body)["traces"])
    # /debug/events?since= filters on wall time
    _, _, body = _get(srv.port, "/debug/events?since=0")
    assert json.loads(body)["events"]
    far_future = time.time() + 3600
    _, _, body = _get(srv.port, f"/debug/events?since={far_future}")
    assert json.loads(body)["events"] == []


def test_http_exemplars_only_under_openmetrics(traced_server):
    srv = traced_server
    root = obs.new_trace()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/generate",
        data=json.dumps({"tokens": [2, 3, 4], "stream": False}).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": root.to_traceparent()})
    with urllib.request.urlopen(req, timeout=120) as resp:
        resp.read()
    # plain exposition: no exemplars, promlint-clean, classic type
    status, headers, plain = _get(srv.port, "/metrics")
    assert headers["Content-Type"].startswith("text/plain")
    assert "# {" not in plain
    assert lint(plain) == [], lint(plain)[:5]
    # OpenMetrics: exemplar carries the LAST trace through that bucket
    status, headers, om = _get(
        srv.port, "/metrics",
        headers={"Accept": "application/openmetrics-text"})
    assert "openmetrics" in headers["Content-Type"]
    assert f'trace_id="{root.trace_id}"' in om
    assert om.rstrip().endswith("# EOF")
    assert lint(om) == [], lint(om)[:5]
    # exemplars live on the serve histograms the issue names
    assert any(ln.startswith("tpu_serve_ttft_seconds_bucket")
               and "# {" in ln for ln in om.splitlines())


def test_openai_id_carries_trace_id(traced_server):
    srv = traced_server

    class _Tok:
        def encode(self, s):
            return [ord(c) % 100 for c in s]

        def decode(self, ids):
            return "".join(chr(97 + int(i) % 26) for i in ids)

    srv.tokenizer = _Tok()
    try:
        root = obs.new_trace()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/completions",
            data=json.dumps({"prompt": "hi", "max_tokens": 2,
                             "temperature": 0}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": root.to_traceparent()})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        # the completion id IS the trace id — no mapping table needed
        assert out["id"] == f"cmpl-{root.trace_id}"
    finally:
        srv.tokenizer = None


def test_malformed_header_gets_fresh_root_over_http(traced_server):
    srv = traced_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/generate",
        data=json.dumps({"tokens": [1, 2], "stream": False}).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": "not-a-traceparent"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        tid = resp.headers["X-Trace-Id"]
        resp.read()
    assert tid and len(tid) == 32  # a fresh, valid root


# -- slice client -> coordinator propagation over real gRPC ------------------

def test_slice_trace_propagates_client_to_coordinator(tmp_path):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from tpu_k8s_device_plugin.slice import SliceClient, SliceCoordinator

    reg = obs.Registry()
    coord_rec = obs.FlightRecorder(registry=reg)
    coordinator = SliceCoordinator(
        expected_workers=2, bind_address="127.0.0.1:0",
        state_path=str(tmp_path / "membership.json"),
        recorder=coord_rec).start()
    address = f"127.0.0.1:{coordinator.port}"
    clients = []
    try:
        client_rec = obs.FlightRecorder()
        for i, name in enumerate(("host-a", "host-b")):
            clients.append(SliceClient(
                rendezvous_address=address, hostname=name, coords=(i,),
                chip_count=4,
                state_path=str(tmp_path / f"{name}.json"),
                recorder=client_rec if name == "host-a" else None))
        ctx = obs.new_trace()
        # first beat: host-a's join attempt (slice not formed yet)
        clients[0].heartbeat_now(trace=ctx)
        # host-b completes formation
        clients[1].heartbeat_now(trace=obs.new_trace())
        # host-a joins the formed slice and heartbeats, same trace
        clients[0].heartbeat_now(trace=ctx)
        assert clients[0].membership is not None
        # the coordinator's journal carries host-a's trace id on both
        # the join and the heartbeat — cross-process, via gRPC metadata
        joins = coord_rec.events(name="tpu_slice_join",
                                 trace_id=ctx.trace_id)
        beats = coord_rec.events(name="tpu_slice_heartbeat",
                                 trace_id=ctx.trace_id)
        assert joins and beats
        assert all(e["attrs"]["hostname"] == "host-a"
                   for e in joins + beats)
        # the client journaled its adopted membership under the trace
        adopted = client_rec.events(name="tpu_slice_membership_adopted",
                                    trace_id=ctx.trace_id)
        assert adopted and adopted[0]["attrs"]["workers"] == 2
    finally:
        for c in clients:
            c.stop()
        coordinator.stop()


def test_untraced_slice_rpcs_still_get_a_root(tmp_path):
    pytest.importorskip("grpc")
    from tpu_k8s_device_plugin.slice import SliceClient, SliceCoordinator

    coord_rec = obs.FlightRecorder()
    coordinator = SliceCoordinator(
        expected_workers=1, bind_address="127.0.0.1:0",
        state_path=None, recorder=coord_rec).start()
    client = SliceClient(
        rendezvous_address=f"127.0.0.1:{coordinator.port}",
        hostname="solo", coords=(0,), chip_count=1, state_path=None)
    try:
        client.heartbeat_now()  # no explicit trace anywhere
        joins = coord_rec.events(name="tpu_slice_join")
        assert joins and len(joins[0]["trace_id"]) == 32
    finally:
        client.stop()
        coordinator.stop()


def test_plugin_debug_traces_and_exemplars(testdata, tmp_path):
    """The plugin side of the acceptance: an Allocate opens a root
    trace tagged with its device ids, queryable via the DebugServer's
    /debug/traces, with an exemplar on tpu_plugin_allocate_seconds
    under the OpenMetrics scrape — and the plain scrape stays clean."""
    pytest.importorskip("grpc")
    from fake_kubelet import FakeKubelet
    from tpu_k8s_device_plugin.manager import PluginManager
    from tpu_k8s_device_plugin.observability import DebugServer
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"))
    kubelet = FakeKubelet(str(tmp_path / "device-plugins")).start()
    manager = PluginManager(impl, kubelet_dir=kubelet.dir,
                            kubelet_watch_interval_s=0.1)
    manager.run(block=False)
    debug = DebugServer(manager, port=0).start()
    try:
        assert kubelet.wait_for_registration()
        stub = kubelet.plugin_stub("google.com_tpu")
        stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[pluginapi.ContainerAllocateRequest(
                devices_ids=["0000:00:04.0"])]))
        _, _, body = _get(debug.port, "/debug/traces")
        traces = json.loads(body)["traces"]
        assert traces, "Allocate left no trace in the journal"
        tid = traces[0]["trace_id"]
        _, _, body = _get(debug.port, f"/debug/traces?trace_id={tid}")
        events = json.loads(body)["events"]
        alloc = [e for e in events
                 if e["name"] == "tpu_plugin_allocate"]
        assert alloc and "0000:00:04.0" in alloc[0]["attrs"]["devices"]
        # exemplar on the allocate histogram, OpenMetrics only
        _, headers, om = _get(
            debug.port, "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert "openmetrics" in headers["Content-Type"]
        assert any(
            ln.startswith("tpu_plugin_allocate_seconds_bucket")
            and f'trace_id="{tid}"' in ln for ln in om.splitlines())
        assert lint(om) == [], lint(om)[:5]
        _, headers, plain = _get(debug.port, "/metrics")
        assert "# {" not in plain and lint(plain) == []
        # the journal is counted on the same registry the scrape serves
        assert "tpu_flight_events_total" in plain
    finally:
        debug.stop()
        manager.stop()
        kubelet.stop()


def test_histogram_quantile_still_works_on_openmetrics_body():
    """The bench parses /metrics bodies; exemplar tails and # EOF must
    not confuse the parser/quantile path."""
    reg = obs.Registry()
    h = reg.histogram("tpu_p_seconds", "P.", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.7):
        h.observe(v, trace_id=obs.new_trace().trace_id)
    samples = obs.parse_exposition(reg.render(openmetrics=True))
    q = obs.histogram_quantile(samples, "tpu_p_seconds", 0.5)
    assert not math.isnan(q) and 0.0 < q <= 1.0
