"""obs core unit tests: registry/renderer invariants (promlint-clean by
construction), histogram bucketing + quantile estimation, span timing,
and the exposition parser the bench reads percentiles back through."""

import logging
import math
import threading

import pytest

from tools.promlint import lint
from tpu_k8s_device_plugin import obs


def test_counter_requires_total_suffix():
    r = obs.Registry()
    with pytest.raises(ValueError):
        r.counter("tpu_things", "Things.")
    c = r.counter("tpu_things_total", "Things.")
    c.inc()
    c.inc(2)
    assert c.value == 3


def test_kind_and_label_mismatch_raise():
    r = obs.Registry()
    r.gauge("tpu_x", "X.", ("a",))
    with pytest.raises(ValueError):
        r.counter("tpu_x", "X.")  # kind drift
    with pytest.raises(ValueError):
        r.gauge("tpu_x", "X.", ("b",))  # label drift
    # same signature returns the same family
    assert r.gauge("tpu_x", "X.", ("a",)) is r.gauge("tpu_x", "X.", ("a",))


def test_labels_must_match_declared_names():
    r = obs.Registry()
    c = r.counter("tpu_y_total", "Y.", ("kind",))
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    c.labels(kind="x").inc()
    assert c.labels(kind="x").value == 1


def test_render_is_promlint_clean_and_escaped():
    r = obs.Registry()
    r.counter("tpu_esc_total", "Weird \\ help\nline.", ("v",)).labels(
        v='quote " backslash \\ newline \n done').inc()
    r.gauge("tpu_esc_up", "Up.").set(1)
    h = r.histogram("tpu_esc_seconds", "H.", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(50)
    text = r.render()
    assert lint(text) == []
    # parse round-trip recovers the escaped label value
    samples = obs.parse_exposition(text)
    (labels,) = [ls for n, ls, _ in samples if n == "tpu_esc_total"]
    assert labels["v"] == 'quote " backslash \\ newline \n done'


def test_histogram_buckets_and_quantiles():
    r = obs.Registry()
    h = r.histogram("tpu_q_seconds", "Q.", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    h.observe_n(20.0, 2)  # bulk observe lands in +Inf
    samples = obs.parse_exposition(r.render())
    by = {(n, ls.get("le")): v for n, ls, v in samples}
    assert by[("tpu_q_seconds_bucket", "0.1")] == 1
    assert by[("tpu_q_seconds_bucket", "1")] == 3
    assert by[("tpu_q_seconds_bucket", "10")] == 4
    assert by[("tpu_q_seconds_bucket", "+Inf")] == 6
    assert by[("tpu_q_seconds_count", None)] == 6
    # interpolated median: target 3 of 6 → upper edge of the (0.1, 1]
    # bucket
    assert obs.histogram_quantile(samples, "tpu_q_seconds", 0.5) == \
        pytest.approx(1.0)
    # q=1 lands in +Inf → clamps to the highest finite bound
    assert obs.histogram_quantile(samples, "tpu_q_seconds", 1.0) == 10.0
    # absent series → NaN
    assert math.isnan(obs.histogram_quantile(samples, "tpu_nope", 0.5))


def test_histogram_quantile_label_filter_and_aggregate():
    r = obs.Registry()
    h = r.histogram("tpu_o_seconds", "O.", ("outcome",), buckets=(1.0,))
    h.labels(outcome="ok").observe(0.5)
    h.labels(outcome="error").observe(100.0)
    samples = obs.parse_exposition(r.render())
    assert obs.histogram_quantile(
        samples, "tpu_o_seconds", 0.5, match={"outcome": "ok"}) <= 1.0
    # unfiltered aggregates both children
    agg = obs.histogram_quantile(samples, "tpu_o_seconds", 0.99)
    assert agg == 1.0  # +Inf clamps to highest finite bound


def test_clear_drops_stale_series():
    r = obs.Registry()
    g = r.gauge("tpu_stale", "S.", ("chip",))
    g.labels(chip="a").set(1)
    g.clear()
    g.labels(chip="b").set(1)
    text = r.render()
    assert 'chip="a"' not in text and 'chip="b"' in text


def test_collector_runs_at_render_and_failures_are_contained():
    r = obs.Registry()
    g = r.gauge("tpu_fresh", "F.")
    r.on_collect(lambda: g.set(42))

    def boom():
        raise RuntimeError("collector bug")

    r.on_collect(boom)
    text = r.render()  # must not raise
    assert "tpu_fresh 42" in text


def test_concurrent_observes_keep_totals_consistent():
    r = obs.Registry()
    c = r.counter("tpu_conc_total", "C.")
    h = r.histogram("tpu_conc_seconds", "H.", buckets=(0.5,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    samples = obs.parse_exposition(r.render())
    by = {(n, ls.get("le")): v for n, ls, v in samples}
    assert by[("tpu_conc_seconds_count", None)] == 8000
    assert by[("tpu_conc_seconds_bucket", "+Inf")] == 8000
    assert lint(r.render()) == []


def test_span_observes_histogram_and_logs_request_id(caplog):
    r = obs.Registry()
    h = r.histogram("tpu_span_seconds", "S.", ("outcome",),
                    buckets=(60.0,))
    logger = logging.getLogger("test.span")
    with caplog.at_level(logging.DEBUG, logger="test.span"):
        with obs.span("demo_op", histogram=h, request_id="req-7",
                      logger=logger) as sp:
            sp.annotate(items=3)
    line = next(rec.message for rec in caplog.records
                if "span=demo_op" in rec.message)
    assert "request_id=req-7" in line
    assert "outcome=ok" in line and "items=3" in line
    samples = obs.parse_exposition(r.render())
    by = {(n, ls.get("outcome")): v for n, ls, v in samples}
    assert by[("tpu_span_seconds_count", "ok")] == 1


def test_span_error_outcome_and_idempotent_end():
    r = obs.Registry()
    h = r.histogram("tpu_span2_seconds", "S.", ("outcome",),
                    buckets=(60.0,))
    with pytest.raises(RuntimeError):
        with obs.span("failing", histogram=h):
            raise RuntimeError("boom")
    sp = obs.Span("twice", histogram=h)
    sp.end(outcome="ok")
    sp.end(outcome="ok")  # second end must not re-observe
    samples = obs.parse_exposition(r.render())
    by = {(n, ls.get("outcome")): v for n, ls, v in samples}
    assert by[("tpu_span2_seconds_count", "error")] == 1
    assert by[("tpu_span2_seconds_count", "ok")] == 1


def test_promlint_rejects_the_old_renderer_mistakes():
    """The violations PR 3's satellite fixed must actually be caught:
    TYPE-without-HELP and counters without _total (the old impl-counter
    rendering), and histograms missing +Inf."""
    old_style = ("# TYPE tpu_plugin_degraded_bounds_allocations counter\n"
                 "tpu_plugin_degraded_bounds_allocations 1\n")
    errs = lint(old_style)
    assert any("(C1)" in e for e in errs)
    assert any("(H1)" in e for e in errs)
    no_inf = ("# HELP h H.\n# TYPE h histogram\n"
              'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n')
    assert any("(B2)" in e for e in lint(no_inf))
