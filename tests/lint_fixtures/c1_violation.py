"""C1 seeded violation: two locks taken in opposite orders."""

import threading


class Crossed:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()

    def forward(self):
        with self._alpha_lock:
            with self._beta_lock:
                return 1

    def backward(self):
        with self._beta_lock:
            with self._alpha_lock:
                return 2
