# tpulint: deterministic-path
"""D1 seeded violation: global RNG + wall clock inside a declared
deterministic path."""

import random
import time


def draw():
    jitter = random.random()
    stamp = time.time()
    return jitter, stamp
