"""C2 seeded violation: unbounded blocking while a lock is held."""

import threading
import time


class Stall:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()

    def sleepy(self):
        with self._lock:
            time.sleep(1.0)

    def device_sync(self, x):
        with self._lock:
            x.block_until_ready()

    def forever(self):
        with self._lock:
            self._done.wait()
