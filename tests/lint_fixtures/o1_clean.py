"""O1 clean twin: Registry-built families, promlint-valid names,
bounded labels."""

from tpu_k8s_device_plugin import obs


def build(reg: obs.Registry):
    requests = reg.counter("tpu_fixture_requests_total",
                           "well-formed counter", ("op",))
    inflight = reg.gauge("tpu_fixture_inflight",
                         "well-formed gauge")
    latency = reg.histogram("tpu_fixture_latency_seconds",
                            "well-formed histogram",
                            buckets=obs.LATENCY_BUCKETS_S)
    return requests, inflight, latency
