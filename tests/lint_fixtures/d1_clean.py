# tpulint: deterministic-path
"""D1 clean twin: a seeded Random instance and caller-injected time."""

import random


def draw(seed: int, now: float):
    rng = random.Random(seed)
    return rng.random(), now
