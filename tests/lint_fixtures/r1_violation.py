"""R1 seeded violation: a naked subprocess boundary — no retry, no
breaker, no fault hook; its failure path cannot be provoked."""

import subprocess


def naked_probe():
    return subprocess.run(["true"], check=False)
