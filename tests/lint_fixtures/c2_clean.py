"""C2 clean twin: the blocking work happens outside the lock, and
waits under a lock carry a timeout."""

import threading
import time


class NoStall:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()

    def sleepy(self):
        with self._lock:
            step = self._next_step()
        time.sleep(step)

    def bounded(self):
        with self._lock:
            self._done.wait(0.5)

    def _next_step(self):
        return 0.01
