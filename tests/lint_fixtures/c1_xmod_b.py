"""C1 cross-module half B: holds module lock B, calls back into A —
together with half A this closes an inter-module lock cycle."""

import threading

_b_lock = threading.Lock()


def lock_b_then_call_a():
    with _b_lock:
        lock_a_inner()


def lock_b_inner():
    with _b_lock:
        return 2
