"""Pragma contract fixture: a pragma with NO justification text must
not suppress anything and is itself a P1 finding."""

import threading
import time

_lock = threading.Lock()


def unjustified():
    with _lock:
        # tpulint: disable=C2
        time.sleep(0.001)
