"""R2 seeded violation: the classic silent swallow."""


def swallow(fn):
    try:
        return fn()
    except Exception:
        pass
    return None
