"""O1 seeded violations: a family constructed outside any Registry, a
counter without its _total suffix, and an unbounded-cardinality
label at the definition site."""

from tpu_k8s_device_plugin import obs


def build(reg):
    direct = obs.Counter("tpu_fixture_direct_total",
                         "constructed outside a Registry")
    unsuffixed = reg.counter("tpu_fixture_requests",
                             "counter missing _total")
    leaky = reg.gauge("tpu_fixture_inflight",
                      "per-request label cardinality",
                      ("request_id",))
    return direct, unsuffixed, leaky
