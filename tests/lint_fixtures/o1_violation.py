"""O1 seeded violations: a family constructed outside any Registry, a
counter without its _total suffix, an unbounded-cardinality label at
the definition site, a request-supplied identity label, and a
tpu_slo_* family defined outside obs.slo (the module whose
SLOAccountant bounds class/tenant label values)."""

from tpu_k8s_device_plugin import obs


def build(reg):
    direct = obs.Counter("tpu_fixture_direct_total",
                         "constructed outside a Registry")
    unsuffixed = reg.counter("tpu_fixture_requests",
                             "counter missing _total")
    leaky = reg.gauge("tpu_fixture_inflight",
                      "per-request label cardinality",
                      ("request_id",))
    identity = reg.counter("tpu_fixture_calls_total",
                           "caller-chosen identity as a label",
                           ("user",))
    rogue_slo = reg.counter("tpu_slo_rogue_total",
                            "tpu_slo_* family outside obs.slo",
                            ("met",))
    return direct, unsuffixed, leaky, identity, rogue_slo
