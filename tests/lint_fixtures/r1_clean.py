"""R1 clean twin: the same boundary routed through RetryPolicy (and a
fault hook, chaos-harness style)."""

import subprocess

from tpu_k8s_device_plugin.resilience import RetryPolicy, faults


def covered_probe():
    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("fixture.probe")
    policy = RetryPolicy(max_attempts=2, seed=0)
    return policy.call(
        lambda: subprocess.run(["true"], check=False),
        op="fixture.probe")
