"""O2 seeded violations: alert-rule expressions over a misspelled
family, a family nothing defines, and an expression outside the tsdb
grammar — each one an alert that would sit at 'no data' forever."""

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.obs.alerts import AlertCondition, AlertRule


def build(reg: obs.Registry):
    reg.gauge("tpu_fixture_queue_depth", "the real family")
    typo = obs.threshold_rule(
        "queue_deep", "tpu_fixture_queue_depht", ">", 100.0)
    phantom = AlertRule(
        "phantom", (AlertCondition(
            "rate(tpu_fixture_never_defined_total[5m])", ">", 0.5),),
        severity="page")
    malformed = AlertCondition(expr="not a selector (", op=">",
                               threshold=1.0)
    return typo, phantom, malformed
