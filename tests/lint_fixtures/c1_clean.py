"""C1 clean twin: nested acquisition, but always the same order."""

import threading


class Ordered:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()

    def forward(self):
        with self._alpha_lock:
            with self._beta_lock:
                return 1

    def backward(self):
        with self._alpha_lock:
            with self._beta_lock:
                return 2
