"""C3 seeded violation: a non-daemon thread nobody ever joins."""

import threading


def fire_and_forget():
    t = threading.Thread(target=print)
    t.start()
    return t
