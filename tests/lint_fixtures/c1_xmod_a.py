"""C1 cross-module half A: holds module lock A, calls into module B."""

import threading

_a_lock = threading.Lock()


def lock_a_then_call_b():
    with _a_lock:
        lock_b_inner()


def lock_a_inner():
    with _a_lock:
        return 1
