"""Pragma contract fixture: a justified pragma whose violation is gone
— clean by default, a P2 finding under --strict."""


def harmless():
    # tpulint: disable=C2 -- fixture: the sleep this excused was deleted
    return 42
