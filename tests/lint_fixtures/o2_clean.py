"""O2 clean twin: every literal alert-rule expression references a
family the same project's Registry defines (including a histogram's
derived _bucket series), plus a justified pragma on an intentionally
external family."""

from tpu_k8s_device_plugin import obs


def build(reg: obs.Registry):
    depth = reg.gauge("tpu_fixture_queue_depth", "bounded gauge")
    errors = reg.counter("tpu_fixture_errors_total", "error counter")
    latency = reg.histogram("tpu_fixture_wait_seconds", "wait time",
                            buckets=obs.FAST_BUCKETS_S)
    rules = [
        obs.threshold_rule(
            "queue_deep", "tpu_fixture_queue_depth", ">", 100.0),
        obs.threshold_rule(
            "errors_hot", "rate(tpu_fixture_errors_total[5m])",
            ">", 0.5, severity="page"),
        obs.threshold_rule(
            "slow_waits",
            "histogram_quantile(0.99, tpu_fixture_wait_seconds[5m])",
            ">", 1.0),
        obs.threshold_rule(
            "peer_down",
            # tpulint: disable=O2 -- a peer process defines tpu_peer_up
            "tpu_peer_up", "<", 1.0),
    ]
    return depth, errors, latency, rules
