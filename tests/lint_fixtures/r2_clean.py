"""R2 clean twin: every handling shape the rule accepts — log,
re-raise, and resilience.suppressed() accounting."""

import logging

from tpu_k8s_device_plugin.resilience import suppressed

log = logging.getLogger(__name__)


def logged(fn):
    try:
        return fn()
    except Exception as e:
        log.warning("fixture call failed: %s", e)
    return None


def reraised(fn):
    try:
        return fn()
    except Exception as e:
        raise RuntimeError("wrapped") from e


def accounted(fn):
    try:
        return fn()
    except Exception as e:
        suppressed("fixture.accounted", e, logger=log)
    return None
