"""Pragma contract fixture: a real violation suppressed by a justified
pragma (line-above and same-line forms)."""

import threading
import time

_lock = threading.Lock()


def line_above():
    with _lock:
        # tpulint: disable=C2 -- fixture: bounded 1ms sleep on a test-local lock
        time.sleep(0.001)


def same_line():
    with _lock:
        time.sleep(0.001)  # tpulint: disable=C2 -- fixture: bounded 1ms sleep on a test-local lock
