"""C3 clean twin: every lifecycle the rule accepts — daemonized,
directly joined, attribute joined from stop(), and list-joined."""

import threading


class Owner:
    def __init__(self):
        self._thread = threading.Thread(target=print)

    def stop(self):
        self._thread.join(timeout=5.0)


def daemonized():
    threading.Thread(target=print, daemon=True).start()


def joined_local():
    t = threading.Thread(target=print)
    t.start()
    t.join()


def joined_pool():
    threads = [threading.Thread(target=print) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
