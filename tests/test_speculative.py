"""Speculative decoding: bit-exactness vs target-only greedy.

The whole value proposition is "same tokens, fewer target passes", so
the only acceptable test is token-for-token equality with
``greedy_generate`` on the target — across draft quality (a draft
sharing the target's params accepts ~everything; a random draft
accepts ~nothing; both must stay exact), gamma values, and step
counts that end mid-window.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads import llama
from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.speculative import speculative_generate

TARGET_CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DRAFT_CFG = dict(vocab=96, d_model=32, n_heads=2, n_layers=1, d_ff=64)
DT = jnp.float32


def _init(model, seed):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def models():
    target = make_decoder(**TARGET_CFG, max_len=96, dtype=DT)
    draft = make_decoder(**DRAFT_CFG, max_len=96, dtype=DT)
    return (target, _init(target, 0)), (draft, _init(draft, 1))


def _oracle(target, params, prompt, n):
    out, _ = greedy_generate(
        target, params, jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0].tolist()


@pytest.mark.parametrize("gamma", [1, 2, 4, 7])
def test_exact_vs_greedy_any_gamma(models, gamma):
    (target, tp), (draft, dp) = models
    prompt = [5, 17, 3, 70, 2, 41]
    got, rate = speculative_generate(
        target, tp, draft, dp, prompt, n_steps=12, gamma=gamma)
    assert np.asarray(got).tolist() == _oracle(target, tp, prompt, 12)
    assert 0.0 <= rate <= 1.0


def test_exact_when_draft_is_target(models):
    # a perfect draft: every proposal accepted, still exact, and the
    # accept rate must be 1.0
    (target, tp), _ = models
    prompt = [9, 1, 44, 23]
    got, rate = speculative_generate(
        target, tp, target, tp, prompt, n_steps=10, gamma=4)
    assert np.asarray(got).tolist() == _oracle(target, tp, prompt, 10)
    assert rate == 1.0


def test_exact_when_draft_is_garbage(models):
    # a draft with different random params: low accept rate, same tokens
    (target, tp), (draft, _) = models
    dp_garbage = _init(draft, 1234)
    prompt = [9, 1, 44, 23, 8]
    got, rate = speculative_generate(
        target, tp, draft, dp_garbage, prompt, n_steps=9, gamma=3)
    assert np.asarray(got).tolist() == _oracle(target, tp, prompt, 9)


def test_n_steps_not_multiple_of_window(models):
    (target, tp), (draft, dp) = models
    prompt = [2, 2, 7]
    for n in (1, 2, 5, 11):
        got, _ = speculative_generate(
            target, tp, draft, dp, prompt, n_steps=n, gamma=4)
        assert np.asarray(got).tolist() == _oracle(target, tp, prompt, n)


def test_llama_gqa_speculative(models):
    # GQA/SwiGLU target with an MHA draft — mixed architectures compose
    cfg = llama.TINY_LLAMA
    target = llama.decoder(cfg, dtype=DT, max_len=96)
    tp = _init(target, 7)
    draft = make_decoder(
        vocab=cfg.vocab, d_model=32, n_heads=2, n_layers=1, d_ff=64,
        max_len=96, dtype=DT)
    dp = _init(draft, 8)
    prompt = [3, 200, 100, 50]
    got, _ = speculative_generate(
        target, tp, draft, dp, prompt, n_steps=8, gamma=3)
    assert np.asarray(got).tolist() == _oracle(target, tp, prompt, 8)


def test_max_len_guard(models):
    (target, tp), (draft, dp) = models
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(
            target, tp, draft, dp, list(range(90)), n_steps=10, gamma=2)
