"""Mixture-of-experts tests: routing-plan invariants, exact agreement
with the per-token oracle, and expert-parallel LM training on the
virtual 8-device mesh (EP alone and EP×SP×TP combined)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.moe import (
    MoEFFN,
    moe_capacity,
    moe_ffn_oracle,
    top_k_routing,
)
from tpu_k8s_device_plugin.workloads.transformer import (
    TransformerLM,
    lm_loss,
    local_causal_attention,
    make_lm_mesh,
    make_lm_train_step,
)

TINY = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)


class TestRoutingPlan:
    def test_dispatch_invariants(self):
        B, T, E, k, C = 2, 16, 4, 2, 6
        logits = jax.random.normal(jax.random.PRNGKey(0), (B, T, E))
        dispatch, combine, aux = top_k_routing(logits, k, C)
        d = np.asarray(dispatch)
        # every capacity slot holds at most one token
        assert (d.sum(axis=1) <= 1.0 + 1e-6).all()
        # every token occupies at most k slots, at most one per expert
        assert (d.sum(axis=(2, 3)) <= k + 1e-6).all()
        assert (d.sum(axis=3) <= 1.0 + 1e-6).all()
        # combine weights are the renormalized gates: sum ≤ 1 per token
        # (< 1 only when a choice was dropped for capacity)
        c = np.asarray(combine)
        assert (c.sum(axis=(2, 3)) <= 1.0 + 1e-5).all()
        assert np.isfinite(float(aux))

    def test_capacity_overflow_drops_tokens(self):
        """All tokens prefer expert 0; capacity 2 keeps exactly 2."""
        B, T, E = 1, 8, 4
        logits = jnp.zeros((B, T, E)).at[..., 0].set(10.0)
        dispatch, _, _ = top_k_routing(logits, 1, 2)
        assert float(dispatch[..., 0, :].sum()) == 2.0

    def test_aux_loss_is_one_at_perfect_balance(self):
        """Uniform router probs and uniform routing → aux = exactly E ·
        Σ (1/E)·(1/E) = 1 (the Switch loss's minimum)."""
        B, T, E = 2, 8, 4
        # rotate argmax evenly across experts with tiny biased logits
        bias = jnp.eye(E)[jnp.arange(T) % E] * 1e-4
        logits = jnp.broadcast_to(bias, (B, T, E))
        _, _, aux = top_k_routing(logits, 1, T)
        assert abs(float(aux) - 1.0) < 1e-3

    def test_capacity_formula(self):
        assert moe_capacity(tokens=64, n_experts=8, k=2, capacity_factor=1.0) == 16
        assert moe_capacity(tokens=4, n_experts=64, k=1, capacity_factor=1.0) == 1


class TestMoEFFN:
    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_per_token_oracle_when_nothing_drops(self, k):
        """With capacity = T no token can be dropped, so the dense-dispatch
        module must agree exactly with running each token through its
        top-k experts directly."""
        B, T, D, F, E = 2, 16, 8, 32, 4
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
        ffn = MoEFFN(
            n_experts=E, d_model=D, d_ff=F, k=k, capacity=T,
            dtype=jnp.float32,
        )
        params = ffn.init(jax.random.PRNGKey(2), x)["params"]
        got = ffn.apply({"params": params}, x)
        want = moe_ffn_oracle(params, x, k=k)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_routing_is_layout_invariant_under_overflow(self):
        """With position-driven slot priority, permuting tokens+positions
        together must permute the output — even when capacity overflows
        and tokens are dropped.  This is what keeps the zig-zag sequence
        layout equivalent to the natural-order model once MoE layers are
        in the stack (transformer.py's permutation-equivalence claim)."""
        B, T, D = 2, 16, 8
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(B, T, D), jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        ffn = MoEFFN(
            n_experts=4, d_model=D, d_ff=16, k=2, capacity=3,  # tight: drops
            dtype=jnp.float32,
        )
        params = ffn.init(jax.random.PRNGKey(2), x, positions)["params"]
        natural = ffn.apply({"params": params}, x, positions)
        perm = rng.permutation(T)
        permuted = ffn.apply(
            {"params": params}, x[:, perm], positions[:, perm]
        )
        np.testing.assert_allclose(
            np.asarray(natural[:, perm]), np.asarray(permuted),
            atol=1e-5, rtol=1e-5,
        )

    def test_sows_aux_loss(self):
        B, T, D = 2, 8, 8
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
        ffn = MoEFFN(n_experts=4, d_model=D, d_ff=16, dtype=jnp.float32)
        variables = ffn.init(jax.random.PRNGKey(2), x)
        _, mut = ffn.apply(
            {"params": variables["params"]}, x, mutable="losses"
        )
        (leaf,) = jax.tree_util.tree_leaves(mut)
        assert float(leaf) > 0


class TestExpertParallelLM:
    def test_ep_training_shards_experts_and_reduces_loss(self):
        mesh = make_lm_mesh(jax.devices(), seq=1, model=2, expert=2)
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, seq_axis=None, n_experts=4, **TINY
        )
        placed = place(*state["batch"])
        params, opt_state = state["params"], state["opt_state"]
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, *placed)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # expert stacks are genuinely EP×TP sharded on device
        w = params["block_0"]["moe"]["experts_up"]
        assert tuple(w.sharding.spec) == ("expert", None, "model")
        shard = w.addressable_shards[0].data
        assert shard.shape[0] == w.shape[0] // mesh.shape["expert"]
        assert shard.shape[2] == w.shape[2] // mesh.shape["model"]

    def test_moe_on_legacy_mesh_without_expert_axis(self):
        """A mesh with no ``expert`` axis replicates the expert stacks
        instead of crashing — MoE models stay runnable on 3-axis meshes."""
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        grid = mesh_utils.create_device_mesh((2, 2, 2))
        mesh = Mesh(grid, axis_names=("data", "seq", "model"))
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, seq_axis=None, n_experts=4, **TINY
        )
        w = state["params"]["block_0"]["moe"]["experts_up"]
        assert tuple(w.sharding.spec) == (None, None, "model")
        _, _, loss = step(
            state["params"], state["opt_state"], *place(*state["batch"])
        )
        assert np.isfinite(float(loss))

    def test_ep_sp_tp_combined_matches_local_oracle(self):
        """dp=1 × expert=2 × seq=2 × model=2: the full parallelism stack
        in one jit, checked against the unsharded local-attention oracle
        (same params, same batch)."""
        mesh = make_lm_mesh(jax.devices(), seq=2, model=2, expert=2)
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, n_experts=4, **TINY
        )
        tokens, labels, positions = state["batch"]
        local_model = TransformerLM(
            attn_fn=local_causal_attention, n_experts=4, **TINY
        )
        host_params = jax.device_get(state["params"])
        want = float(lm_loss(
            local_model, host_params, tokens, labels, positions
        ))
        _, _, loss = step(
            state["params"], state["opt_state"],
            *place(tokens, labels, positions),
        )
        assert np.isclose(float(loss), want, rtol=2e-2), (float(loss), want)


def test_single_token_fast_path_matches_dense():
    """T=1 takes the gather-based serving path; it must equal the dense
    dispatch bit-for-bit in f32 (same gates, same experts, same gelu)."""
    import flax.linen as nn_  # noqa: F401

    from tpu_k8s_device_plugin.workloads.moe import MoEFFN

    rng = jax.random.PRNGKey(21)
    B, D, F, E = 4, 16, 32, 4
    ffn = MoEFFN(n_experts=E, d_model=D, d_ff=F, k=2, dtype=jnp.float32)
    x1 = jax.random.normal(rng, (B, 1, D), jnp.float32)
    params = ffn.init(rng, x1)["params"]

    got = ffn.apply({"params": params}, x1)

    # force the dense path by running the same token at T=2 (token 1 a
    # copy) with dropless capacity, then compare token 0's output
    x2 = jnp.concatenate([x1, x1], axis=1)
    dense = ffn.apply(
        {"params": params}, x2,
        jnp.broadcast_to(jnp.arange(2, dtype=jnp.int32), (B, 2)),
    )
    np.testing.assert_allclose(
        np.asarray(got[:, 0]), np.asarray(dense[:, 0]),
        atol=1e-5, rtol=1e-5,
    )
