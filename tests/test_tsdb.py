"""In-process TSDB + burn-rate alerting suite (PR 18).

Three pillars hold the retention layer to its contract:

- **Determinism**: under a fake clock, identical sample streams must
  produce byte-identical ``/debug/query`` JSON — the seeded fuzz runs
  twin TSDBs over random workloads and diffs the bytes.
- **Boundedness**: memory never grows with uptime — the fuzz also
  checks the series cap, the raw ring, and every tier ring stay
  within their computed budgets after arbitrarily many ticks.
- **Monotonicity**: counters must stay non-decreasing across the
  raw -> tier handoff (downsampling keeps the *last* sample per
  aligned bucket precisely so rate()/increase() never see a phantom
  reset at a tier boundary).

Plus the burn-rate math suite (hand-computed windows vs rule
thresholds) and the alert state machine
(inactive -> pending -> firing -> resolved, ``for:`` dwell, journal
evidence), and the obs_query watch renderer against a real server.
"""

import json
import math
import random
import threading

import pytest

from tools import obs_query
from tools.promlint import lint
from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.obs import alerts as alerts_mod
from tpu_k8s_device_plugin.obs import tsdb as tsdb_mod
from tpu_k8s_device_plugin.obs.tsdb import (
    RangeExpr,
    Selector,
    parse_duration,
    parse_expr,
)

pytestmark = pytest.mark.filterwarnings("ignore")

T0 = 1_700_000_000.0  # fixed epoch base: every fake clock starts here


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# -- grammar ----------------------------------------------------------------

def test_parse_duration_units():
    assert parse_duration("30s") == 30.0
    assert parse_duration("5m") == 300.0
    assert parse_duration("1h") == 3600.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration("2d") == 172800.0
    assert parse_duration("45") == 45.0  # bare seconds
    for bad in ("", "5x", "m5", "-3s"):
        with pytest.raises(ValueError):
            parse_duration(bad)


def test_format_duration_round_trips():
    for s in (0.25, 1.0, 30.0, 90.0, 300.0, 3600.0, 21600.0, 86400.0):
        assert parse_duration(tsdb_mod.format_duration(s)) == s


def test_parse_expr_selector():
    e = parse_expr("tpu_serve_queue_depth")
    assert isinstance(e, Selector)
    assert e.name == "tpu_serve_queue_depth" and e.matchers == ()
    e = parse_expr('tpu_slo_goodput_ratio{class="interactive"}')
    assert e.matchers == (("class", "interactive"),)
    assert e.matches({"class": "interactive", "extra": "x"})
    assert not e.matches({"class": "batch"})


def test_parse_expr_range_functions():
    e = parse_expr("rate(tpu_serve_errors_total[5m])")
    assert isinstance(e, RangeExpr)
    assert e.fn == "rate" and e.window_s == 300.0
    assert e.selector.name == "tpu_serve_errors_total"
    e = parse_expr('avg_over_time(x{a="b"}[30s])')
    assert e.fn == "avg_over_time" and e.window_s == 30.0
    e = parse_expr("histogram_quantile(0.95, tpu_serve_ttft_seconds[1m])")
    assert e.fn == "histogram_quantile" and e.quantile == 0.95
    # round-trippable display form
    assert parse_expr(str(e)) == e


def test_parse_expr_rejects_malformed():
    for bad in ("", "rate(x)", "rate(x[5m]", "foo(x[5m])",
                "histogram_quantile(1.5, x[1m])", 'x{a=b}',
                'x{a="b" c="d"}'):
        with pytest.raises(ValueError):
            parse_expr(bad)


def test_expr_metric_names():
    assert obs.expr_metric_names("tpu_serve_queue_depth") == \
        ["tpu_serve_queue_depth"]
    assert obs.expr_metric_names(
        'rate(tpu_serve_errors_total{code="500"}[1m])') == \
        ["tpu_serve_errors_total"]
    assert obs.expr_metric_names(
        "histogram_quantile(0.5, tpu_serve_ttft_seconds[1m])") == \
        ["tpu_serve_ttft_seconds"]
    with pytest.raises(ValueError):
        obs.expr_metric_names("not a selector")


# -- storage ----------------------------------------------------------------

def _tsdb(reg, clock, **kw):
    kw.setdefault("self_metrics", False)
    return obs.TSDB(reg, now_fn=clock, **kw)


def test_raw_window_prunes_by_time():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock, raw_window_s=10.0, tiers=())
    for i in range(30):
        g.set(float(i))
        db.tick(clock.advance(1.0))
    pts = db.points(Selector("g"), 0, clock.t)[0][1]
    assert len(pts) <= 11  # 10s window at 1s ticks
    assert pts[0][0] >= clock.t - 10.0


def test_raw_ring_prunes_by_count():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock, raw_window_s=1e6, raw_points=8, tiers=())
    for i in range(100):
        g.set(float(i))
        db.tick(clock.advance(1.0))
    pts = db.points(Selector("g"), 0, clock.t)[0][1]
    assert len(pts) == 8
    assert pts[-1][1] == 99.0


def test_same_instant_retick_latest_wins():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock)
    g.set(1.0)
    db.tick(clock.t)
    g.set(2.0)
    db.tick(clock.t)  # same fake instant: overwrite, not append
    pts = db.points(Selector("g"), 0, clock.t)[0][1]
    assert pts == [(clock.t, 2.0)]


def test_clock_backwards_clamps():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock)
    g.set(1.0)
    db.tick(T0 + 100.0)
    g.set(2.0)
    db.tick(T0 + 50.0)  # clock jumped back: clamp to last tick
    pts = db.points(Selector("g"), 0, T0 + 200.0)[0][1]
    assert [t for t, _ in pts] == [T0 + 100.0]
    assert pts[-1][1] == 2.0


def test_series_cap_drops_and_counts():
    reg = obs.Registry()
    g = reg.gauge("g", "h", ("i",))
    clock = FakeClock()
    db = obs.TSDB(reg, now_fn=clock, max_series=4, self_metrics=True)
    for i in range(10):
        g.labels(i=str(i)).set(float(i))
    db.tick(clock.advance(1.0))
    # 4 slots: the tsdb self-metrics are part of the same registry but
    # self-gauges are set AFTER the sample pass, so the first tick's
    # slots go to whatever parsed first; the cap itself must hold
    assert db.series_count() == 4
    body = reg.render()
    samples = dict(((n, tuple(sorted(ls.items()))), v)
                   for n, ls, v in obs.parse_exposition(body))
    assert samples[("tpu_tsdb_dropped_samples_total", ())] > 0


def test_counter_monotone_across_tier_boundary():
    """The raw window is short; the tiers keep the tail.  A counter
    sampled across the raw -> tier handoff must stay non-decreasing
    in the merged read — the property rate()/increase() depend on."""
    reg = obs.Registry()
    c = reg.counter("c_total", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock, raw_window_s=20.0,
               tiers=((10.0, 120.0), (30.0, 600.0)))
    for _ in range(300):
        c.inc(2.0)
        db.tick(clock.advance(1.0))
    pts = db.points(Selector("c_total"), 0, clock.t)[0][1]
    assert len(pts) >= 10
    values = [v for _, v in pts]
    assert values == sorted(values)
    # tail of the merged view is raw-resolution, head is tiered
    times = [t for t, _ in pts]
    assert times == sorted(times)
    assert times[0] < clock.t - 20.0  # tiers extended past raw window


def test_tier_keeps_last_sample_per_bucket():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock, raw_window_s=5.0, tiers=((10.0, 100.0),))
    for i in range(40):
        g.set(float(i))
        db.tick(clock.advance(1.0))
    pts = db.points(Selector("g"), 0, clock.t - 5.0)[0][1]
    # tier region only: one point per 10s bucket, each the bucket's
    # last sample (value == index of that tick)
    buckets = [math.floor(t / 10.0) for t, _ in pts]
    assert len(buckets) == len(set(buckets))
    for t, v in pts:
        assert v == t - T0 - 1.0  # last tick within the bucket


# -- evaluation -------------------------------------------------------------

def test_instant_selector_staleness():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock, lookback_s=30.0)
    g.set(7.0)
    db.tick(clock.t)
    assert db.evaluate("g", at=clock.t + 29.0) == [({}, 7.0)]
    assert db.evaluate("g", at=clock.t + 31.0) == []  # stale


def test_rate_and_increase_reset_aware():
    reg = obs.Registry()
    clock = FakeClock()
    db = _tsdb(reg, clock)
    # hand-fed stream with a counter reset in the middle
    stream = [(0.0, 0.0), (10.0, 40.0), (20.0, 80.0),
              (30.0, 5.0),  # reset
              (40.0, 25.0)]
    g = reg.gauge("c_total", "h")
    for dt, v in stream:
        g.set(v)
        db.tick(T0 + dt)
    # increase = positive deltas only: 40 + 40 + 20 = 100
    (_, inc), = db.evaluate("increase(c_total[40s])", at=T0 + 40.0)
    assert inc == 100.0
    (_, r), = db.evaluate("rate(c_total[40s])", at=T0 + 40.0)
    assert r == pytest.approx(100.0 / 40.0)


def test_avg_min_max_over_time():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock)
    for i, v in enumerate([4.0, 8.0, 6.0]):
        g.set(v)
        db.tick(T0 + i * 10.0)
    at = T0 + 20.0
    (_, avg), = db.evaluate("avg_over_time(g[30s])", at=at)
    assert avg == pytest.approx(6.0)
    (_, lo), = db.evaluate("min_over_time(g[30s])", at=at)
    assert lo == 4.0
    (_, hi), = db.evaluate("max_over_time(g[30s])", at=at)
    assert hi == 8.0


def test_histogram_quantile_over_window():
    reg = obs.Registry()
    h = reg.histogram("lat_seconds", "h", buckets=(0.1, 1.0, 10.0))
    clock = FakeClock()
    db = _tsdb(reg, clock)
    for v in [0.05] * 50:
        h.observe(v)
    db.tick(T0)  # baseline: 50 fast samples already counted
    for v in [0.5] * 50:
        h.observe(v)
    db.tick(T0 + 10.0)
    # the quantile is over the window's *increase* (the 50 slow
    # samples), not lifetime counts: all 50 land in (0.1, 1.0], so
    # p50 interpolates to the bucket midpoint 0.1 + 0.5*(1.0-0.1)
    (_, p50), = db.evaluate(
        "histogram_quantile(0.5, lat_seconds[30s])", at=T0 + 10.0)
    assert p50 == pytest.approx(0.55)
    (_, p99), = db.evaluate(
        "histogram_quantile(0.99, lat_seconds[30s])", at=T0 + 10.0)
    assert p99 == pytest.approx(0.1 + 0.99 * 0.9)
    # windows with zero increase yield no output, not NaN
    assert db.evaluate(
        "histogram_quantile(0.5, lat_seconds[30s])", at=T0 + 500.0) == []


def test_label_matcher_filters_series():
    reg = obs.Registry()
    g = reg.gauge("g", "h", ("cls",))
    clock = FakeClock()
    db = _tsdb(reg, clock)
    g.labels(cls="a").set(1.0)
    g.labels(cls="b").set(2.0)
    db.tick(clock.t)
    assert db.evaluate('g{cls="b"}', at=clock.t) == [({"cls": "b"}, 2.0)]
    both = db.evaluate("g", at=clock.t)
    assert sorted(v for _, v in both) == [1.0, 2.0]


# -- HTTP query handler -----------------------------------------------------

def test_handle_query_selector_and_range_fn():
    reg = obs.Registry()
    g = reg.gauge("g", "h")
    clock = FakeClock()
    db = _tsdb(reg, clock)
    for i in range(5):
        g.set(float(i))
        db.tick(clock.advance(10.0))
    out = db.handle_query({"expr": "g", "range": "60s",
                           "at": str(clock.t)})
    assert out["range_s"] == 60.0
    (s,) = out["series"]
    assert s["name"] == "g" and len(s["points"]) == 5
    out = db.handle_query({"expr": "avg_over_time(g[30s])",
                           "range": "30s", "step": "10s",
                           "at": str(clock.t)})
    (s,) = out["series"]
    assert s["name"] == "avg_over_time(g[30s])"
    assert len(s["points"]) == 4  # inclusive step grid


def test_handle_query_rejects_malformed():
    db = _tsdb(obs.Registry(), FakeClock())
    for params in ({}, {"expr": ""}, {"expr": "bad expr("},
                   {"expr": "g", "range": "0"},
                   {"expr": "g", "range": "-5s"},
                   {"expr": "g", "range": "60s", "step": "nope"}):
        with pytest.raises(ValueError):
            db.handle_query(params)


# -- determinism + boundedness (seeded fuzz) --------------------------------

def _fuzz_workload(seed, db, reg_handles, clock, n_ticks):
    """One deterministic random workload: same seed -> same stream."""
    rng = random.Random(seed)
    g, c, h = reg_handles
    for _ in range(n_ticks):
        for cls in ("a", "b", "c"):
            if rng.random() < 0.8:
                g.labels(cls=cls).set(rng.uniform(0, 100))
        c.inc(rng.uniform(0, 5))
        if rng.random() < 0.5:
            h.observe(rng.uniform(0, 2))
        db.tick(clock.advance(rng.choice([0.5, 1.0, 2.0, 5.0])))


def _make_fuzz_db(seed):
    reg = obs.Registry()
    handles = (
        reg.gauge("fz_gauge", "h", ("cls",)),
        reg.counter("fz_total", "h"),
        reg.histogram("fz_seconds", "h", buckets=(0.1, 0.5, 1.0)),
    )
    clock = FakeClock()
    db = _tsdb(reg, clock, raw_window_s=30.0, raw_points=64,
               tiers=((15.0, 120.0), (60.0, 600.0)), max_series=64)
    _fuzz_workload(seed, db, handles, clock, n_ticks=400)
    return db, clock


FUZZ_QUERIES = (
    "fz_total",
    'fz_gauge{cls="b"}',
    "rate(fz_total[2m])",
    "increase(fz_total[10m])",
    "avg_over_time(fz_gauge[1m])",
    "max_over_time(fz_gauge[5m])",
    "histogram_quantile(0.9, fz_seconds[5m])",
)


@pytest.mark.parametrize("seed", [0, 1, 7, 42])
def test_fuzz_byte_identical_queries(seed):
    """Twin TSDBs fed the identical seeded stream answer every query
    byte-identically — retention and evaluation are deterministic."""
    db1, clock1 = _make_fuzz_db(seed)
    db2, clock2 = _make_fuzz_db(seed)
    assert clock1.t == clock2.t
    for q in FUZZ_QUERIES:
        params = {"expr": q, "range": "10m", "at": str(clock1.t)}
        assert db1.handle_query_json(params) == \
            db2.handle_query_json(params), q


@pytest.mark.parametrize("seed", [0, 3, 99])
def test_fuzz_bounded_memory(seed):
    """After arbitrarily many ticks every ring stays within its
    computed budget: series cap, raw ring, per-tier ring."""
    db, clock = _make_fuzz_db(seed)
    assert db.series_count() <= 64
    # per-series bound: raw_points + sum(window/step + 2) per tier
    per_series = 64 + (120 // 15 + 2) + (600 // 60 + 2)
    assert db.point_count() <= db.series_count() * per_series
    # keep running: the budget must not creep
    for _ in range(100):
        db.tick(clock.advance(1.0))
    assert db.point_count() <= db.series_count() * per_series
    assert db.series_count() <= 64


@pytest.mark.parametrize("seed", [0, 5, 11])
def test_fuzz_counter_monotone_everywhere(seed):
    """Counters stay non-decreasing through every tier handoff, for
    any random tick cadence."""
    db, clock = _make_fuzz_db(seed)
    for labels, pts in db.points(Selector("fz_total"), 0, clock.t):
        values = [v for _, v in pts]
        assert values == sorted(values), labels
        times = [t for t, _ in pts]
        assert times == sorted(times)
    # histogram bucket series are counters too
    for labels, pts in db.points(
            Selector("fz_seconds_bucket"), 0, clock.t):
        values = [v for _, v in pts]
        assert values == sorted(values), labels


# -- burn-rate math ---------------------------------------------------------

def test_burn_rate_hand_computed():
    # 99% objective -> 1% budget; 5% observed miss rate = 5x burn
    assert obs.burn_rate(100, 5, 0.99) == pytest.approx(5.0)
    # exactly at budget
    assert obs.burn_rate(1000, 10, 0.99) == pytest.approx(1.0)
    # the page threshold: 14.4% misses against a 1% budget
    assert obs.burn_rate(1000, 144, 0.99) == pytest.approx(14.4)
    # 99.9% objective: same miss count burns 10x harder
    assert obs.burn_rate(1000, 144, 0.999) == pytest.approx(144.0)
    assert obs.burn_rate(0, 0, 0.99) == 0.0
    for bad in (0.0, 1.0, -1.0, 2.0):
        with pytest.raises(ValueError):
            obs.burn_rate(10, 1, bad)


def test_burn_rate_rules_derivation():
    policies = {"interactive": obs.SLOPolicy(
        name="interactive", ttft_ms=250, objective=0.99)}
    rules = obs.burn_rate_rules(policies)
    by_name = {r.name: r for r in rules}
    page = by_name["slo_burn_page_interactive"]
    ticket = by_name["slo_burn_ticket_interactive"]
    assert page.severity == "page" and ticket.severity == "ticket"
    # multi-window AND: 14.4x over 5m AND 1h
    assert [c.threshold for c in page.conditions] == [14.4, 14.4]
    assert page.conditions[0].expr == (
        'avg_over_time(tpu_slo_error_budget_burn_rate'
        '{class="interactive"}[5m])')
    assert page.conditions[1].expr.endswith("[1h])")
    # ticket: 1x over 6h
    (tc,) = ticket.conditions
    assert tc.threshold == 1.0 and tc.expr.endswith("[6h])")


def test_burn_rate_rules_window_scale():
    policies = {"x": obs.SLOPolicy(name="x", deadline_ms=100,
                                   objective=0.95)}
    rules = obs.burn_rate_rules(policies, window_scale=0.01)
    page = next(r for r in rules if r.severity == "page")
    wins = sorted(parse_expr(c.expr).window_s for c in page.conditions)
    assert wins == [3.0, 36.0]  # 5m/1h scaled by 0.01
    with pytest.raises(ValueError):
        obs.burn_rate_rules(policies, window_scale=0.0)


def test_burn_rate_rules_custom_metric():
    policies = {"x": obs.SLOPolicy(name="x", ttft_ms=10,
                                   objective=0.9)}
    (page, _) = obs.burn_rate_rules(
        policies, metric="tpu_router_fleet_burn_rate")
    assert "tpu_router_fleet_burn_rate" in page.conditions[0].expr


def test_parse_alert_rules_round_trip():
    doc = {"rules": [
        {"name": "queue_deep", "expr": "tpu_serve_queue_depth",
         "op": ">", "threshold": 100, "for_s": 60,
         "severity": "ticket", "description": "queue too deep"},
        {"name": "multi", "severity": "page", "conditions": [
            {"expr": "rate(tpu_serve_errors_total[1m])",
             "op": ">", "threshold": 0.5},
            {"expr": "rate(tpu_serve_errors_total[10m])",
             "op": ">", "threshold": 0.5}]},
    ]}
    rules = obs.parse_alert_rules(json.dumps(doc))
    assert [r.name for r in rules] == ["queue_deep", "multi"]
    assert rules[0].for_s == 60.0 and rules[0].severity == "ticket"
    assert len(rules[1].conditions) == 2
    assert rules[1].severity == "page"


def test_parse_alert_rules_rejects_malformed():
    bads = (
        "not json",
        '{"no_rules": []}',
        '{"rules": [{"expr": "x"}]}',           # missing name
        '{"rules": [{"name": "a"}]}',           # no expr/conditions
        '{"rules": [{"name": "a", "expr": "bad("}]}',
        '{"rules": [{"name": "a", "expr": "x", "op": "!="}]}',
        '{"rules": [{"name": "a", "expr": "x", "severity": "loud"}]}',
        '{"rules": [{"name": "a", "expr": "x"},'
        ' {"name": "a", "expr": "y"}]}',        # duplicate names
    )
    for text in bads:
        with pytest.raises(ValueError):
            obs.parse_alert_rules(text)


# -- alert state machine ----------------------------------------------------

def _alert_rig(rules, *, resolved_hold_s=20.0):
    reg = obs.Registry()
    g = reg.gauge("sig", "h")
    clock = FakeClock()
    db = obs.TSDB(reg, now_fn=clock, self_metrics=False)
    rec = obs.FlightRecorder(registry=None)
    ev = obs.AlertEvaluator(db, rules, recorder=rec,
                            resolved_hold_s=resolved_hold_s)
    return reg, g, clock, db, rec, ev


def _state_of(ev, name):
    for a in ev.status()["alerts"]:
        if a["name"] == name:
            return a["state"]
    raise KeyError(name)


def test_alert_full_traversal_with_dwell():
    rule = obs.threshold_rule("hot", "sig", ">", 10.0, for_s=5.0,
                              severity="page")
    reg, g, clock, db, rec, ev = _alert_rig([rule])
    g.set(1.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "hot") == "inactive"
    # breach: pending, then dwell for_s before firing
    g.set(50.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "hot") == "pending"
    db.tick(clock.advance(2.0))
    assert _state_of(ev, "hot") == "pending"  # dwell not met
    db.tick(clock.advance(4.0))
    assert _state_of(ev, "hot") == "firing"
    assert ev.firing() == ["hot"] and ev.firing("page") == ["hot"]
    assert ev.firing("ticket") == []
    # recovery: resolved, held visible, then inactive
    g.set(1.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "hot") == "resolved"
    db.tick(clock.advance(5.0))
    assert _state_of(ev, "hot") == "resolved"  # inside the hold
    db.tick(clock.advance(30.0))
    assert _state_of(ev, "hot") == "inactive"
    # journal: every transition recorded, in order, with severity
    evs = rec.events(name=obs.ALERT_TRANSITION_EVENT)
    path = [(e["attrs"]["state_from"], e["attrs"]["state_to"])
            for e in evs]
    assert path == [("inactive", "pending"), ("pending", "firing"),
                    ("firing", "resolved"), ("resolved", "inactive")]
    assert all(e["attrs"]["severity"] == "page" for e in evs)
    # exported families reflect the machine
    body = reg.render()
    by = {(n, tuple(sorted(ls.items()))): v
          for n, ls, v in obs.parse_exposition(body)}
    key = (("alert", "hot"), ("severity", "page"))
    assert by[("tpu_alert_state", key)] == 0.0
    assert by[("tpu_alert_transitions_total", key)] == 4.0
    assert by[("tpu_alert_evaluations_total", ())] >= 7.0


def test_alert_for_zero_fires_within_one_tick():
    rule = obs.threshold_rule("fast", "sig", ">", 0.5)
    _, g, clock, db, rec, ev = _alert_rig([rule])
    g.set(1.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "fast") == "firing"  # pending+firing same tick
    evs = rec.events(name=obs.ALERT_TRANSITION_EVENT)
    assert [e["attrs"]["state_to"] for e in evs] == \
        ["pending", "firing"]


def test_alert_pending_cancels_without_firing():
    rule = obs.threshold_rule("flap", "sig", ">", 10.0, for_s=30.0)
    _, g, clock, db, rec, ev = _alert_rig([rule])
    g.set(50.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "flap") == "pending"
    g.set(1.0)
    db.tick(clock.advance(1.0))
    assert _state_of(ev, "flap") == "inactive"
    evs = rec.events(name=obs.ALERT_TRANSITION_EVENT)
    assert [e["attrs"]["state_to"] for e in evs] == \
        ["pending", "inactive"]
    assert ev.firing() == []


def test_alert_multi_window_and_semantics():
    """The page pair is an AND: a short spike trips the 5m window but
    not the 1h window, so no page — the SRE anti-flap property."""
    policies = {"c": obs.SLOPolicy(name="c", ttft_ms=10,
                                   objective=0.99)}
    rules = obs.burn_rate_rules(policies, metric="sig_burn",
                                label="cls", window_scale=0.01)
    reg = obs.Registry()
    g = reg.gauge("sig_burn", "h", ("cls",))
    clock = FakeClock()
    db = obs.TSDB(reg, now_fn=clock, self_metrics=False)
    ev = obs.AlertEvaluator(db, rules)
    # long calm period fills the 36s long window with burn 0
    g.labels(cls="c").set(0.0)
    for _ in range(40):
        db.tick(clock.advance(1.0))
    # short spike: 3s of high burn trips the 3s window only
    g.labels(cls="c").set(100.0)
    for _ in range(3):
        db.tick(clock.advance(1.0))
    assert ev.firing("page") == []  # long window still healthy
    # sustained: the long window catches up -> page
    for _ in range(40):
        db.tick(clock.advance(1.0))
    assert ev.firing("page") == ["slo_burn_page_c"]


def test_alert_brief_shape():
    rules = [obs.threshold_rule("p", "sig", ">", 0.0, severity="page"),
             obs.threshold_rule("t", "sig", ">", 0.0,
                                severity="ticket"),
             obs.threshold_rule("later", "sig", ">", 0.0,
                                for_s=100.0)]
    _, g, clock, db, _, ev = _alert_rig(rules)
    g.set(1.0)
    db.tick(clock.advance(1.0))
    brief = ev.brief()
    assert {f["name"] for f in brief["firing"]} == {"p", "t"}
    assert brief["pending"] == 1
    assert brief["firing_page"] == 1
    # status_json is valid, sorted JSON
    doc = json.loads(ev.status_json())
    assert set(doc["firing"]) == {"p", "t"}


def test_evaluator_rejects_duplicate_rules():
    db = _tsdb(obs.Registry(), FakeClock())
    r = obs.threshold_rule("dup", "sig", ">", 0.0)
    with pytest.raises(ValueError):
        obs.AlertEvaluator(db, [r, r])


def test_alert_condition_ops():
    c = alerts_mod.AlertCondition("sig", ">=", 5.0)
    assert c.holds(5.0) and not c.holds(4.9)
    c = alerts_mod.AlertCondition("sig", "<", 1.0)
    assert c.holds(0.5) and not c.holds(1.0)
    with pytest.raises(ValueError):
        alerts_mod.AlertCondition("sig", "!=", 1.0)
    with pytest.raises(ValueError):
        alerts_mod.AlertCondition("bad expr (", ">", 1.0)


# -- scrape self-metrics (satellite 1) --------------------------------------

def test_scrape_meta_present_from_first_scrape_both_modes():
    reg = obs.Registry()
    reg.counter("app_things_total", "h").inc()
    meta = obs.ScrapeMeta(reg)
    text = meta.render(openmetrics=False)
    om = meta.render(openmetrics=True)
    for body in (text, om):
        assert 'tpu_scrape_duration_seconds_bucket' in body
        assert 'tpu_scrape_series{' in body
        assert 'tpu_scrape_size_bytes{' in body
        # both mode children visible regardless of which mode scraped
        assert 'mode="text"' in body and 'mode="openmetrics"' in body
        assert not lint(body), f"scrape meta fails promlint"
    assert om.rstrip().endswith("# EOF")
    # the second scrape carries the FIRST scrape's measured numbers
    body2 = meta.render(openmetrics=False)
    by = {(n, tuple(sorted(ls.items()))): v
          for n, ls, v in obs.parse_exposition(body2)}
    assert by[("tpu_scrape_series", (("mode", "text"),))] > 0
    assert by[("tpu_scrape_size_bytes", (("mode", "text"),))] > 0


def test_tsdb_and_alert_families_lint_clean():
    reg = obs.Registry()
    g = reg.gauge("sig", "h")
    clock = FakeClock()
    db = obs.TSDB(reg, now_fn=clock, self_metrics=True)
    obs.AlertEvaluator(db, [obs.threshold_rule(
        "hot", "sig", ">", 1.0, severity="page")])
    g.set(5.0)
    db.tick(clock.advance(1.0))
    meta = obs.ScrapeMeta(reg)
    for om in (False, True):
        body = meta.render(openmetrics=om)
        assert not lint(body)
        assert "tpu_alert_state{" in body
        assert "tpu_tsdb_ticks_total" in body


# -- severity threading (satellite 2) ---------------------------------------

def _alert_event(sev, name="tpu_alert_transition", span="s1"):
    return {"name": name, "t_wall": 10.0, "span_id": span,
            "trace_id": "t1", "parent_id": "",
            "attrs": {"severity": sev, "alert": "hot",
                      "state_from": "pending", "state_to": "firing"}}


def test_event_severity_precedence():
    assert obs.event_severity(_alert_event("page")) == "page"
    assert obs.event_severity(
        {"severity": "info", "attrs": {"severity": "page"}}) == "info"
    assert obs.event_severity({"name": "x"}) == ""
    assert obs.event_severity({"attrs": {}}) == ""


def test_flatten_promotes_severity():
    tree = obs.stitch([
        _alert_event("page"),
        {"name": "plain", "t_wall": 5.0, "span_id": "s1",
         "trace_id": "t1", "parent_id": "", "attrs": {}},
    ])
    flat = obs.flatten(tree)
    by_name = {e["name"]: e for e in flat}
    assert by_name["tpu_alert_transition"]["severity"] == "page"
    assert "severity" not in by_name["plain"]


def test_render_tree_tags_severity():
    out = obs.render_tree(obs.stitch([_alert_event("ticket")]))
    assert "severity=ticket" in out


# -- obs_query watch --------------------------------------------------------

def test_sparkline_rendering():
    assert obs_query.sparkline([]) == "(no data)"
    s = obs_query.sparkline([1.0, 2.0, 3.0])
    assert s.startswith(obs_query.SPARK_BLOCKS[0])
    assert obs_query.SPARK_BLOCKS[-1] in s
    assert "min=1 last=3 max=3" in s
    flat = obs_query.sparkline([5.0, 5.0])
    assert flat.startswith(obs_query.SPARK_BLOCKS[0] * 2)
    # NaNs dropped, not rendered
    assert "nan" not in obs_query.sparkline([float("nan"), 2.0])


def test_render_watch_frame_pure():
    queries = [
        {"expr": "tpu_slo_goodput_ratio",
         "series": [{"name": "tpu_slo_goodput_ratio",
                     "labels": {"class": "interactive"},
                     "points": [[1.0, 0.9], [2.0, 0.4]]}]},
        {"expr": "tpu_serving_kv_pages_free", "series": []},
    ]
    alerts = {"alerts": [
        {"name": "slo_burn_page_interactive", "severity": "page",
         "state": "firing", "value": 90.0, "since": 100.0},
        {"name": "quiet", "severity": "info", "state": "inactive"},
        {"name": "slow_ticket", "severity": "ticket",
         "state": "pending", "value": 2.0, "since": 100.0},
    ]}
    out = obs_query.render_watch_frame(queries, alerts)
    assert "{class=interactive}" in out
    assert "(no data)" in out
    # severity-ranked table: page row above ticket row, inactive hidden
    lines = out.splitlines()
    page_i = next(i for i, l in enumerate(lines)
                  if "slo_burn_page_interactive" in l)
    ticket_i = next(i for i, l in enumerate(lines)
                    if "slow_ticket" in l)
    assert page_i < ticket_i
    assert "quiet" not in out
    empty = obs_query.render_watch_frame(queries, {"alerts": []})
    assert "no pending or firing alerts" in empty


def test_watch_against_real_server():
    """Acceptance: obs_query watch renders live sparklines against a
    real serving surface (the health exporter, cheapest to boot)."""
    from tpu_k8s_device_plugin.health.metrics import MetricsHTTPServer

    srv = MetricsHTTPServer(port=0, host="127.0.0.1",
                            sysfs_root="/nonexistent",
                            dev_root="/nonexistent",
                            tick_interval_s=0.05).start()
    try:
        import time
        deadline = time.time() + 10.0
        while srv.tsdb.series_count() == 0 and time.time() < deadline:
            time.sleep(0.05)
        frames = []
        rc = obs_query.watch(
            f"http://127.0.0.1:{srv.port}",
            ["tpu_exporter_chips", "rate(tpu_tsdb_ticks_total[30s])"],
            range_s=60.0, interval_s=0.05, iterations=2,
            out=frames.append)
        assert rc == 0
        text = "\n".join(frames)
        assert "tpu_exporter_chips" in text
        assert any(ch in text for ch in obs_query.SPARK_BLOCKS)
        assert "alert" in text  # alert table rendered
    finally:
        srv.stop()


def test_watch_cli_flag_wiring(capsys):
    """--watch requires exactly one endpoint and exits cleanly."""
    from tpu_k8s_device_plugin.health.metrics import MetricsHTTPServer

    srv = MetricsHTTPServer(port=0, host="127.0.0.1",
                            sysfs_root="/nonexistent",
                            dev_root="/nonexistent",
                            tick_interval_s=0.05).start()
    try:
        import time
        time.sleep(0.3)
        rc = obs_query.main([
            "--watch", "--endpoint", f"http://127.0.0.1:{srv.port}",
            "--watch-expr", "tpu_exporter_scrapes_total",
            "--interval", "0.05", "--iterations", "1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "tpu_exporter_scrapes_total" in out
    finally:
        srv.stop()
    with pytest.raises(SystemExit):
        obs_query.main(["--watch"])  # no endpoint
