"""Protocol layer tests: message roundtrips + gRPC wiring over a unix socket."""

import concurrent.futures
import os

import grpc
import pytest

from tpu_k8s_device_plugin.proto import (
    deviceplugin_pb2 as pb,
    deviceplugin_pb2_grpc as pb_grpc,
    tpuhealth_pb2 as hpb,
    tpuhealth_pb2_grpc as hpb_grpc,
)


def test_device_message_roundtrip():
    d = pb.Device(
        ID="tpu-0000:00:04.0",
        health="Healthy",
        topology=pb.TopologyInfo(nodes=[pb.NUMANode(ID=1)]),
    )
    d2 = pb.Device.FromString(d.SerializeToString())
    assert d2.ID == "tpu-0000:00:04.0"
    assert d2.topology.nodes[0].ID == 1


def test_allocate_response_roundtrip():
    resp = pb.AllocateResponse(
        container_responses=[
            pb.ContainerAllocateResponse(
                envs={"TPU_VISIBLE_CHIPS": "0,1"},
                devices=[
                    pb.DeviceSpec(
                        container_path="/dev/accel0",
                        host_path="/dev/accel0",
                        permissions="rw",
                    )
                ],
            )
        ]
    )
    r2 = pb.AllocateResponse.FromString(resp.SerializeToString())
    assert r2.container_responses[0].envs["TPU_VISIBLE_CHIPS"] == "0,1"
    assert r2.container_responses[0].devices[0].host_path == "/dev/accel0"


class _EchoPlugin(pb_grpc.DevicePluginServicer):
    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(get_preferred_allocation_available=True)

    def ListAndWatch(self, request, context):
        yield pb.ListAndWatchResponse(
            devices=[pb.Device(ID="chip0", health="Healthy")]
        )

    def Allocate(self, request, context):
        out = pb.AllocateResponse()
        for creq in request.container_requests:
            cres = out.container_responses.add()
            for did in creq.devices_ids:
                cres.devices.add(
                    container_path=f"/dev/{did}", host_path=f"/dev/{did}",
                    permissions="rw",
                )
        return out


@pytest.fixture
def uds_server(tmp_path):
    sock = str(tmp_path / "plugin.sock")
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=4))
    pb_grpc.add_DevicePluginServicer_to_server(_EchoPlugin(), server)
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield sock
    server.stop(0)


def test_grpc_unary_and_stream_over_unix_socket(uds_server):
    with grpc.insecure_channel(f"unix://{uds_server}") as ch:
        stub = pb_grpc.DevicePluginStub(ch)
        opts = stub.GetDevicePluginOptions(pb.Empty())
        assert opts.get_preferred_allocation_available

        stream = stub.ListAndWatch(pb.Empty())
        first = next(iter(stream))
        assert first.devices[0].ID == "chip0"

        resp = stub.Allocate(
            pb.AllocateRequest(
                container_requests=[
                    pb.ContainerAllocateRequest(devices_ids=["accel0", "accel1"])
                ]
            )
        )
        paths = [d.host_path for d in resp.container_responses[0].devices]
        assert paths == ["/dev/accel0", "/dev/accel1"]


def test_tpuhealth_roundtrip():
    s = hpb.TpuState(
        id="0000:00:05.0", accel_index=1, health="Unhealthy",
        device="/dev/accel1",
    )
    s2 = hpb.TpuState.FromString(s.SerializeToString())
    assert s2.accel_index == 1 and s2.health == "Unhealthy"
    assert hpb.TpuHealth.Name(hpb.UNHEALTHY) == "UNHEALTHY"
    assert hpb_grpc is not None


def test_method_paths_match_kubelet_abi():
    """The gRPC method paths are an ABI with the kubelet — pin them."""
    fd = pb.DESCRIPTOR
    assert fd.package == "v1beta1"
    svc = fd.services_by_name["DevicePlugin"]
    assert sorted(m.name for m in svc.methods) == [
        "Allocate",
        "GetDevicePluginOptions",
        "GetPreferredAllocation",
        "ListAndWatch",
        "PreStartContainer",
    ]
    assert "Registration" in fd.services_by_name


def test_slice_roundtrip_and_service_shape():
    """slice.proto wire sanity.  Its _pb2 is built by the no-protoc
    fallback (tools/gen_slice_pb2.py), so pin both the roundtrip AND the
    descriptor shape a real protoc run must reproduce."""
    from tpu_k8s_device_plugin.proto import (
        slice_pb2 as spb,
        slice_pb2_grpc as spb_grpc,
    )

    jr = spb.JoinResponse(
        formed=True, rank=1, joined=2, expected=2,
        membership=spb.Membership(
            slice_id="abc123", generation=2, num_workers=2,
            hostnames=["host-a", "host-b"],
            coordinator_address="host-a:8476",
            reshaped_from=["def456"], degraded=True,
        ),
    )
    jr2 = spb.JoinResponse.FromString(jr.SerializeToString())
    assert jr2.rank == 1 and tuple(jr2.membership.hostnames) == (
        "host-a", "host-b")
    # reshape lineage rides the wire (fields 6/7, PR 8)
    assert tuple(jr2.membership.reshaped_from) == ("def456",)
    assert jr2.membership.degraded is True

    hb = spb.HeartbeatRequest(hostname="host-b", healthy=False,
                              reason="chip_state=dead", generation=1)
    assert spb.HeartbeatRequest.FromString(
        hb.SerializeToString()).reason == "chip_state=dead"

    fd = spb.DESCRIPTOR
    assert fd.package == "tpuslice"
    svc = fd.services_by_name["SliceRendezvous"]
    assert sorted(m.name for m in svc.methods) == ["Heartbeat", "Join"]
    assert spb_grpc is not None
