"""Native tpuprobe shim tests (build + ctypes binding).

The reference's native boundary has no tests at all (its cgo paths are
only exercised by hardware-gated tests, SURVEY.md §4.2); here the shim's
full C ABI is covered: inotify watch semantics, the chardev probe's errno
contract, and the NUMA sysfs read against fixtures.
"""

import errno
import os
import threading
import time

import pytest

tpuprobe = pytest.importorskip(
    "tpu_k8s_device_plugin.hostinfo.tpuprobe",
    reason="native shim unbuildable (no C++ toolchain)",
)


def test_version_banner():
    assert tpuprobe.version().startswith("tpuprobe ")


class TestProbeDevice:
    def test_chardev_ok(self):
        assert tpuprobe.probe_device_node("/dev/null") == 0

    def test_missing_is_enoent(self):
        assert tpuprobe.probe_device_node("/nonexistent/accel0") == -2

    def test_regular_file_is_enotsup(self, tmp_path):
        # -ENOTSUP is the reserved "exists but not a chardev" sentinel so
        # callers can tell fixture trees from a driver-reported ENODEV
        p = tmp_path / "accel0"
        p.write_text("")
        assert tpuprobe.probe_device_node(str(p)) == -errno.ENOTSUP


class TestNumaNode:
    def test_fixture_read(self, testdata):
        d = os.path.join(
            testdata, "v5e-8", "sys", "devices", "pci0000:00", "0000:00:04.0"
        )
        assert tpuprobe.numa_node(d) >= 0

    def test_missing_dir(self):
        assert tpuprobe.numa_node("/nonexistent") < 0


class TestDirWatcher:
    def test_create_event(self, tmp_path):
        with tpuprobe.DirWatcher(str(tmp_path)) as w:
            t = threading.Timer(
                0.1, lambda: (tmp_path / "kubelet.sock").write_text("")
            )
            t.start()
            t0 = time.monotonic()
            assert w.wait(5.0)
            # event-driven: must fire well before the timeout
            assert time.monotonic() - t0 < 2.0

    def test_timeout_without_event(self, tmp_path):
        with tpuprobe.DirWatcher(str(tmp_path)) as w:
            assert not w.wait(0.1)

    def test_delete_event(self, tmp_path):
        f = tmp_path / "sock"
        f.write_text("")
        with tpuprobe.DirWatcher(str(tmp_path)) as w:
            w.wait(0.05)  # drain the create we just did
            threading.Timer(0.1, f.unlink).start()
            assert w.wait(5.0)

    def test_missing_dir_raises(self):
        with pytest.raises(OSError):
            tpuprobe.DirWatcher("/nonexistent-dir-xyz")

    def test_deleted_watch_dir_raises_estale(self, tmp_path):
        """A deleted watch directory must surface as an error, not silent
        timeouts — the manager needs to know its watch went poll-only so it
        can re-create it (some kubelet restarts recreate the dp dir)."""
        d = tmp_path / "device-plugins"
        d.mkdir()
        with tpuprobe.DirWatcher(str(d)) as w:
            threading.Timer(0.1, d.rmdir).start()
            with pytest.raises(OSError) as ei:
                # first wait may return the IN_DELETE event batch as stale
                # already; loop a bounded number of times to absorb timing
                for _ in range(50):
                    w.wait(0.2)
            assert ei.value.errno == errno.ESTALE

    def test_closed_watcher_raises(self, tmp_path):
        w = tpuprobe.DirWatcher(str(tmp_path))
        w.close()
        with pytest.raises(ValueError):
            w.wait(0.01)


def test_health_server_uses_native_probe(testdata):
    """probe_chip_states goes through the native path when available and
    still accepts fixture trees (regular-file device nodes)."""
    from tpu_k8s_device_plugin.health import server as hs

    assert hs._tpuprobe is not None
    root = os.path.join(testdata, "v5e-8")
    states = hs.probe_chip_states(
        os.path.join(root, "sys"), os.path.join(root, "dev")
    )
    assert len(states) == 8
    assert all(s.health == "Healthy" for s in states.values())
