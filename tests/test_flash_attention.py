"""Flash-attention kernel tests (interpreter mode on the CPU mesh — the
same kernel code path that compiles on TPU): exact agreement with the
full-attention oracle, custom-VJP gradients, and LM integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.flash_attention import (
    flash_attention,
    flash_causal_attention,
)
from tpu_k8s_device_plugin.workloads.ring_attention import full_attention


def qkv(shape, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(kk, shape, dtype) for kk in ks)


class TestForward:
    # shapes are the smallest that preserve the structural cases
    # (multiple blocks per axis, uneven bq != bk both ways, clamping):
    # interpret-mode cost scales with B*T^2*H*D and this file is on the
    # suite's critical path (1-core box, VERDICT r2 #8)
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize(
        "shape,blocks",
        [
            ((2, 64, 2, 16), (32, 32)),
            ((1, 128, 2, 8), (64, 32)),    # uneven bq != bk
            ((2, 32, 1, 32), (64, 64)),    # blocks clamp to T
        ],
    )
    def test_matches_oracle(self, causal, shape, blocks):
        q, k, v = qkv(shape)
        got = flash_attention(
            q, k, v, causal=causal, block_q=blocks[0], block_k=blocks[1]
        )
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_bf16_inputs(self):
        q, k, v = qkv((2, 64, 2, 16), jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=3e-2, rtol=3e-2,
        )

    def test_indivisible_seq_degrades_block_size(self):
        """T=96 with 64-blocks runs at the largest divisor (48) and
        still matches the oracle exactly."""
        q, k, v = qkv((1, 96, 1, 8))
        got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize(
        "shape,blocks",
        [
            ((1, 64, 2, 16), (32, 32)),
            ((2, 128, 1, 8), (64, 32)),    # bq != bk: dkv diagonal lower
            ((1, 128, 2, 8), (32, 64)),    # bound exercised both ways
        ],
    )
    def test_gradients_match_oracle(self, causal, shape, blocks):
        q, k, v = qkv(shape, seed=3)

        def flash_loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=causal,
                    block_q=blocks[0], block_k=blocks[1],
                ) ** 2
            )

        def oracle_loss(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4
            )

    def test_bf16_gradients(self):
        """bf16 end-to-end: the kernel casts P/dS to bf16 for the MXU
        (same rounding as the forward's P·V), so compare loosely."""
        q, k, v = qkv((1, 64, 2, 16), jnp.bfloat16, seed=5)

        def flash_loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True).astype(jnp.float32)
                ** 2
            )

        def oracle_loss(q, k, v):
            return jnp.sum(
                full_attention(q, k, v, causal=True).astype(jnp.float32)
                ** 2
            )

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(w, np.float32),
                atol=5e-2, rtol=5e-2,
            )


class TestLMIntegration:
    def test_lm_forward_matches_einsum_attention(self):
        """TransformerLM with the flash kernel produces the same logits
        as the einsum local attention (natural token order)."""
        from tpu_k8s_device_plugin.workloads.transformer import (
            TransformerLM, local_causal_attention, synthetic_lm_batch,
        )

        tiny = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)
        rng = jax.random.PRNGKey(1)
        tokens, _, positions = synthetic_lm_batch(rng, 2, 32, tiny["vocab"])
        ref_model = TransformerLM(attn_fn=local_causal_attention, **tiny)
        params = ref_model.init(rng, tokens, positions)["params"]
        want = ref_model.apply({"params": params}, tokens, positions)
        flash_model = TransformerLM(attn_fn=flash_causal_attention, **tiny)
        got = flash_model.apply({"params": params}, tokens, positions)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=3e-2, rtol=3e-2
        )
