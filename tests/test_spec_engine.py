"""Engine-level speculative decoding: batched propose/verify rounds
inside the continuous-batching engine (vLLM's speculative_model).

Oracle: greedy spec-decode is bit-exact vs plain greedy decoding, so
every slot's output must equal the standalone greedy_generate run of
its own prompt — regardless of draft quality, scheduling, admissions
interleaving, budgets, stops, or cache exhaustion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    greedy_generate,
    make_decoder,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

TARGET_CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DRAFT_CFG = dict(vocab=96, d_model=32, n_heads=2, n_layers=1, d_ff=64)
DT = jnp.float32
MAX_LEN = 64


def _init(model, seed):
    rng = jax.random.PRNGKey(seed)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    return model.init(rng, tokens, pos)["params"]


@pytest.fixture(scope="module")
def models():
    target = make_decoder(**TARGET_CFG, max_len=MAX_LEN, dtype=DT)
    draft = make_decoder(**DRAFT_CFG, max_len=MAX_LEN, dtype=DT)
    return (target, _init(target, 0)), (draft, _init(draft, 1))


def _oracle(target, params, prompt, n):
    out, _ = greedy_generate(
        target, params, jnp.asarray(prompt, jnp.int32)[None, :], n)
    return np.asarray(out)[0].tolist()


def test_spec_rounds_match_plain_greedy(models):
    (target, tp), (draft, dp) = models
    eng = ServingEngine(target, tp, n_slots=2, max_new_tokens=9,
                        draft=(draft, dp), gamma=3)
    pa, pb = [5, 17, 3, 70], [11, 2, 9]
    sa, sb = eng.admit(pa), eng.admit(pb)
    eng.run_spec(12)
    assert eng.output(sa) == _oracle(target, tp, pa, 9)
    assert eng.output(sb) == _oracle(target, tp, pb, 9)
    assert eng.finished(sa) and eng.finished(sb)
    st = eng.stats()
    assert st["spec_proposed"] >= st["spec_accepted"] >= 0
    # fewer device rounds than tokens is the whole point
    assert 1 <= st["spec_rounds"] < 9


def test_draft_equals_target_accepts_everything(models):
    (target, tp), _ = models
    eng = ServingEngine(target, tp, n_slots=1, max_new_tokens=8,
                        draft=(target, tp), gamma=3)
    s = eng.admit([5, 17, 3, 70])
    eng.run_spec(8)
    assert eng.output(s) == _oracle(target, tp, [5, 17, 3, 70], 8)
    assert eng.accept_rate == 1.0
    # admit emits 1, each full round commits gamma+1 = 4: 2 rounds
    assert eng.stats()["spec_rounds"] == 2


def test_garbage_draft_still_exact(models):
    (target, tp), (draft, _) = models
    garbage = _init(draft, 999)
    eng = ServingEngine(target, tp, n_slots=1, max_new_tokens=7,
                        draft=(draft, garbage), gamma=4)
    s = eng.admit([3, 14, 15, 92])
    eng.run_spec(10)
    assert eng.output(s) == _oracle(target, tp, [3, 14, 15, 92], 7)


def test_stop_token_mid_round(models):
    """A stop token landing inside a round's committed block must
    retire the slot there and discard the rest of the block."""
    (target, tp), (draft, dp) = models
    want = _oracle(target, tp, [5, 17, 3, 70], 8)
    stop = want[4]
    plain = ServingEngine(target, tp, n_slots=1, max_new_tokens=8)
    sp = plain.admit([5, 17, 3, 70], stop=[stop])
    plain.run(10)
    eng = ServingEngine(target, tp, n_slots=1, max_new_tokens=8,
                        draft=(draft, dp), gamma=4)
    s = eng.admit([5, 17, 3, 70], stop=[stop])
    eng.run_spec(10)
    assert eng.output(s) == plain.output(sp)
    assert eng.finish_reason(s) == plain.finish_reason(sp) == "stop"


def test_cache_exhaustion_matches_plain(models):
    (target, tp), (draft, dp) = models
    prompt = [5, 17, 3, 70]
    small_t = make_decoder(**TARGET_CFG, max_len=16, dtype=DT)
    small_d = make_decoder(**DRAFT_CFG, max_len=16, dtype=DT)
    plain = ServingEngine(small_t, tp, n_slots=1)
    sp = plain.admit(prompt)
    plain.run(20)
    eng = ServingEngine(small_t, tp, n_slots=1,
                        draft=(small_d, dp), gamma=3)
    s = eng.admit(prompt)
    eng.run_spec(20)
    assert eng.output(s) == plain.output(sp)
    assert eng.finish_reason(s) == plain.finish_reason(sp) == "length"


def test_admission_between_rounds(models):
    """Continuous batching: a prompt admitted mid-stream joins the
    next round; both slots stay exact."""
    (target, tp), (draft, dp) = models
    pa, pb = [5, 17, 3, 70], [11, 2, 9, 44, 8]
    eng = ServingEngine(target, tp, n_slots=2, max_new_tokens=7,
                        draft=(draft, dp), gamma=3)
    sa = eng.admit(pa)
    eng.spec_round()
    sb = eng.admit(pb)
    eng.run_spec(10)
    assert eng.output(sa) == _oracle(target, tp, pa, 7)
    assert eng.output(sb) == _oracle(target, tp, pb, 7)


def test_spec_with_auto_prefix(models):
    """APC reuses the TARGET's prompt K/V; the draft prefills cold —
    outputs still exact for both the donor and the borrower."""
    (target, tp), (draft, dp) = models
    shared = [7, 3, 9, 12, 5, 8, 1, 2]
    pa, pb = shared + [5, 9], shared + [44]
    eng = ServingEngine(target, tp, n_slots=2, max_new_tokens=6,
                        chunk=4, auto_prefix_min=4,
                        draft=(draft, dp), gamma=3)
    sa = eng.admit(pa)
    sb = eng.admit(pb)
    assert eng.stats()["prefix_cache_hits"] == 1
    eng.run_spec(10)
    assert eng.output(sa) == _oracle(target, tp, pa, 6)
    assert eng.output(sb) == _oracle(target, tp, pb, 6)


def test_released_donor_survives_spec_rounds(models):
    """Spec rounds on OTHER slots must not touch a released slot's
    prompt K/V: the rollback may only set lens for dispatched slots
    (a released slot's host mirror is 0 — pushing it to the device
    would park the clamped verify writes ON TOP of the donor rows)."""
    (target, tp), (draft, dp) = models
    shared = [7, 3, 9, 12, 5, 8, 1, 2]
    pa = shared + [5, 9]
    eng = ServingEngine(target, tp, n_slots=2,
                        chunk=4, auto_prefix_min=4,
                        draft=(draft, dp), gamma=3)
    # request A retires on a stop token and releases; its donor stays.
    # B admits FIRST into the other slot so A's parked slot (and donor
    # record) survive until C arrives
    stop_a = _oracle(target, tp, pa, 8)[2]
    eng.admit([44, 61, 20])
    sa = eng.admit(pa, stop=[stop_a])
    eng.run_spec(8)
    assert eng.finished(sa) and eng.finish_reason(sa) == "stop"
    eng.release(sa)
    # long-running request B keeps spec rounds (and their clamped
    # writes) going while A's slot is parked
    for _ in range(3):
        eng.spec_round()
    # request C shares A's prefix: APC must reuse A's rows and still
    # be bit-exact vs the cold oracle
    before = eng.stats()["prefix_cache_hits"]
    sc = eng.admit(shared + [44])
    assert eng.stats()["prefix_cache_hits"] == before + 1
    for _ in range(3):
        eng.spec_round()
    got = eng.output(sc)
    assert len(got) >= 4
    assert got == _oracle(target, tp, shared + [44], len(got))


def test_spec_donor_bound_rejects_long_prompts(models):
    """With a proposer, EVERY verify extend writes gamma+1 rows, and a
    parked slot's clamped write band is [max_len-gamma-1, max_len-1] —
    admit must reject prompts whose K/V would sit inside it (ADVICE
    r4: the plain t_p <= max_len-1 invariant only covers T=1 writes)."""
    (target, tp), (draft, dp) = models
    small_t = make_decoder(**TARGET_CFG, max_len=16, dtype=DT)
    small_d = make_decoder(**DRAFT_CFG, max_len=16, dtype=DT)
    eng = ServingEngine(small_t, tp, n_slots=1,
                        draft=(small_d, dp), gamma=3)
    # bound is 16 - 3 - 1 = 12: 12 admits, 13 rejects
    s = eng.admit(list(range(1, 13)))
    eng.release(s)
    with pytest.raises(ValueError, match="donor bound"):
        eng.admit(list(range(1, 14)))
    # n-gram proposers share the same verify band
    eng2 = ServingEngine(small_t, tp, n_slots=1, draft="ngram", gamma=3)
    with pytest.raises(ValueError, match="donor bound"):
        eng2.admit(list(range(1, 14)))
    # the bound guards APC donor reads: with auto_prefix off, parked
    # rows are never read back and long prompts must still admit
    # (spec_round's own headroom fallback protects live decoding)
    eng3 = ServingEngine(small_t, tp, n_slots=1, draft="ngram",
                         gamma=3, auto_prefix=False)
    s3 = eng3.admit(list(range(1, 14)))
    eng3.run_spec(6)
    assert len(eng3.output(s3)) >= 1


def test_greedy_only_guard(models):
    (target, tp), (draft, dp) = models
    eng = ServingEngine(target, tp, n_slots=1, draft=(draft, dp))
    eng.admit([5, 17, 3], temperature=0.8)
    with pytest.raises(ValueError, match="greedy-only"):
        eng.spec_round()


def test_requires_draft(models):
    (target, tp), _ = models
    eng = ServingEngine(target, tp, n_slots=1)
    eng.admit([5, 17, 3])
    with pytest.raises(RuntimeError, match="draft"):
        eng.spec_round()


def test_draft_validation(models):
    (target, tp), (draft, dp) = models
    short = make_decoder(**DRAFT_CFG, max_len=MAX_LEN // 2, dtype=DT)
    with pytest.raises(ValueError, match="max_len"):
        ServingEngine(target, tp, n_slots=1, draft=(short, dp))
    with pytest.raises(ValueError, match="gamma"):
        ServingEngine(target, tp, n_slots=1, draft=(draft, dp), gamma=0)

# -- n-gram (prompt-lookup) mode ---------------------------------------------

def test_ngram_propose_unit():
    from tpu_k8s_device_plugin.workloads.serving import _ngram_propose
    import numpy as np
    # ...a b c X ... a b c -> proposes X and what followed
    seq = np.asarray([9, 1, 2, 3, 7, 8, 4, 1, 2, 3], np.int32)
    got = _ngram_propose(seq, 3, 3).tolist()
    assert got == [7, 8, 4]
    # LATEST earlier occurrence wins
    seq = np.asarray([1, 2, 5, 0, 1, 2, 6, 0, 1, 2], np.int32)
    assert _ngram_propose(seq, 2, 1).tolist() == [6]
    # continuation shorter than gamma pads with the last token
    seq = np.asarray([1, 2, 7, 1, 2], np.int32)
    assert _ngram_propose(seq, 2, 3).tolist() == [7, 1, 2]
    # no match: repeat last token
    seq = np.asarray([1, 2, 3, 4], np.int32)
    assert _ngram_propose(seq, 2, 2).tolist() == [4, 4]
    # degenerate history
    seq = np.asarray([5], np.int32)
    assert _ngram_propose(seq, 3, 2).tolist() == [5, 5]


def test_ngram_spec_matches_plain_greedy(models):
    """Draft-free prompt-lookup speculation: same verify machinery,
    proposals from the request's own history — exact regardless of
    hit rate."""
    (target, tp), _ = models
    eng = ServingEngine(target, tp, n_slots=2, max_new_tokens=9,
                        draft="ngram", gamma=3, ngram_n=2)
    pa = [5, 17, 3, 5, 17, 3, 5, 17]  # repetitive: lookups will hit
    pb = [11, 2, 9]
    sa, sb = eng.admit(pa), eng.admit(pb)
    eng.run_spec(12)
    assert eng.output(sa) == _oracle(target, tp, pa, 9)
    assert eng.output(sb) == _oracle(target, tp, pb, 9)
    assert eng.stats()["spec_rounds"] >= 1


def test_ngram_spec_server(models):
    from tpu_k8s_device_plugin.workloads.server import EngineServer
    import http.client, json as _json
    (target, tp), _ = models
    eng = ServingEngine(target, tp, n_slots=2, draft="ngram", gamma=3)
    srv = EngineServer(eng, max_new_tokens=6, window=4)
    srv.start(host="127.0.0.1", port=0)
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=120)
        c.request("POST", "/generate", _json.dumps(
            {"tokens": [5, 17, 3, 70], "stream": False}),
            {"Content-Type": "application/json"})
        r = c.getresponse()
        ev = _json.loads(r.read().decode().strip().splitlines()[0])
        assert ev["tokens"] == _oracle(target, tp, [5, 17, 3, 70], 6)
        assert eng.stats()["spec_rounds"] >= 1
        # /metrics renders the same counters for a scrape
        c2 = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        c2.request("GET", "/metrics")
        body = c2.getresponse().read().decode()
        assert "tpu_serving_spec_rounds" in body
        assert "tpu_serving_tokens_emitted" in body
    finally:
        srv.stop()


def test_ngram_validation(models):
    (target, tp), _ = models
    with pytest.raises(ValueError, match="ngram_n"):
        ServingEngine(target, tp, n_slots=1, draft="ngram", ngram_n=0)
