"""Disaggregated prefill/decode serving: migration equivalence.

The acceptance bar for the router-v2 disagg path: a request routed
prefill-replica -> KV migration -> decode-replica must produce output
BYTE-IDENTICAL to the same request served by one mixed replica —
unary bodies compared raw, streams compared as their token sequence
plus the authoritative terminal event (window framing may legally
coalesce differently across the hop).  The matrix covers greedy,
seeded sampling, grammar-constrained decoding (including grammar-state
re-homing onto a decode engine whose combined table has DIFFERENT
offsets), and APC-hit admissions, plus every router fallback that must
complete the request before any client byte."""

import http.client
import json
import re
import time

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_k8s_device_plugin import obs  # noqa: E402
from tpu_k8s_device_plugin.workloads.inference import make_decoder  # noqa: E402
from tpu_k8s_device_plugin.workloads.router import RouterServer  # noqa: E402
from tpu_k8s_device_plugin.workloads.server import EngineServer  # noqa: E402
from tpu_k8s_device_plugin.workloads.serving import ServingEngine  # noqa: E402

import numpy as np  # noqa: E402

from tpu_k8s_device_plugin.workloads.migrate import (  # noqa: E402
    MigrateError,
    dump_payload,
    load_payload,
)

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
MAX_LEN = 128
EOS = 0
# long enough to clear the router's prefill threshold (16 below) and
# span multiple admission chunks on the paged engine
LONG = [(i * 7) % 126 + 1 for i in range(40)]


class _ByteTok:
    def encode(self, s):
        return list(s.encode("latin-1"))

    def decode(self, ids, **kw):
        return bytes(int(t) % 256 for t in ids).decode("latin-1")


def _build():
    model = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(jax.random.PRNGKey(0), tokens, pos)["params"]
    return model, params


def _server(model, params, role):
    eng = ServingEngine(model, params, n_slots=2, eos_id=EOS,
                        kv_paging=True)
    tb = [bytes([i]) if i else b"" for i in range(CFG["vocab"])]
    srv = EngineServer(eng, max_new_tokens=16, window=4,
                       token_bytes=tb, tokenizer=_ByteTok(),
                       replica_role=role)
    srv.start(host="127.0.0.1", port=0)
    return srv


@pytest.fixture(scope="module")
def stack():
    """One mixed baseline replica + a prefill/decode pair behind a
    phase-aware router (threshold 16 so LONG migrates)."""
    model, params = _build()
    mixed = _server(model, params, "mixed")
    pre = _server(model, params, "prefill")
    dec = _server(model, params, "decode")
    rt = RouterServer(statz_interval_s=0.2, replica_ttl_s=30.0,
                      seed=5, prefill_threshold=16)
    rt.start(host="127.0.0.1", port=0)
    pre.start_registration(f"http://127.0.0.1:{rt.port}",
                           replica_id="pre-0", model="t",
                           interval_s=0.3)
    dec.start_registration(f"http://127.0.0.1:{rt.port}",
                           replica_id="dec-0", model="t",
                           interval_s=0.3)
    deadline = time.time() + 30
    while time.time() < deadline and sum(
            r["healthy"] for r in rt.replicas()) < 2:
        time.sleep(0.05)
    assert sum(r["healthy"] for r in rt.replicas()) == 2
    yield mixed, pre, dec, rt
    rt.stop()
    mixed.stop()
    pre.stop()
    dec.stop()


def _post(port, payload, path="/generate"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", path, json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, dict(resp.headers), body
    finally:
        conn.close()


def _stream_view(body):
    """(concatenated streamed tokens, terminal event) — the stream
    surfaces that must be identical across the hop (frame coalescing
    is timing-dependent and may differ legally)."""
    toks, done = [], None
    for line in body.strip().split(b"\n"):
        ev = json.loads(line)
        if "done" in ev:
            done = ev
        elif "tokens" in ev:
            toks += ev["tokens"]
        elif "token" in ev:
            toks.append(ev["token"])
    return toks, done


def test_codec_roundtrips_checkpoint_shapes_exactly():
    """The wire codec must round-trip every type a preempt checkpoint
    carries, bit-exactly: nested dicts with int keys, numpy arrays of
    every pool dtype (bfloat16 included — ml_dtypes stringifies as
    opaque void, the bug chaos episode 12 caught), tuples, frozensets,
    non-finite floats."""
    ml_dtypes = pytest.importorskip("ml_dtypes")

    bf16 = np.arange(12, dtype=np.float32).astype(
        ml_dtypes.bfloat16).reshape(3, 4)
    state = {
        "kv": {0: {"k": bf16, "v": np.ones((2, 2), np.int8)},
               "scales": np.linspace(0, 1, 5).astype(np.float32)},
        "record": (np.array([1, 2, 3], np.int32), -1, 3,
                   np.float32(0.5), None),
        "stops": frozenset({5, 9}),
        "outputs": [4, 5, 6],
        "inf": float("inf"),
        "nan": float("nan"),
        "blob": b"\x00\xff",
        "gstate": -1,
    }
    out = load_payload(dump_payload(state))
    assert out["kv"][0]["k"].dtype == ml_dtypes.bfloat16
    assert np.array_equal(out["kv"][0]["k"].view(np.uint16),
                          bf16.view(np.uint16))
    assert out["kv"][0]["v"].dtype == np.int8
    assert out["kv"]["scales"].dtype == np.float32
    assert isinstance(out["record"], tuple)
    assert np.array_equal(out["record"][0], state["record"][0])
    assert out["record"][0].dtype == np.int32
    assert out["stops"] == frozenset({5, 9})
    assert out["outputs"] == [4, 5, 6]
    assert out["inf"] == float("inf")
    assert out["nan"] != out["nan"]
    assert out["blob"] == b"\x00\xff"
    assert out["gstate"] == -1


def test_codec_rejects_malformed_payloads():
    with pytest.raises(MigrateError):
        load_payload(b"not a payload")
    with pytest.raises(MigrateError):
        load_payload(b"TPUMIG1\n\x00\x00")       # truncated header
    good = dump_payload({"a": np.arange(4)})
    with pytest.raises(MigrateError):
        load_payload(good[:-3])                   # truncated blob


def _int8_engine(model, params):
    return ServingEngine(model, params, n_slots=2, chunk=8,
                         max_new_tokens=12, auto_prefix_min=4,
                         kv_paging=True, kv_dtype="int8")


def test_codec_roundtrips_int8_pool_preemption():
    """preempt -> encode -> decode -> resume under --kv-dtype int8:
    quantized pools checkpoint raw int8 bytes + scales, so a stream
    that crossed the codec continues bit-identically to an int8 ref
    that was never preempted — int8's lossiness lives at write time,
    never in the checkpoint."""
    model, params = _build()
    eng, ref = _int8_engine(model, params), _int8_engine(model, params)
    a, b = list(range(1, 10)), list(range(30, 40))
    sa = eng.admit(a)
    sb = eng.admit(b, temperature=0.7, seed=13)
    ra = ref.admit(a)
    rb = ref.admit(b, temperature=0.7, seed=13)
    for _ in range(3):
        eng.step()
        ref.step()
    state = load_payload(dump_payload(eng.preempt(sb)))
    for _ in range(2):
        eng.step()
        ref.step()
    sb2 = eng.resume(state)
    while any(eng.active):
        eng.step()
    while any(ref.active):
        ref.step()
    assert eng.output(sa) == ref.output(ra)
    assert eng.output(sb2) == ref.output(rb)
    eng._pool.check()


def test_codec_roundtrips_int8_session_checkpoints():
    """Session-tier checkpoints ride the same codec: an int8 parked
    conversation demoted, codec-round-tripped, and resumed on a SECOND
    engine serves turn 2 byte-identically to the first engine's warm
    device hit — both read the same quantized storage."""
    model, params = _build()
    p1, p2 = list(range(1, 13)), [40, 41, 42]

    def turn(eng, prompt, **kw):
        s = eng.admit(list(prompt), **kw)
        while not eng.finished(s):
            eng.step()
        return s, eng.output(s)

    eng1 = _int8_engine(model, params)
    s, out1 = turn(eng1, p1, session="c8")
    eng1.park_session(s, "c8", len(out1))
    chain = p1 + out1 + p2
    _, warm = turn(eng1, chain, session="c8")

    eng2 = _int8_engine(model, params)
    s, out1b = turn(eng2, p1, session="c8")
    assert out1b == out1
    eng2.park_session(s, "c8", len(out1b))
    raw = dump_payload(eng2.demote_session(eng2.session_slots()["c8"]))
    eng3 = _int8_engine(model, params)
    eng3.resume_session(load_payload(raw))
    _, moved = turn(eng3, chain, session="c8")
    assert moved == warm
    eng3._pool.check()


# the equivalence matrix: greedy / seeded sampling / penalties /
# grammar — each long enough to migrate
MATRIX = [
    pytest.param({"tokens": LONG, "max_new_tokens": 10}, id="greedy"),
    pytest.param({"tokens": LONG, "max_new_tokens": 10,
                  "temperature": 0.8, "top_p": 0.9, "seed": 7},
                 id="seeded"),
    pytest.param({"tokens": LONG, "max_new_tokens": 10,
                  "presence_penalty": 0.5, "frequency_penalty": 0.2,
                  "repetition_penalty": 1.1, "temperature": 0.6,
                  "seed": 3}, id="penalties"),
    pytest.param({"tokens": LONG, "max_new_tokens": 10,
                  "guided_regex": r"\d+"}, id="grammar"),
]


@pytest.mark.parametrize("payload", MATRIX)
def test_unary_byte_identical(stack, payload):
    mixed, pre, dec, rt = stack
    body = dict(payload)
    body["stream"] = False
    st_m, _, out_m = _post(mixed.port, body)
    st_r, hd_r, out_r = _post(rt.port, body)
    assert st_m == st_r == 200, (out_m, out_r)
    assert hd_r.get("X-Replica") == "dec-0"   # decode served it
    assert out_r == out_m                      # BYTE-identical


@pytest.mark.parametrize("payload", MATRIX)
def test_stream_identical(stack, payload):
    mixed, pre, dec, rt = stack
    st_m, _, out_m = _post(mixed.port, dict(payload))
    st_r, hd_r, out_r = _post(rt.port, dict(payload))
    assert st_m == st_r == 200
    assert hd_r.get("X-Replica") == "dec-0"
    assert _stream_view(out_r) == _stream_view(out_m)


def test_grammar_state_rehomed_across_offset_skew(stack):
    """The decode engine's combined grammar table has DIFFERENT row
    offsets than the prefill engine's (a decoy pattern registered
    first): the migrated gstate must still continue the constraint
    bit-identically — the rel/abs translation, not luck."""
    mixed, pre, dec, rt = stack
    # decoy grammar registered on the DECODE engine only
    st, _, _ = _post(dec.port, {"tokens": LONG[:8],
                                "guided_regex": "[ab]+",
                                "max_new_tokens": 4, "stream": False})
    assert st == 200
    assert dec.engine.n_grammars >= 1
    payload = {"tokens": list(reversed(LONG)), "max_new_tokens": 8,
               "guided_regex": r"[0-9]+\.[0-9]+", "stream": False}
    st_m, _, out_m = _post(mixed.port, payload)
    st_r, hd_r, out_r = _post(rt.port, payload)
    assert st_m == st_r == 200
    assert hd_r.get("X-Replica") == "dec-0"
    assert out_r == out_m
    # the constraint really was re-homed: both engines know the
    # pattern, at (potentially) different offsets
    gid_p = pre._grammar_gids[r"[0-9]+\.[0-9]+"]
    gid_d = dec._grammar_gids[r"[0-9]+\.[0-9]+"]
    assert pre.engine._growbounds[gid_p][0] \
        != dec.engine._growbounds[gid_d][0]


def test_apc_hit_paths_migrate_identically(stack):
    """Admissions that hit the prefill replica's automatic prefix
    cache — a chunk-aligned shared prefix AND a full-prompt exact
    repeat — must migrate byte-identically too (the donor splice and
    the zero-extend repeat both checkpoint exactly)."""
    mixed, pre, dec, rt = stack
    base = [(i * 11) % 126 + 1 for i in range(64)]
    warm = {"tokens": base, "max_new_tokens": 6, "stream": False}
    # donor: a NORMAL completion on the prefill replica (migrated
    # admissions free their pages at export, so the donor must come
    # from a directly-served request) and the same on the baseline
    st, _, _ = _post(pre.port, warm)
    assert st == 200
    st, _, _ = _post(mixed.port, warm)
    assert st == 200
    hits_before = pre.engine.stats()["prefix_cache_hits"]
    # exact repeat -> the zero-extend donor path, then migration
    st_m, _, out_m = _post(mixed.port, warm)
    st_r, hd_r, out_r = _post(rt.port, warm)
    assert st_m == st_r == 200
    assert hd_r.get("X-Replica") == "dec-0"
    assert out_r == out_m
    # shared chunk-aligned prefix with a fresh tail -> partial match
    tail = {"tokens": base[:32] + [99, 98, 97, 96],
            "max_new_tokens": 6, "stream": False}
    st_m, _, out_m = _post(mixed.port, tail)
    st_r, hd_r, out_r = _post(rt.port, tail)
    assert st_m == st_r == 200
    assert out_r == out_m
    assert pre.engine.stats()["prefix_cache_hits"] > hits_before


def test_openai_unary_identical_modulo_ids(stack):
    """OpenAI completions migrate too: byte-identical after
    normalizing the per-request id/created fields (same contract as
    the router's SSE equivalence test)."""
    mixed, pre, dec, rt = stack
    payload = {"prompt": "x" * 80, "max_tokens": 6,
               "temperature": 0.0}

    def norm(b):
        b = re.sub(rb"cmpl-[0-9a-f]+", b"cmpl-X", b)
        return re.sub(rb'"created": \d+', b'"created": 0', b)

    st_m, _, out_m = _post(mixed.port, payload,
                           path="/v1/completions")
    st_r, hd_r, out_r = _post(rt.port, payload,
                              path="/v1/completions")
    assert st_m == st_r == 200, (out_m, out_r)
    assert hd_r.get("X-Replica") == "dec-0"
    assert norm(out_r) == norm(out_m)


def test_short_and_multicopy_requests_skip_disagg(stack):
    mixed, pre, dec, rt = stack

    def migrations(outcome):
        samples = obs.parse_exposition(rt.registry.render())
        vals = [v for n, lab, v in samples
                if n == "tpu_router_migrations_total"
                and lab.get("outcome") == outcome]
        return vals[0] if vals else 0.0

    before = migrations("ok")
    st, _, _ = _post(rt.port, {"tokens": [1, 2, 3],
                               "max_new_tokens": 4})
    assert st == 200
    st, _, body = _post(rt.port, {"tokens": LONG, "n": 2,
                                  "max_new_tokens": 4,
                                  "stream": False})
    assert st == 200
    assert len(json.loads(body)["choices"]) == 2
    assert migrations("ok") == before


def test_finished_at_first_token_declines_and_serves(stack):
    """A 1-token budget has nothing to migrate: the prefill replica
    serves the complete response itself and the router relays it
    (outcome=declined), byte-identical to the baseline."""
    mixed, pre, dec, rt = stack
    payload = {"tokens": LONG, "max_new_tokens": 1, "stream": False}
    st_m, _, out_m = _post(mixed.port, payload)
    st_r, hd_r, out_r = _post(rt.port, payload)
    assert st_m == st_r == 200
    assert hd_r.get("X-Replica") == "pre-0"    # prefill served whole
    assert out_r == out_m
    samples = obs.parse_exposition(rt.registry.render())
    declined = [v for n, lab, v in samples
                if n == "tpu_router_migrations_total"
                and lab.get("outcome") == "declined"]
    assert declined and declined[0] >= 1


def test_migration_metrics_journal_and_statz(stack):
    """Metric/journal proof across all three surfaces: the router's
    migration counters + ship histogram + stitched journal, and both
    replicas' /statz migrations ledgers in role lock-step."""
    mixed, pre, dec, rt = stack
    st, _, _ = _post(rt.port, {"tokens": LONG, "max_new_tokens": 6,
                               "stream": False})
    assert st == 200
    samples = obs.parse_exposition(rt.registry.render())
    ok = [v for n, lab, v in samples
          if n == "tpu_router_migrations_total"
          and lab.get("outcome") == "ok"]
    assert ok and ok[0] >= 1
    ships = [v for n, lab, v in samples
             if n == "tpu_router_migrate_seconds_count"]
    assert ships and ships[0] >= 1
    roles = {lab.get("role"): v for n, lab, v in samples
             if n == "tpu_router_role_requests_total"}
    assert roles.get("prefill", 0) >= 1
    assert roles.get("decode", 0) >= 1
    names = [e["name"] for e in rt.recorder.events()]
    assert "tpu_router_migrated" in names
    statz_p = pre.statz()
    statz_d = dec.statz()
    assert statz_p["role"] == "prefill"
    assert statz_d["role"] == "decode"
    assert statz_p["migrations"]["out"] >= 1
    assert statz_d["migrations"]["in"] >= 1
    p_names = [e["name"] for e in pre.recorder.events()]
    d_names = [e["name"] for e in dec.recorder.events()]
    assert "tpu_serve_migrate_out" in p_names
    assert "tpu_serve_migrate_in" in d_names


def test_decode_unreachable_falls_back_before_any_byte(stack):
    """Kill-mid-migration containment, in-process form: the decode
    class looks routable but refuses connections — the request must
    complete through normal routing (no client byte was sent when
    the migration failed), with the fallback journaled."""
    import socket

    mixed, pre, dec, rt = stack
    rt2 = RouterServer(statz_interval_s=60.0, replica_ttl_s=60.0,
                       seed=9, prefill_threshold=16,
                       breaker_threshold=10)
    rt2.start(host="127.0.0.1", port=0)
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        rt2.register({"address": f"127.0.0.1:{pre.port}",
                      "replica_id": "pre-0", "role": "prefill"})
        rt2.register({"address": f"127.0.0.1:{dead_port}",
                      "replica_id": "dec-dead", "role": "decode"})
        payload = {"tokens": LONG, "max_new_tokens": 6,
                   "stream": False}
        st_m, _, out_m = _post(mixed.port, payload)
        st_r, hd_r, out_r = _post(rt2.port, payload)
        assert st_r == 200
        assert hd_r.get("X-Replica") == "pre-0"
        assert out_r == out_m          # recomputed whole, still exact
        samples = obs.parse_exposition(rt2.registry.render())
        fb = [v for n, lab, v in samples
              if n == "tpu_router_migrations_total"
              and lab.get("outcome") == "fallback"]
        assert fb and fb[0] >= 1
        names = [e["name"] for e in rt2.recorder.events()]
        assert "tpu_router_migrate_fallback" in names
    finally:
        rt2.stop()


def test_tenant_quota_global_not_rate_times_replicas(stack):
    """The acceptance bar for globally-correct quotas: a tenant quota
    of RATE on a 2-replica fleet sheds at RATE — not 2 x RATE — under
    evenly-spread load, metric/journal-proven.  Pinning is OFF so the
    spread is real; the router-level bucket is the global arbiter."""
    from tpu_k8s_device_plugin.workloads.qos import (
        parse_tenant_quotas,
    )

    mixed, pre, dec, rt = stack
    # burst 60 tokens, cost per request = 8 prompt + 4 budget = 12:
    # exactly 5 requests fit the burst whatever replica they land on
    rt2 = RouterServer(statz_interval_s=60.0, replica_ttl_s=60.0,
                       seed=13, disagg=False, tenant_pinning=False,
                       tenant_quotas=parse_tenant_quotas(
                           ["acme=0.001:60"]))
    rt2.start(host="127.0.0.1", port=0)
    try:
        rt2.register({"address": f"127.0.0.1:{pre.port}",
                      "replica_id": "pre-0", "role": "prefill"})
        rt2.register({"address": f"127.0.0.1:{dec.port}",
                      "replica_id": "dec-0", "role": "decode"})
        # prompts alternating affinity targets (the ring is
        # id-derived, so this is deterministic): even requests land
        # on pre-0, odd on dec-0 — a genuinely even spread
        from tpu_k8s_device_plugin.workloads.router import (
            affinity_key,
        )

        def prompt_for(rid, start):
            for i in range(start, start + 500):
                cand = [(i + j) % 126 + 1 for j in range(8)]
                if rt2.affinity_target(affinity_key(
                        {"tokens": cand},
                        rt2.prefix_chunk)) == rid:
                    return cand
            raise AssertionError(f"no prompt hashed to {rid}")

        statuses, served_by = [], set()
        for i in range(10):
            rid = "pre-0" if i % 2 == 0 else "dec-0"
            st, hd, _ = _post(rt2.port, {
                "tokens": prompt_for(rid, i * 37 + 1),
                "max_new_tokens": 4, "stream": False,
                "tenant": "acme"})
            statuses.append(st)
            if st == 200:
                served_by.add(hd.get("X-Replica"))
        ok = sum(s == 200 for s in statuses)
        shed = sum(s == 429 for s in statuses)
        # RATE-enforced globally: the 60-token burst admits 5, NOT 10
        # (a per-replica bucket of the same size would admit 10)
        assert ok == 5, statuses
        assert shed == 5, statuses
        # the load really spread over both replicas (pinning off)
        assert served_by == {"pre-0", "dec-0"}, served_by
        samples = obs.parse_exposition(rt2.registry.render())
        qshed = [v for n, lab, v in samples
                 if n == "tpu_router_shed_total"
                 and lab.get("reason") == "tenant_quota"]
        assert qshed and qshed[0] == 5
        names = [e["name"] for e in rt2.recorder.events()]
        assert "tpu_router_tenant_quota_shed" in names
    finally:
        rt2.stop()


def test_prefill_unreachable_falls_back(stack):
    """The prefill class down entirely: the router skips disagg and
    the decode replica serves the request whole."""
    import socket

    mixed, pre, dec, rt = stack
    rt2 = RouterServer(statz_interval_s=60.0, replica_ttl_s=60.0,
                       seed=11, prefill_threshold=16,
                       breaker_threshold=10)
    rt2.start(host="127.0.0.1", port=0)
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        rt2.register({"address": f"127.0.0.1:{dead_port}",
                      "replica_id": "pre-dead", "role": "prefill"})
        rt2.register({"address": f"127.0.0.1:{dec.port}",
                      "replica_id": "dec-0", "role": "decode"})
        payload = {"tokens": LONG, "max_new_tokens": 6,
                   "stream": False}
        st_m, _, out_m = _post(mixed.port, payload)
        st_r, hd_r, out_r = _post(rt2.port, payload)
        assert st_r == 200
        assert hd_r.get("X-Replica") == "dec-0"
        assert out_r == out_m
    finally:
        rt2.stop()
