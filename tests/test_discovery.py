"""Discovery + topology tests against fixture sysfs/tpu-env trees.

Mirrors the reference's fixture-driven parser tests
(amdgpu_test.go:122-287) with the TPU fixture trees under testdata/.
"""

import os

import pytest

from tpu_k8s_device_plugin.tpu import (
    get_tpu_chips,
    is_homogeneous,
    parse_accelerator_type,
    read_tpu_env,
    unique_partition_config_count,
)
from tpu_k8s_device_plugin.tpu.discovery import (
    get_driver_versions,
    get_firmware_version,
    list_accel_nodes,
    list_tpu_pci_devices,
)
from tpu_k8s_device_plugin.tpu.topology import (
    IciTopology,
    partition_modes_from_env,
    topology_from_env,
)


def fixture(testdata, name):
    root = os.path.join(testdata, name)
    return (
        os.path.join(root, "sys"),
        os.path.join(root, "run", "tpu", "tpu-env"),
    )


# ---------------------------------------------------------------------------
# tpu-env parsing + accelerator types
# ---------------------------------------------------------------------------

def test_parse_accelerator_type():
    spec, chips = parse_accelerator_type("v5litepod-8")
    assert spec.generation == "v5e" and chips == 8 and spec.cores_per_chip == 1
    spec, chips = parse_accelerator_type("v5p-8")
    assert spec.generation == "v5p" and chips == 4 and spec.cores_per_chip == 2
    spec, chips = parse_accelerator_type("v4-32")
    assert spec.generation == "v4" and chips == 16
    with pytest.raises(ValueError):
        parse_accelerator_type("h100-8")
    with pytest.raises(ValueError):
        parse_accelerator_type("not a type")


def test_read_tpu_env_formats(tmp_path):
    p = tmp_path / "tpu-env"
    p.write_text(
        "ACCELERATOR_TYPE: 'v5litepod-8'\n"
        "# comment\n"
        "WORKER_ID=3\n"
        "garbage line without separator\n"
        'HOST_BOUNDS: "1,1,1"\n'
    )
    env = read_tpu_env(str(p))
    assert env["ACCELERATOR_TYPE"] == "v5litepod-8"
    assert env["WORKER_ID"] == "3"
    assert env["HOST_BOUNDS"] == "1,1,1"


def test_read_tpu_env_missing_file():
    assert read_tpu_env("/nonexistent/tpu-env") == {}


# ---------------------------------------------------------------------------
# sysfs enumeration
# ---------------------------------------------------------------------------

def test_list_accel_nodes(testdata):
    sys_root, _ = fixture(testdata, "v5e-8")
    nodes = list_accel_nodes(sys_root)
    assert [i for i, _ in nodes] == list(range(8))
    # the device symlink resolves into the PCI tree
    assert nodes[0][1].endswith("0000:00:04.0")


def test_pci_fallback_enumeration(testdata):
    sys_root, _ = fixture(testdata, "vfio-pf")
    assert list_accel_nodes(sys_root) == []
    pci = list_tpu_pci_devices(sys_root)
    assert len(pci) == 4
    assert all(p.endswith(".0") for p in pci)


def test_get_tpu_chips_v5e8(testdata):
    sys_root, env_path = fixture(testdata, "v5e-8")
    devs, topo = get_tpu_chips(sys_root, "/dev", env_path)
    assert len(devs) == 8
    assert topo.topology_str == "2x4"
    assert topo.local_chip_count == 8 and topo.num_workers == 1
    d0 = devs["0000:00:04.0"]
    assert d0.accel_index == 0 and d0.coords == (0, 0, 0)
    assert d0.device_id == "0x0062" and d0.vendor_id == "0x1ae0"
    assert d0.dev_path == "/dev/accel0"
    # NUMA split: first four chips node 0, last four node 1
    by_idx = sorted(devs.values(), key=lambda d: d.accel_index)
    assert [d.numa_node for d in by_idx] == [0, 0, 0, 0, 1, 1, 1, 1]
    # x-fastest coordinate assignment on the 2x4 grid
    assert by_idx[1].coords == (1, 0, 0)
    assert by_idx[2].coords == (0, 1, 0)
    assert by_idx[7].coords == (1, 3, 0)
    assert is_homogeneous(devs)
    assert unique_partition_config_count(devs) == {"tpu": 8}


def test_get_tpu_chips_multihost_worker0(testdata):
    sys_root, env_path = fixture(testdata, "v5e-16-host0")
    devs, topo = get_tpu_chips(sys_root, "/dev", env_path)
    assert len(devs) == 8
    assert topo.topology_str == "4x4"
    assert topo.num_workers == 2 and topo.worker_id == 0
    # worker 0 occupies x in [0,2); global == local here
    assert topo.global_chip_coords(7) == (1, 3, 0)


def test_get_tpu_chips_multihost_worker1(testdata):
    """Worker 1's chips sit at x in [2,4) of the global 4x4 mesh: local
    coords match worker 0's, global coords carry the host offset."""
    sys_root, env_path = fixture(testdata, "v5e-16-host1")
    devs, topo = get_tpu_chips(sys_root, "/dev", env_path)
    assert len(devs) == 8
    assert topo.topology_str == "4x4"
    assert topo.num_workers == 2 and topo.worker_id == 1
    assert topo.global_chip_coords(0) == (2, 0, 0)
    assert topo.global_chip_coords(7) == (3, 3, 0)
    by_idx = sorted(devs.values(), key=lambda d: d.accel_index)
    assert by_idx[0].coords == (0, 0, 0)  # local grid is worker-relative


def test_get_tpu_chips_v5p_partitioning(testdata):
    sys_root, env_path = fixture(testdata, "v5p-8")
    devs, topo = get_tpu_chips(sys_root, "/dev", env_path)
    assert len(devs) == 4
    assert topo.spec.cores_per_chip == 2
    assert {d.partition_mode for d in devs.values()} == {"chip"}

    sys_root, env_path = fixture(testdata, "v5p-8-core")
    devs, _ = get_tpu_chips(sys_root, "/dev", env_path)
    assert {d.partition_mode for d in devs.values()} == {"core"}
    assert unique_partition_config_count(devs) == {"tpucore": 4}

    sys_root, env_path = fixture(testdata, "v5p-8-hetero")
    devs, _ = get_tpu_chips(sys_root, "/dev", env_path)
    assert not is_homogeneous(devs)
    assert unique_partition_config_count(devs) == {"tpu": 2, "tpucore": 2}


def test_get_tpu_chips_no_metadata_fallback(testdata):
    """Without tpu-env, generation comes from the PCI device id and the grid
    from a squarish factorisation of the chip count."""
    sys_root, env_path = fixture(testdata, "v5e-4-nometa")
    devs, topo = get_tpu_chips(sys_root, "/dev", env_path)
    assert len(devs) == 4
    assert topo.spec is not None and topo.spec.generation == "v5e"
    assert topo.chips_per_host_bounds == (2, 2, 1)


def test_iommu_groups_discovered(testdata):
    sys_root, env_path = fixture(testdata, "v5e-8")
    devs, _ = get_tpu_chips(sys_root, "/dev", env_path)
    assert devs["0000:00:04.0"].iommu_group == "8"
    assert devs["0000:00:0b.0"].iommu_group == "15"


# ---------------------------------------------------------------------------
# ICI distance model
# ---------------------------------------------------------------------------

def test_ici_distance_mesh():
    topo = IciTopology(chips_per_host_bounds=(2, 4, 1))
    assert topo.ici_distance(0, 1) == 1     # (0,0)-(1,0)
    assert topo.ici_distance(0, 2) == 1     # (0,0)-(0,1)
    assert topo.ici_distance(0, 7) == 4     # (0,0)-(1,3)
    assert topo.ici_distance(3, 3) == 0


def test_ici_distance_torus_wrap():
    topo = IciTopology(chips_per_host_bounds=(4, 4, 1), wrap=(True, True, False))
    # (0,0) to (3,0): 3 hops unwrapped, 1 hop around the torus
    assert topo.ici_distance(0, 3) == 1
    # (0,0) to (3,3): 1 + 1 with both wraps
    assert topo.ici_distance(0, 15) == 2


def test_partition_modes_overrides():
    env = {"TPU_PARTITION_MODE_OVERRIDES": "1:core, 3:core, 9:core, x:core"}
    assert partition_modes_from_env(env, 4) == ["chip", "core", "chip", "core"]
    env = {"TPU_PARTITION_MODE": "core"}
    assert partition_modes_from_env(env, 2) == ["core", "core"]


def test_topology_from_env_derives_host_grid():
    env = {"ACCELERATOR_TYPE": "v5litepod-16", "CHIPS_PER_HOST_BOUNDS": "2,4,1"}
    topo = topology_from_env(env)
    assert topo.num_workers == 2


# ---------------------------------------------------------------------------
# version probing (labeller inputs)
# ---------------------------------------------------------------------------

def test_driver_versions(testdata):
    sys_root, _ = fixture(testdata, "v5e-8")
    v = get_driver_versions(sys_root)
    assert v["driver-version"] == "1.8.0"
    assert v["driver-src-version"].endswith("TPU")


def test_firmware_version(testdata):
    sys_root, _ = fixture(testdata, "v5e-8")
    assert get_firmware_version(sys_root, accel_index=0) == "2.12.1"
    assert get_firmware_version("/nonexistent", accel_index=0) == ""
