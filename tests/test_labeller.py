"""Labeller tests: generators against fixtures, controller against a fake
API server.

The reference tests only label-key inventory and stale-removal on
constructed Node objects (main_test.go:42-125); this adds what it lacks —
an end-to-end reconcile against a live (local, fake) API server asserting
the actual PATCH bodies.
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_k8s_device_plugin.labeller import (
    LabelContext,
    NodeClient,
    NodeLabelController,
    generate_labels,
)
from tpu_k8s_device_plugin.labeller.controller import label_delta
from tpu_k8s_device_plugin.types import constants


def ctx_for(testdata, name, driver_type=constants.CONTAINER):
    root = os.path.join(testdata, name)
    return LabelContext.collect(
        driver_type=driver_type,
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )


class TestGenerators:
    def test_v5e8_labels(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5e-8"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-8"
        assert labels[f"{base}.topology"] == "2x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.cores-per-chip"] == "1"
        assert labels[f"{base}.worker-id"] == "0"
        assert labels[f"{base}.num-workers"] == "1"
        assert labels[f"{base}.product-name"] == "TPU-v5e"
        assert labels[f"{base}.hbm"] == "16Gi"
        assert labels[f"{base}.partitioning-supported"] == "false"
        assert labels[f"{base}.core-partition"] == "chip"
        assert labels[f"{base}.mode"] == "container"
        # every label is mirrored under the beta prefix
        beta = constants.LABEL_PREFIX_BETA
        for key, val in list(labels.items()):
            if key.startswith(base + "."):
                assert labels[key.replace(base, beta, 1)] == val

    def test_multi_host_slice_identity(self, testdata):
        """Worker 0 of a 2-host v5e-16: the scheduler-facing slice shape
        must be the global topology, not the local grid."""
        labels = generate_labels(ctx_for(testdata, "v5e-16-host0"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-16"
        assert labels[f"{base}.topology"] == "4x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.worker-id"] == "0"
        assert labels[f"{base}.num-workers"] == "2"

    def test_multi_host_slice_identity_worker1(self, testdata):
        """Worker 1 of the same slice must emit the SAME global topology —
        the label is slice-scoped, not host-scoped — with its own id."""
        labels = generate_labels(ctx_for(testdata, "v5e-16-host1"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-16"
        assert labels[f"{base}.topology"] == "4x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.worker-id"] == "1"
        assert labels[f"{base}.num-workers"] == "2"

    def test_slice_labels_from_membership_file(self, testdata, tmp_path,
                                               monkeypatch):
        """With a formed slice persisted by the plugin's slice client, the
        labeller emits the slice-id (pod-affinity key) and this host's
        rendezvous rank; without the file, neither label appears."""
        from tpu_k8s_device_plugin.slice import Membership, save_membership

        monkeypatch.setattr("socket.gethostname", lambda: "host-b")
        root = os.path.join(testdata, "v5e-16-host1")
        kwargs = dict(
            driver_type=constants.CONTAINER,
            sysfs_root=os.path.join(root, "sys"),
            dev_root=os.path.join(root, "dev"),
            tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
        )
        base = constants.LABEL_PREFIX

        no_file = generate_labels(LabelContext.collect(
            slice_state_path=str(tmp_path / "absent.json"), **kwargs))
        assert f"{base}.slice-id" not in no_file
        assert f"{base}.slice-rank" not in no_file

        state = tmp_path / "membership.json"
        save_membership(str(state), Membership(
            slice_id="abc123def456", generation=1,
            hostnames=("host-a", "host-b"),
            coordinator_address="host-a:8476",
        ))
        labels = generate_labels(LabelContext.collect(
            slice_state_path=str(state), **kwargs))
        assert labels[f"{base}.slice-id"] == "abc123def456"
        assert labels[f"{base}.slice-rank"] == "1"

    def test_slice_shape_labels_track_reshape(self, testdata, tmp_path,
                                              monkeypatch):
        """Gang schedulers place against the REAL topology: generation,
        current worker count, and the degraded flag all come from the
        membership file and move when the slice reshapes."""
        from tpu_k8s_device_plugin.slice import Membership, save_membership

        monkeypatch.setattr("socket.gethostname", lambda: "host-a")
        root = os.path.join(testdata, "v5e-16-host0")
        kwargs = dict(
            driver_type=constants.CONTAINER,
            sysfs_root=os.path.join(root, "sys"),
            dev_root=os.path.join(root, "dev"),
            tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
        )
        base = constants.LABEL_PREFIX
        state = tmp_path / "membership.json"
        save_membership(str(state), Membership(
            slice_id="aaa111", generation=1,
            hostnames=("host-a", "host-b"),
            coordinator_address="host-a:8476",
        ))
        labels = generate_labels(LabelContext.collect(
            slice_state_path=str(state), **kwargs))
        assert labels[f"{base}.slice-generation"] == "1"
        assert labels[f"{base}.slice-workers"] == "2"
        assert labels[f"{base}.slice-degraded"] == "false"

        # host-b evicted: survivors re-formed into a degraded gen 2
        save_membership(str(state), Membership(
            slice_id="bbb222", generation=2, hostnames=("host-a",),
            coordinator_address="host-a:8476",
            reshaped_from=("aaa111",), degraded=True,
        ))
        labels = generate_labels(LabelContext.collect(
            slice_state_path=str(state), **kwargs))
        assert labels[f"{base}.slice-id"] == "bbb222"
        assert labels[f"{base}.slice-generation"] == "2"
        assert labels[f"{base}.slice-workers"] == "1"
        assert labels[f"{base}.slice-degraded"] == "true"

    def test_v5p_partitioned_host(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5p-8-core"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.partitioning-supported"] == "true"
        assert labels[f"{base}.cores-per-chip"] == "2"
        assert labels[f"{base}.core-partition"] == "core"

    def test_hetero_host_reports_mixed(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5p-8-hetero"))
        assert labels[f"{constants.LABEL_PREFIX}.core-partition"] == "mixed"

    def test_enabled_subset(self, testdata):
        labels = generate_labels(
            ctx_for(testdata, "v5e-8"), enabled=["topology"]
        )
        assert set(labels) == {
            f"{constants.LABEL_PREFIX}.topology",
            f"{constants.LABEL_PREFIX_BETA}.topology",
        }

    def test_empty_values_dropped(self, testdata):
        # v5e-4-nometa has no tpu-env: no accelerator-type/worker labels,
        # but sysfs-derived ones still appear
        labels = generate_labels(ctx_for(testdata, "v5e-4-nometa"))
        assert f"{constants.LABEL_PREFIX}.accelerator-type" not in labels
        assert labels[f"{constants.LABEL_PREFIX}.chips-per-host"] == "4"


class TestLabelValueValidity:
    """One invalid value rejects the whole merge patch, stopping EVERY
    label from reconciling (ADVICE r1) — values must be validated and
    over-long joins capped."""

    def test_long_device_id_join_capped(self):
        from tpu_k8s_device_plugin.labeller.generators import (
            LabelContext, _device_id, is_valid_label_value,
        )
        from tpu_k8s_device_plugin.tpu.discovery import TpuDevice

        chips = {
            f"0000:00:{i:02x}.0": TpuDevice(
                id=f"0000:00:{i:02x}.0", accel_index=i,
                pci_address=f"0000:00:{i:02x}.0", device_id=f"0x{i:04x}",
            )
            for i in range(4, 24)  # 20 distinct ids: raw join = 139 chars
        }
        val = _device_id(LabelContext(constants.CONTAINER, chips=chips))
        assert is_valid_label_value(val), val
        assert val.endswith("-more")
        assert val.startswith("0x0004_")

    def test_invalid_generated_value_dropped_not_fatal(
        self, testdata, monkeypatch
    ):
        from tpu_k8s_device_plugin.labeller import generators

        bad = dict(generators.LABEL_GENERATORS)
        bad["firmware"] = lambda ctx: "has spaces!"  # invalid label value
        monkeypatch.setattr(generators, "LABEL_GENERATORS", bad)
        labels = generate_labels(ctx_for(testdata, "v5e-8"))
        # the bad label is dropped; everything else still reconciles
        assert f"{constants.LABEL_PREFIX}.firmware" not in labels
        assert labels[f"{constants.LABEL_PREFIX}.topology"] == "2x4"

    def test_validity_rules(self):
        from tpu_k8s_device_plugin.labeller.generators import (
            is_valid_label_value,
        )

        assert is_valid_label_value("v5litepod-8")
        assert is_valid_label_value("a")
        assert not is_valid_label_value("x" * 64)
        assert is_valid_label_value("x" * 63)
        assert not is_valid_label_value("-leading")
        assert not is_valid_label_value("trailing-")
        assert not is_valid_label_value("has space")
        assert not is_valid_label_value("")


class TestLabelDelta:
    def test_delta_sets_removes_and_keeps(self):
        current = {
            f"{constants.LABEL_PREFIX}.topology": "2x4",
            f"{constants.LABEL_PREFIX}.stale": "old",
            f"{constants.LABEL_PREFIX_BETA}.stale": "old",
            "kubernetes.io/hostname": "n1",
        }
        desired = {
            f"{constants.LABEL_PREFIX}.topology": "4x4",
            f"{constants.LABEL_PREFIX}.chips-per-host": "8",
        }
        delta = label_delta(current, desired)
        assert delta == {
            f"{constants.LABEL_PREFIX}.topology": "4x4",
            f"{constants.LABEL_PREFIX}.chips-per-host": "8",
            f"{constants.LABEL_PREFIX}.stale": None,
            f"{constants.LABEL_PREFIX_BETA}.stale": None,
        }
        # foreign labels are never touched
        assert "kubernetes.io/hostname" not in delta

    def test_in_sync_is_empty(self):
        labels = {f"{constants.LABEL_PREFIX}.topology": "2x4"}
        assert label_delta(dict(labels), dict(labels)) == {}

    def test_event_filter_skips_self_induced_and_heartbeats(self):
        desired = {f"{constants.LABEL_PREFIX}.topology": "2x4"}
        in_sync = {
            "type": "MODIFIED",
            "object": {"metadata": {"labels": dict(desired)}},
        }
        assert not NodeLabelController._event_needs_reconcile(in_sync, desired)
        drifted = {
            "type": "MODIFIED",
            "object": {"metadata": {"labels": {}}},
        }
        assert NodeLabelController._event_needs_reconcile(drifted, desired)
        deleted = {"type": "DELETED", "object": {}}
        assert not NodeLabelController._event_needs_reconcile(deleted, desired)


class FakeApiServer:
    """Serves one Node object with resourceVersion semantics; records PATCH
    bodies, applies merge-patch label semantics, and supports scripted
    watch responses (event lists, an ERROR-410 event, or an HTTP 410)."""

    def __init__(self, node_name="test-node", labels=None):
        self.node = {
            "metadata": {
                "name": node_name,
                "labels": dict(labels or {}),
                "resourceVersion": "100",
            }
        }
        self.patches = []
        # each watch request pops one script entry: a list of event dicts
        # to stream, or "http-410" for an HTTP-level 410 response; an empty
        # queue streams nothing (long-poll that returns no events)
        self.watch_script = []
        self.watch_requests = []
        self.list_requests = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if "watch=true" in self.path:
                    outer.watch_requests.append(self.path)
                    script = (
                        outer.watch_script.pop(0)
                        if outer.watch_script else []
                    )
                    if script == "http-410":
                        self._send({"kind": "Status", "code": 410}, code=410)
                        return
                    body = b"".join(
                        json.dumps(ev).encode() + b"\n" for ev in script
                    )
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                outer.list_requests.append(self.path)
                self._send(outer.node)

            def do_PATCH(self):
                length = int(self.headers["Content-Length"])
                patch = json.loads(self.rfile.read(length))
                outer.patches.append(patch)
                meta = outer.node["metadata"]
                labels = meta["labels"]
                for k, v in patch["metadata"]["labels"].items():
                    if v is None:
                        labels.pop(k, None)
                    else:
                        labels[k] = v
                meta["resourceVersion"] = str(
                    int(meta["resourceVersion"]) + 1
                )
                self._send(outer.node)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self):
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def stop(self):
        self._server.shutdown()


@pytest.fixture
def fake_api():
    srv = FakeApiServer(
        labels={
            f"{constants.LABEL_PREFIX}.stale": "gone",
            "kubernetes.io/hostname": "test-node",
        }
    )
    yield srv
    srv.stop()


class TestController:
    def test_reconcile_applies_and_cleans(self, testdata, fake_api):
        compute = lambda: generate_labels(ctx_for(testdata, "v5e-8"))
        c = NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute
        )
        delta = c.reconcile()
        assert delta[f"{constants.LABEL_PREFIX}.stale"] is None
        assert delta[f"{constants.LABEL_PREFIX}.topology"] == "2x4"
        applied = fake_api.node["metadata"]["labels"]
        assert f"{constants.LABEL_PREFIX}.stale" not in applied
        assert applied[f"{constants.LABEL_PREFIX}.topology"] == "2x4"
        assert applied["kubernetes.io/hostname"] == "test-node"
        # second pass: in sync, no PATCH issued
        n = len(fake_api.patches)
        assert c.reconcile() == {}
        assert len(fake_api.patches) == n

    def test_dissolved_slice_clears_stale_labels_on_node(
        self, testdata, fake_api, tmp_path, monkeypatch
    ):
        """Satellite: when the membership file disappears (slice
        dissolved / state mount wiped), the next reconcile must
        actively PATCH the stale slice-* labels off the Node — a gang
        scheduler must never place against a slice that no longer
        exists."""
        from tpu_k8s_device_plugin.slice import Membership, save_membership

        monkeypatch.setattr("socket.gethostname", lambda: "host-a")
        root = os.path.join(testdata, "v5e-16-host0")
        kwargs = dict(
            driver_type=constants.CONTAINER,
            sysfs_root=os.path.join(root, "sys"),
            dev_root=os.path.join(root, "dev"),
            tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
        )
        state = tmp_path / "membership.json"
        save_membership(str(state), Membership(
            slice_id="abc123def456", generation=3,
            hostnames=("host-a", "host-b"),
            coordinator_address="host-a:8476",
        ))
        compute = lambda: generate_labels(LabelContext.collect(
            slice_state_path=str(state), **kwargs))
        c = NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute
        )
        c.reconcile()
        applied = fake_api.node["metadata"]["labels"]
        base = constants.LABEL_PREFIX
        slice_keys = [
            f"{prefix}.{key}"
            for prefix in (base, constants.LABEL_PREFIX_BETA)
            for key in ("slice-id", "slice-rank", "slice-generation",
                        "slice-workers", "slice-degraded")
        ]
        for key in slice_keys:
            assert key in applied, key
        assert applied[f"{base}.slice-id"] == "abc123def456"

        # the slice dissolves: membership file gone
        os.unlink(state)
        delta = c.reconcile()
        for key in slice_keys:
            assert delta[key] is None, key
            assert key not in fake_api.node["metadata"]["labels"], key
        # non-slice labels are untouched
        assert fake_api.node["metadata"]["labels"][
            f"{base}.topology"] == "4x4"

    def test_reconcile_recomputes(self, testdata, fake_api):
        """Labels must track live state (the reference computes once at
        startup — SURVEY §7 'What NOT to copy')."""
        state = {"fixture": "v5e-8"}
        compute = lambda: generate_labels(ctx_for(testdata, state["fixture"]))
        c = NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute
        )
        c.reconcile()
        assert (
            fake_api.node["metadata"]["labels"][
                f"{constants.LABEL_PREFIX}.chips-per-host"
            ]
            == "8"
        )
        state["fixture"] = "v5e-4-nometa"
        c.reconcile()
        labels = fake_api.node["metadata"]["labels"]
        assert labels[f"{constants.LABEL_PREFIX}.chips-per-host"] == "4"
        # accelerator-type came from v5e-8 metadata only; must be cleaned up
        assert f"{constants.LABEL_PREFIX}.accelerator-type" not in labels


class TestWatchResourceVersion:
    """Informer semantics across watch reconnects (VERDICT r1 #10):
    resume from the last seen resourceVersion; on 410 Gone re-list
    cleanly instead of generic error backoff."""

    def _controller(self, testdata, fake_api, interval=0.3):
        compute = lambda: generate_labels(ctx_for(testdata, "v5e-8"))
        return NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute,
            interval_s=interval,
        )

    def _run_until(self, c, fake_api, n_watches, timeout=10.0):
        t = threading.Thread(target=c.run, daemon=True)
        t.start()
        deadline = time.time() + timeout
        while (time.time() < deadline
               and len(fake_api.watch_requests) < n_watches):
            time.sleep(0.05)
        c.stop()
        t.join(timeout=5)
        assert not t.is_alive()
        assert len(fake_api.watch_requests) >= n_watches, \
            fake_api.watch_requests

    def test_watch_resumes_from_resource_version(self, testdata, fake_api):
        fake_api.watch_script = [[], []]  # two empty long-polls
        c = self._controller(testdata, fake_api)
        self._run_until(c, fake_api, n_watches=2)
        # reconcile PATCHed (rv 100 -> 101), then re-listed: every watch
        # must resume from the listed resourceVersion, not replay
        for req in fake_api.watch_requests[:2]:
            assert "resourceVersion=101" in req

    def test_error_event_410_triggers_clean_relist(self, testdata, fake_api):
        fake_api.watch_script = [
            [{"type": "ERROR", "object": {"kind": "Status", "code": 410}}],
            [],
        ]
        c = self._controller(testdata, fake_api)
        t0 = time.time()
        # run; afterwards verify a fresh LIST happened between the two
        # watches (clean re-list) and promptly (no interval backoff)
        self._run_until(c, fake_api, n_watches=2)
        assert len(fake_api.list_requests) >= 2, fake_api.list_requests
        # the resumed watch carries the re-listed version, not none/stale
        assert "resourceVersion=101" in fake_api.watch_requests[1]
        assert time.time() - t0 < 5.0

    def test_http_410_triggers_clean_relist(self, testdata, fake_api):
        fake_api.watch_script = ["http-410", []]
        c = self._controller(testdata, fake_api)
        t0 = time.time()
        self._run_until(c, fake_api, n_watches=2)
        assert "resourceVersion=101" in fake_api.watch_requests[0]
        # a fresh LIST ran between the 410 and the resumed watch, and the
        # recovery was immediate (not the interval/backoff path)
        assert len(fake_api.list_requests) >= 2, fake_api.list_requests
        assert "resourceVersion=101" in fake_api.watch_requests[1]
        assert time.time() - t0 < 5.0

    def test_event_rv_advances_resume_point(self, testdata, fake_api):
        """An in-sync event (e.g. a status heartbeat) must still advance
        the watch resume point to the event's resourceVersion, so a
        mid-stream reconnect doesn't replay it; no reconcile is paid."""
        desired = generate_labels(ctx_for(testdata, "v5e-8"))
        c = self._controller(testdata, fake_api)
        c._last_rv = "100"
        patches_before = len(fake_api.patches)
        event = {
            "type": "MODIFIED",
            "object": {"metadata": {
                "labels": dict(desired), "resourceVersion": "205",
            }},
        }
        out = c._process_event(event, desired)
        assert c._last_rv == "205"
        assert out is desired  # no recompute for an in-sync event
        assert len(fake_api.patches) == patches_before

    def test_drifted_event_reconciles(self, testdata, fake_api):
        desired = generate_labels(ctx_for(testdata, "v5e-8"))
        c = self._controller(testdata, fake_api)
        event = {
            "type": "MODIFIED",
            "object": {"metadata": {"labels": {}, "resourceVersion": "205"}},
        }
        c._process_event(event, desired)
        # drift -> reconcile PATCHed the fake node back in sync
        assert fake_api.node["metadata"]["labels"][
            f"{constants.LABEL_PREFIX}.topology"
        ] == "2x4"


class TestCli:
    def test_oneshot(self, testdata, fake_api, monkeypatch):
        from tpu_k8s_device_plugin.cmd import node_labeller

        root = os.path.join(testdata, "v5e-8")
        rc = node_labeller.main([
            "--oneshot",
            "--node-name", "test-node",
            "--kube-api", fake_api.url,
            "--sysfs-root", os.path.join(root, "sys"),
            "--dev-root", os.path.join(root, "dev"),
            "--tpu-env", os.path.join(root, "run", "tpu", "tpu-env"),
        ])
        assert rc == 0
        labels = fake_api.node["metadata"]["labels"]
        assert labels[f"{constants.LABEL_PREFIX}.accelerator-type"] == "v5litepod-8"

    def test_requires_node_name(self, monkeypatch):
        from tpu_k8s_device_plugin.cmd import node_labeller

        monkeypatch.delenv("DS_NODE_NAME", raising=False)
        assert node_labeller.main(["--oneshot"]) == 2
