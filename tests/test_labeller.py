"""Labeller tests: generators against fixtures, controller against a fake
API server.

The reference tests only label-key inventory and stale-removal on
constructed Node objects (main_test.go:42-125); this adds what it lacks —
an end-to-end reconcile against a live (local, fake) API server asserting
the actual PATCH bodies.
"""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_k8s_device_plugin.labeller import (
    LabelContext,
    NodeClient,
    NodeLabelController,
    generate_labels,
)
from tpu_k8s_device_plugin.labeller.controller import label_delta
from tpu_k8s_device_plugin.types import constants


def ctx_for(testdata, name, driver_type=constants.CONTAINER):
    root = os.path.join(testdata, name)
    return LabelContext.collect(
        driver_type=driver_type,
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )


class TestGenerators:
    def test_v5e8_labels(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5e-8"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-8"
        assert labels[f"{base}.topology"] == "2x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.cores-per-chip"] == "1"
        assert labels[f"{base}.worker-id"] == "0"
        assert labels[f"{base}.num-workers"] == "1"
        assert labels[f"{base}.product-name"] == "TPU-v5e"
        assert labels[f"{base}.hbm"] == "16Gi"
        assert labels[f"{base}.partitioning-supported"] == "false"
        assert labels[f"{base}.core-partition"] == "chip"
        assert labels[f"{base}.mode"] == "container"
        # every label is mirrored under the beta prefix
        beta = constants.LABEL_PREFIX_BETA
        for key, val in list(labels.items()):
            if key.startswith(base + "."):
                assert labels[key.replace(base, beta, 1)] == val

    def test_multi_host_slice_identity(self, testdata):
        """Worker 0 of a 2-host v5e-16: the scheduler-facing slice shape
        must be the global topology, not the local grid."""
        labels = generate_labels(ctx_for(testdata, "v5e-16-host0"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-16"
        assert labels[f"{base}.topology"] == "4x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.worker-id"] == "0"
        assert labels[f"{base}.num-workers"] == "2"

    def test_multi_host_slice_identity_worker1(self, testdata):
        """Worker 1 of the same slice must emit the SAME global topology —
        the label is slice-scoped, not host-scoped — with its own id."""
        labels = generate_labels(ctx_for(testdata, "v5e-16-host1"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.accelerator-type"] == "v5litepod-16"
        assert labels[f"{base}.topology"] == "4x4"
        assert labels[f"{base}.chips-per-host"] == "8"
        assert labels[f"{base}.worker-id"] == "1"
        assert labels[f"{base}.num-workers"] == "2"

    def test_v5p_partitioned_host(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5p-8-core"))
        base = constants.LABEL_PREFIX
        assert labels[f"{base}.partitioning-supported"] == "true"
        assert labels[f"{base}.cores-per-chip"] == "2"
        assert labels[f"{base}.core-partition"] == "core"

    def test_hetero_host_reports_mixed(self, testdata):
        labels = generate_labels(ctx_for(testdata, "v5p-8-hetero"))
        assert labels[f"{constants.LABEL_PREFIX}.core-partition"] == "mixed"

    def test_enabled_subset(self, testdata):
        labels = generate_labels(
            ctx_for(testdata, "v5e-8"), enabled=["topology"]
        )
        assert set(labels) == {
            f"{constants.LABEL_PREFIX}.topology",
            f"{constants.LABEL_PREFIX_BETA}.topology",
        }

    def test_empty_values_dropped(self, testdata):
        # v5e-4-nometa has no tpu-env: no accelerator-type/worker labels,
        # but sysfs-derived ones still appear
        labels = generate_labels(ctx_for(testdata, "v5e-4-nometa"))
        assert f"{constants.LABEL_PREFIX}.accelerator-type" not in labels
        assert labels[f"{constants.LABEL_PREFIX}.chips-per-host"] == "4"


class TestLabelDelta:
    def test_delta_sets_removes_and_keeps(self):
        current = {
            f"{constants.LABEL_PREFIX}.topology": "2x4",
            f"{constants.LABEL_PREFIX}.stale": "old",
            f"{constants.LABEL_PREFIX_BETA}.stale": "old",
            "kubernetes.io/hostname": "n1",
        }
        desired = {
            f"{constants.LABEL_PREFIX}.topology": "4x4",
            f"{constants.LABEL_PREFIX}.chips-per-host": "8",
        }
        delta = label_delta(current, desired)
        assert delta == {
            f"{constants.LABEL_PREFIX}.topology": "4x4",
            f"{constants.LABEL_PREFIX}.chips-per-host": "8",
            f"{constants.LABEL_PREFIX}.stale": None,
            f"{constants.LABEL_PREFIX_BETA}.stale": None,
        }
        # foreign labels are never touched
        assert "kubernetes.io/hostname" not in delta

    def test_in_sync_is_empty(self):
        labels = {f"{constants.LABEL_PREFIX}.topology": "2x4"}
        assert label_delta(dict(labels), dict(labels)) == {}

    def test_event_filter_skips_self_induced_and_heartbeats(self):
        desired = {f"{constants.LABEL_PREFIX}.topology": "2x4"}
        in_sync = {
            "type": "MODIFIED",
            "object": {"metadata": {"labels": dict(desired)}},
        }
        assert not NodeLabelController._event_needs_reconcile(in_sync, desired)
        drifted = {
            "type": "MODIFIED",
            "object": {"metadata": {"labels": {}}},
        }
        assert NodeLabelController._event_needs_reconcile(drifted, desired)
        deleted = {"type": "DELETED", "object": {}}
        assert not NodeLabelController._event_needs_reconcile(deleted, desired)


class FakeApiServer:
    """Serves one Node object; records PATCH bodies and applies merge-patch
    label semantics."""

    def __init__(self, node_name="test-node", labels=None):
        self.node = {
            "metadata": {"name": node_name, "labels": dict(labels or {})}
        }
        self.patches = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(outer.node)

            def do_PATCH(self):
                length = int(self.headers["Content-Length"])
                patch = json.loads(self.rfile.read(length))
                outer.patches.append(patch)
                labels = outer.node["metadata"]["labels"]
                for k, v in patch["metadata"]["labels"].items():
                    if v is None:
                        labels.pop(k, None)
                    else:
                        labels[k] = v
                self._send(outer.node)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self):
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def stop(self):
        self._server.shutdown()


@pytest.fixture
def fake_api():
    srv = FakeApiServer(
        labels={
            f"{constants.LABEL_PREFIX}.stale": "gone",
            "kubernetes.io/hostname": "test-node",
        }
    )
    yield srv
    srv.stop()


class TestController:
    def test_reconcile_applies_and_cleans(self, testdata, fake_api):
        compute = lambda: generate_labels(ctx_for(testdata, "v5e-8"))
        c = NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute
        )
        delta = c.reconcile()
        assert delta[f"{constants.LABEL_PREFIX}.stale"] is None
        assert delta[f"{constants.LABEL_PREFIX}.topology"] == "2x4"
        applied = fake_api.node["metadata"]["labels"]
        assert f"{constants.LABEL_PREFIX}.stale" not in applied
        assert applied[f"{constants.LABEL_PREFIX}.topology"] == "2x4"
        assert applied["kubernetes.io/hostname"] == "test-node"
        # second pass: in sync, no PATCH issued
        n = len(fake_api.patches)
        assert c.reconcile() == {}
        assert len(fake_api.patches) == n

    def test_reconcile_recomputes(self, testdata, fake_api):
        """Labels must track live state (the reference computes once at
        startup — SURVEY §7 'What NOT to copy')."""
        state = {"fixture": "v5e-8"}
        compute = lambda: generate_labels(ctx_for(testdata, state["fixture"]))
        c = NodeLabelController(
            NodeClient(base_url=fake_api.url), "test-node", compute
        )
        c.reconcile()
        assert (
            fake_api.node["metadata"]["labels"][
                f"{constants.LABEL_PREFIX}.chips-per-host"
            ]
            == "8"
        )
        state["fixture"] = "v5e-4-nometa"
        c.reconcile()
        labels = fake_api.node["metadata"]["labels"]
        assert labels[f"{constants.LABEL_PREFIX}.chips-per-host"] == "4"
        # accelerator-type came from v5e-8 metadata only; must be cleaned up
        assert f"{constants.LABEL_PREFIX}.accelerator-type" not in labels


class TestCli:
    def test_oneshot(self, testdata, fake_api, monkeypatch):
        from tpu_k8s_device_plugin.cmd import node_labeller

        root = os.path.join(testdata, "v5e-8")
        rc = node_labeller.main([
            "--oneshot",
            "--node-name", "test-node",
            "--kube-api", fake_api.url,
            "--sysfs-root", os.path.join(root, "sys"),
            "--dev-root", os.path.join(root, "dev"),
            "--tpu-env", os.path.join(root, "run", "tpu", "tpu-env"),
        ])
        assert rc == 0
        labels = fake_api.node["metadata"]["labels"]
        assert labels[f"{constants.LABEL_PREFIX}.accelerator-type"] == "v5litepod-8"

    def test_requires_node_name(self, monkeypatch):
        from tpu_k8s_device_plugin.cmd import node_labeller

        monkeypatch.delenv("DS_NODE_NAME", raising=False)
        assert node_labeller.main(["--oneshot"]) == 2
