"""Shared test configuration.

JAX-importing tests run on a virtual 8-device CPU mesh so multi-chip sharding
is exercised without TPU hardware (mirrors how the reference tests multi-GPU
hosts purely from sysfs fixtures, SURVEY.md §4).  The env must be set before
the first ``import jax`` anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # tests are COMPILE-bound on this box (dozens of distinct mesh
    # compiles, one CPU core); backend opt level 0 halves compile time
    # and the tests only check correctness, with both sides of every
    # oracle comparison compiled the same way.  bench.py and the
    # driver's dryrun run outside conftest and keep full optimization.
    _flags = _flags + " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = _flags

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The AOT-cache loader logs a scary-but-benign machine-feature banner per
# cache hit (the compile target records XLA tuning pseudo-features like
# prefer-no-scatter that the host-feature probe doesn't report); silence
# C++ log spam below FATAL for test runs
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# The host image's sitecustomize pins JAX_PLATFORMS to the real-TPU tunnel
# AFTER our env assignment above; jax.config beats env, so pin it here too,
# before any test module initializes a backend.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except (AttributeError, KeyError):  # pragma: no cover
        pass  # older jax: XLA_FLAGS above still sizes the device pool
    # persistent compilation cache (VERDICT r3 #7): the suite's floor is
    # ~30 serial mesh compiles on this box's ONE core, so cache compiled
    # executables across runs — first run pays full price, repeat runs
    # (the common case: the driver re-running the suite per round) load
    # AOT results instead of recompiling.  Identical coverage, no test
    # shrinkage.  TPU_DP_NO_COMPILE_CACHE=1 opts out (e.g. to measure a
    # cold run).
    if not os.environ.get("TPU_DP_NO_COMPILE_CACHE"):
        _cache_dir = os.environ.get(
            "TPU_DP_COMPILE_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"),
        )
        try:
            jax.config.update("jax_compilation_cache_dir", _cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
            # CPU executables only persist with the XLA-caches knob on
            jax.config.update(
                "jax_persistent_cache_enable_xla_caches", "all")
        except (AttributeError, KeyError, ValueError):  # pragma: no cover
            pass  # older jax: cache unsupported, run cold
except ImportError:  # pragma: no cover
    pass

import pytest  # noqa: E402

# Race-detector analog (SURVEY.md §5: the reference never runs `go test
# -race`; CI should).  Python has no data-race sanitizer, so the CI
# race-stress job approximates one: TPU_DP_RACE_STRESS=1 shrinks the
# interpreter's thread switch interval ~1000x (from 5ms to 5us), forcing
# preemption inside critical sections that a default-cadence run would
# almost never interleave, and arms faulthandler so a deadlock dumps all
# thread stacks instead of timing out silently.  The concurrency-heavy
# suites (plugin manager lifecycle, health exporter, inotify watcher) are
# then run repeatedly — see .github/workflows/test.yml `race-stress`.
if os.environ.get("TPU_DP_RACE_STRESS"):
    sys.setswitchinterval(5e-6)
    # hang diagnostics come from pytest's built-in faulthandler plugin
    # (capture-safe, per-test timer): pyproject sets
    # faulthandler_timeout=300 for every run — CI tightens it to 120 —
    # so a provoked deadlock dumps all thread stacks, locally too


@pytest.fixture
def testdata(request):
    """Absolute path to the repo-root testdata/ fixture directory."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "testdata"
    )
