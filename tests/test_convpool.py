"""Fused conv+pool ("flash-conv"): forward and gradients must match
the unfused ``conv → max_pool`` pipeline, tie-breaks included, and the
AlexNet pool="fused" wiring must reproduce the pool="xla" model."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from tpu_k8s_device_plugin.workloads.alexnet import (
    AlexNet,
    loss_fn,
    space_to_depth,
)
from tpu_k8s_device_plugin.workloads.convpool import conv_pool


def _ref(x, k):
    y = lax.conv_general_dilated(
        x, k, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return nn.max_pool(y, (3, 3), (2, 2))


@pytest.mark.parametrize("window,shape,feat", [
    (3, (4, 8, 8, 6), 8),    # even spatial, oh=3 -> pool_rows 3
    (5, (2, 9, 9, 4), 8),    # odd spatial + the 5x5 window
    (3, (2, 7, 7, 4), 6),    # oh=3 with odd input
])
def test_matches_unfused_fwd_and_grad(window, shape, feat):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    k = jax.random.normal(
        jax.random.PRNGKey(1), (window, window, shape[-1], feat),
        jnp.float32) * 0.2
    np.testing.assert_allclose(
        np.asarray(_ref(x, k)), np.asarray(conv_pool(x, k)),
        rtol=1e-5, atol=1e-5)
    gw = jax.grad(lambda x_, k_: (_ref(x_, k_) ** 2).sum(),
                  argnums=(0, 1))(x, k)
    gg = jax.grad(lambda x_, k_: (conv_pool(x_, k_) ** 2).sum(),
                  argnums=(0, 1))(x, k)
    for a, b in zip(gw, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_tie_break_matches_select_and_scatter():
    # constant input patches force exact ties in every pool window; the
    # gradient then depends entirely on the argmax tie-break, which
    # must match XLA's first-offset-in-row-major rule
    x = jnp.ones((2, 8, 8, 4), jnp.float32)
    k = jnp.ones((3, 3, 4, 6), jnp.float32) * 0.1
    gw = jax.grad(lambda x_: _ref(x_, k).sum())(x)
    gg = jax.grad(lambda x_: conv_pool(x_, k).sum())(x)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gg),
                               rtol=1e-5, atol=1e-5)


def test_bf16_path():
    x = jax.random.normal(
        jax.random.PRNGKey(2), (2, 8, 8, 4)).astype(jnp.bfloat16)
    k = (jax.random.normal(jax.random.PRNGKey(3), (3, 3, 4, 8)) * 0.2
         ).astype(jnp.bfloat16)
    want = _ref(x, k).astype(jnp.float32)
    got = conv_pool(x, k).astype(jnp.float32)
    # bf16 conv accumulation order differs between XLA's conv and the
    # tap-packed matmul; both accumulate in f32 so the pooled outputs
    # agree to bf16 resolution
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-2, atol=2e-2)


def test_bad_kernel_shapes_rejected():
    x = jnp.zeros((2, 8, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="odd-square"):
        conv_pool(x, jnp.zeros((2, 2, 4, 8), jnp.float32))
    with pytest.raises(ValueError, match="odd-square"):
        conv_pool(x, jnp.zeros((3, 3, 5, 8), jnp.float32))


def _remap_params(xla_params):
    """pool='fused' swaps stages 1/2/5 to FusedConvPool modules: map
    the xla-model tree onto the fused-model tree (same tensors)."""
    p = xla_params
    return {
        "FusedConvPool_0": p["Conv_0"],
        "FusedConvPool_1": p["Conv_1"],
        "Conv_0": p["Conv_2"],
        "Conv_1": p["Conv_3"],
        "FusedConvPool_2": p["Conv_4"],
        "Dense_0": p["Dense_0"],
        "Dense_1": p["Dense_1"],
        "Dense_2": p["Dense_2"],
    }


def test_alexnet_fused_matches_xla():
    # full-model equivalence at a reduced image size (64 -> s2d 16x16:
    # stage spatial chain 16 -> 7 -> 3 -> 1, all three pools fused)
    rng = jax.random.PRNGKey(0)
    img = jax.random.normal(rng, (2, 64, 64, 3), jnp.float32)
    x = space_to_depth(img)
    labels = jnp.asarray([3, 7])
    ref_model = AlexNet(num_classes=10, s2d=True, pool="xla",
                        dtype=jnp.float32)
    params = ref_model.init(rng, x, train=False)["params"]
    fused_model = AlexNet(num_classes=10, s2d=True, pool="fused",
                          dtype=jnp.float32)
    fparams = _remap_params(params)
    want = ref_model.apply({"params": params}, x, train=False)
    got = fused_model.apply({"params": fparams}, x, train=False)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=1e-4, atol=1e-4)
    gw = jax.grad(lambda p: loss_fn(ref_model, p, x, labels))(params)
    gg = jax.grad(lambda p: loss_fn(fused_model, p, x, labels))(fparams)
    for ref_name, fused_name in (
            ("Conv_0", "FusedConvPool_0"),
            ("Conv_1", "FusedConvPool_1"),
            ("Conv_4", "FusedConvPool_2"),
            ("Dense_0", "Dense_0")):
        for leaf in ("kernel", "bias"):
            np.testing.assert_allclose(
                np.asarray(gw[ref_name][leaf]),
                np.asarray(gg[fused_name][leaf]),
                rtol=2e-3, atol=2e-3,
                err_msg=f"{ref_name}->{fused_name}.{leaf}")


def test_alexnet_fused_requires_s2d():
    model = AlexNet(num_classes=10, s2d=False, pool="fused",
                    dtype=jnp.float32)
    with pytest.raises(ValueError, match="s2d"):
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 64, 64, 3), jnp.float32), train=False)
