"""Device-plugin CLI tests: flag validation + the impl autodetect chain.

The reference's fallback chain (container → vf → pf,
/root/reference/cmd/k8s-device-plugin/main.go:85-115) was untested there
and here until now (VERDICT r1 #7: a transposed builder dict would ship).
"""

import os

import pytest

from tpu_k8s_device_plugin.cmd.device_plugin import (
    build_parser,
    main,
    select_device_impl,
)
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
from tpu_k8s_device_plugin.tpu.device_impl_vfio import TpuPfImpl, TpuVfImpl


def args_for(testdata, name, *extra):
    root = os.path.join(testdata, name)
    return build_parser().parse_args([
        "--sysfs-root", os.path.join(root, "sys"),
        "--dev-root", os.path.join(root, "dev"),
        "--tpu-env", os.path.join(root, "run", "tpu", "tpu-env"),
        *extra,
    ])


class TestAutodetectChain:
    def test_accel_class_host_selects_container(self, testdata):
        impl, driver_type = select_device_impl(args_for(testdata, "v5e-8"))
        assert isinstance(impl, TpuContainerImpl)
        assert driver_type == "container"
        assert impl.get_resource_names() == ["tpu"]

    def test_vfio_pf_host_falls_through_to_pf(self, testdata):
        """No accel class, chips bound to vfio-pci: container and vf both
        fail, the chain must land on pf-passthrough."""
        impl, driver_type = select_device_impl(args_for(testdata, "vfio-pf"))
        assert isinstance(impl, TpuPfImpl)
        assert driver_type == "pf-passthrough"
        # single naming keeps the plain resource; mixed exposes tpu_pf
        assert impl.get_resource_names() == ["tpu"]
        mixed, _ = select_device_impl(args_for(
            testdata, "vfio-pf", "--resource_naming_strategy", "mixed"
        ))
        assert mixed.get_resource_names() == ["tpu_pf"]

    def test_sriov_host_falls_through_to_vf(self, testdata):
        """tpu-vf bound PFs with virtfns: vf-passthrough wins before pf."""
        impl, driver_type = select_device_impl(args_for(testdata, "vfio-vf"))
        assert isinstance(impl, TpuVfImpl)
        assert driver_type == "vf-passthrough"
        assert impl.get_resource_names() == ["tpu"]

    def test_no_tpus_anywhere_exits(self, tmp_path):
        empty = tmp_path / "empty"
        (empty / "sys").mkdir(parents=True)
        args = build_parser().parse_args([
            "--sysfs-root", str(empty / "sys"),
            "--dev-root", str(empty / "dev"),
            "--tpu-env", str(empty / "tpu-env"),
        ])
        with pytest.raises(SystemExit):
            select_device_impl(args)

    def test_explicit_driver_type_is_not_a_chain(self, testdata):
        """An explicit --driver_type must fail loudly when unusable, not
        silently fall through to another mode."""
        args = args_for(testdata, "vfio-pf", "--driver_type", "container")
        with pytest.raises(RuntimeError):
            select_device_impl(args)

    def test_explicit_pf_on_pf_host(self, testdata):
        args = args_for(testdata, "vfio-pf", "--driver_type",
                        "pf-passthrough")
        impl, driver_type = select_device_impl(args)
        assert isinstance(impl, TpuPfImpl)
        assert driver_type == "pf-passthrough"


class TestFlagValidation:
    def test_negative_pulse_rejected(self, testdata):
        root = os.path.join(testdata, "v5e-8")
        rc = main([
            "--pulse", "-1",
            "--sysfs-root", os.path.join(root, "sys"),
            "--dev-root", os.path.join(root, "dev"),
            "--tpu-env", os.path.join(root, "run", "tpu", "tpu-env"),
        ])
        assert rc == 2

    def test_unknown_driver_type_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["--driver_type", "gpu"])

    def test_unknown_strategy_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["--resource_naming_strategy", "both"])


class TestSliceFlags:
    """--slice-rendezvous / --slice-workers: validation, env overrides,
    and coordinator self-election (docs §"Multi-host slices")."""

    def _impl(self, testdata):
        impl, _ = select_device_impl(args_for(testdata, "v5e-16-host0"))
        return impl

    def test_default_off(self, testdata):
        args = args_for(testdata, "v5e-16-host0")
        assert args.slice_rendezvous == "" and args.slice_workers == 0

    def test_env_overrides(self, testdata, monkeypatch):
        from tpu_k8s_device_plugin.types import constants
        monkeypatch.setenv(constants.ENV_SLICE_RENDEZVOUS, "h0:8475")
        monkeypatch.setenv(constants.ENV_SLICE_WORKERS, "4")
        args = args_for(testdata, "v5e-16-host0")
        assert args.slice_rendezvous == "h0:8475"
        assert args.slice_workers == 4

    def test_bad_address_rejected(self, testdata):
        from tpu_k8s_device_plugin.cmd.device_plugin import setup_slice
        impl = self._impl(testdata)
        args = args_for(testdata, "v5e-16-host0",
                        "--slice-rendezvous", "no-port",
                        "--slice-workers", "2")
        with pytest.raises(SystemExit, match="HOST:PORT"):
            setup_slice(args, impl, "container")

    def test_workers_required(self, testdata):
        from tpu_k8s_device_plugin.cmd.device_plugin import setup_slice
        impl = self._impl(testdata)
        args = args_for(testdata, "v5e-16-host0",
                        "--slice-rendezvous", "h0:8475")
        with pytest.raises(SystemExit, match="slice-workers"):
            setup_slice(args, impl, "container")

    def test_passthrough_driver_rejected(self, testdata):
        from tpu_k8s_device_plugin.cmd.device_plugin import setup_slice
        impl = self._impl(testdata)
        args = args_for(testdata, "v5e-16-host0",
                        "--slice-rendezvous", "h0:8475",
                        "--slice-workers", "2")
        with pytest.raises(SystemExit, match="container driver"):
            setup_slice(args, impl, "pf-passthrough")

    def test_self_election_and_wiring(self, testdata, tmp_path, monkeypatch):
        """ONLY the plugin whose hostname exactly matches the rendezvous
        HOST serves the coordinator (identical flags on every member, one
        self-elects); every plugin gets a client attached to its impl
        with the host's metadata coordinate."""
        import socket as socket_mod

        from tpu_k8s_device_plugin.cmd.device_plugin import setup_slice

        with socket_mod.socket() as s:   # free ephemeral port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        impl = self._impl(testdata)
        args = args_for(
            testdata, "v5e-16-host0",
            "--slice-rendezvous", f"tpu-host-0:{port}",
            "--slice-workers", "2",
            "--slice-state-file", str(tmp_path / "membership.json"),
        )
        monkeypatch.setattr(socket_mod, "gethostname", lambda: "tpu-host-0")
        coordinator, client = setup_slice(args, impl, "container")
        try:
            assert coordinator is not None      # exact hostname match
            assert impl._slice is client
            assert client._coords == (0,)       # fixture WORKER_ID: '0'
            assert client._chip_count == 8
        finally:
            client.stop()
            coordinator.stop()

        # a DIFFERENT hostname must NOT self-elect a second coordinator
        monkeypatch.setattr(socket_mod, "gethostname", lambda: "tpu-host-1")
        impl2 = self._impl(testdata)
        coordinator2, client2 = setup_slice(args, impl2, "container")
        try:
            assert coordinator2 is None
            assert impl2._slice is client2
        finally:
            client2.stop()
