"""Continuous sampling profiler: determinism and bounds (PR 19).

The contract under test, in the profiler's own words: bounds are
structural, not aspirational.  The ring holds at most ``window_s``
one-second buckets, the interned stack set never exceeds
``max_stacks`` (overflow folds into ``(other)``), and 1000 extra ticks
change NEITHER — memory is flat no matter how long the process runs.
Plus the operational half: the folded output parses, phase tags track
the scheduler's ``begin_phase`` stream, a jax.profiler capture
suspends sampling instead of double-accounting it, and measured
overhead at the default 19 hz stays under the 3% bound the ISSUE
advertises.

Everything here drives :meth:`SamplingProfiler.sample_once` inline
with fake ``frames_fn``/``now_fn`` seams — no real threads, no real
sleeps — except the overhead test, which deliberately runs the real
sampling thread against a busy main thread.
"""

import re
import threading
import time

import pytest

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.obs import profiler as prof_mod
from tpu_k8s_device_plugin.workloads.scheduler import IterationScheduler

pytestmark = pytest.mark.filterwarnings("ignore")

T0 = 1_700_000_000.0


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class _FakeCode:
    def __init__(self, name):
        self.co_name = name


class _FakeFrame:
    """Just enough of a frame for fold_stack: f_code / f_globals /
    f_back."""

    def __init__(self, name, mod="fake", back=None):
        self.f_code = _FakeCode(name)
        self.f_globals = {"__name__": mod}
        self.f_back = back


def chain(*names, mod="fake"):
    """Build a frame chain root→leaf and return the LEAF frame (what
    sys._current_frames hands out)."""
    frame = None
    for name in names:
        frame = _FakeFrame(name, mod, frame)
    return frame


# -- fold_stack -------------------------------------------------------------

def test_fold_stack_renders_root_to_leaf():
    leaf = chain("main", "serve", "step")
    assert prof_mod.fold_stack(leaf) == "fake.main;fake.serve;fake.step"


def test_fold_stack_bounds_runaway_recursion():
    leaf = chain(*[f"f{i}" for i in range(500)])
    folded = prof_mod.fold_stack(leaf)
    frames = folded.split(";")
    assert frames[0] == "(deep)"
    assert len(frames) <= prof_mod.MAX_FRAMES + 1


# -- ring bounds ------------------------------------------------------------

def test_ring_memory_flat_over_1000_extra_ticks():
    """The ISSUE's determinism bound: after the ring is warm, +1000
    ticks grow neither the bucket ring nor the interned-stack set."""
    clock = FakeClock()
    shapes = [chain("main", f"work{i % 7}") for i in range(7)]
    i = [0]

    def frames():
        i[0] += 1
        return {1: shapes[i[0] % 7], 2: shapes[(i[0] + 3) % 7]}

    p = obs.SamplingProfiler(hz=19.0, window_s=60, max_stacks=32,
                             frames_fn=frames, now_fn=clock)
    for _ in range(100):  # warm the ring past its window
        clock.advance(1.0)
        p.sample_once()
    buckets_before = len(p._buckets)
    stacks_before = p.stack_count()
    assert buckets_before == 60  # maxlen, structurally
    for _ in range(1000):
        clock.advance(1.0)
        p.sample_once()
    assert len(p._buckets) == buckets_before
    assert p.stack_count() == stacks_before


def test_overflow_stacks_fold_into_other():
    clock = FakeClock()
    p = obs.SamplingProfiler(hz=19.0, window_s=60, max_stacks=3,
                             frames_fn=lambda: {}, now_fn=clock)
    # feed 10 distinct shapes through a mutable frames map
    for i in range(10):
        p._frames_fn = lambda i=i: {1: chain("main", f"shape{i}")}
        clock.advance(1.0)
        p.sample_once()
    assert p.stack_count() == 3
    folded = p.folded()
    assert prof_mod.OVERFLOW_STACK in folded
    # the 7 overflow samples all aggregated into the one (other) line
    other = [ln for ln in folded.splitlines()
             if prof_mod.OVERFLOW_STACK in ln]
    assert len(other) == 1 and other[0].endswith(" 7")


def test_window_slicing_drops_old_buckets():
    clock = FakeClock()
    p = obs.SamplingProfiler(hz=19.0, window_s=600,
                             frames_fn=lambda: {1: chain("m", "old")},
                             now_fn=clock)
    p.sample_once()
    clock.advance(300.0)
    p._frames_fn = lambda: {1: chain("m", "new")}
    p.sample_once()
    recent = p.folded(seconds=60)
    assert "fake.m;fake.new" in recent
    assert "fake.m;fake.old" not in recent
    full = p.folded()
    assert "fake.m;fake.old" in full


# -- folded format ----------------------------------------------------------

FOLDED_LINE = re.compile(r"^phase:[\w()-]+(;[^ ;]+)* \d+$")


def test_folded_output_parses_and_tags_phase():
    clock = FakeClock()
    p = obs.SamplingProfiler(hz=19.0, window_s=60,
                             phase_fn=lambda: "dispatch",
                             frames_fn=lambda: {
                                 1: chain("main", "serve", "step")},
                             now_fn=clock)
    p.sample_once()
    folded = p.folded()
    assert folded.endswith("\n")
    for line in folded.splitlines():
        assert FOLDED_LINE.match(line), line
    assert "phase:dispatch;fake.main;fake.serve;fake.step 1" \
        in folded.splitlines()


def test_phase_tags_match_scheduler_begin_phase_stream():
    """Drive a real IterationScheduler.begin_phase sequence and assert
    every sample lands under the phase current at sample time."""
    sched = IterationScheduler.__new__(IterationScheduler)
    sched._phase_acc = {"dispatch": 0.0, "harvest": 0.0,
                        "stream": 0.0, "idle": 0.0}
    sched.phase = "idle"
    clock = FakeClock()
    p = obs.SamplingProfiler(hz=19.0, window_s=600,
                             phase_fn=lambda: sched.phase,
                             frames_fn=lambda: {1: chain("m", "f")},
                             now_fn=clock)
    stream = ["dispatch", "harvest", "stream", "idle", "dispatch",
              "harvest"]
    for phase in stream:
        sched.begin_phase(phase)
        clock.advance(1.0)
        p.sample_once()
    doc = p.as_json()
    by_phase = {s["phase"]: s["count"] for s in doc["stacks"]}
    assert by_phase == {"dispatch": 2, "harvest": 2, "stream": 1,
                       "idle": 1}
    with pytest.raises(ValueError):
        sched.begin_phase("nonsense")


def test_active_request_count_averages_per_stack():
    clock = FakeClock()
    active = [0]
    p = obs.SamplingProfiler(hz=19.0, window_s=60,
                             active_fn=lambda: active[0],
                             frames_fn=lambda: {1: chain("m", "f")},
                             now_fn=clock)
    for n in (2, 4, 6):
        active[0] = n
        clock.advance(1.0)
        p.sample_once()
    doc = p.as_json()
    assert doc["stacks"][0]["count"] == 3
    assert doc["stacks"][0]["mean_active"] == pytest.approx(4.0)


# -- suspend (jax.profiler composition) -------------------------------------

def test_suspend_parks_sampling_and_counts_ticks():
    """The jax capture contract: while suspended the sampler records
    NO stacks (no double-accounting of capture machinery) but still
    counts the passes, so the timeline shows the gap honestly."""
    reg = obs.Registry()
    clock = FakeClock()
    p = obs.SamplingProfiler(reg, hz=19.0, window_s=60,
                             frames_fn=lambda: {1: chain("m", "f")},
                             now_fn=clock)
    clock.advance(1.0)
    assert p.sample_once() == 1
    with p.suspend(reason="jax_profiler"):
        assert p.suspended
        with p.suspend():  # re-entrant: nested capture helpers
            clock.advance(1.0)
            assert p.sample_once() == 0
        clock.advance(1.0)
        assert p.sample_once() == 0
    assert not p.suspended
    clock.advance(1.0)
    assert p.sample_once() == 1
    doc = p.as_json()
    assert doc["ticks"] == 4
    assert doc["samples"] == 2
    assert doc["suspended_ticks"] == 2
    text = reg.render()
    assert "tpu_profiler_ticks_total 4" in text
    assert "tpu_profiler_suspended_ticks_total 2" in text


def test_engine_profile_capture_suspends_sampler():
    """workloads.server wraps the jax.profiler capture in
    profiler.suspend() — pin that composition at the source level so
    a refactor can't silently drop it."""
    import inspect

    from tpu_k8s_device_plugin.workloads import server as server_mod
    src = inspect.getsource(server_mod.EngineServer.profile)
    assert ".suspend(" in src


# -- metrics + handler ------------------------------------------------------

def test_profiler_meta_metrics_are_promlint_clean():
    from tools.promlint import lint

    reg = obs.Registry()
    p = obs.SamplingProfiler(reg, hz=19.0,
                             frames_fn=lambda: {1: chain("m", "f")})
    p.sample_once()
    for om in (False, True):
        problems = lint(reg.render(openmetrics=om), openmetrics=om)
        assert problems == [], problems


def test_handle_pprof_formats_and_validation():
    clock = FakeClock()
    p = obs.SamplingProfiler(hz=19.0, window_s=600,
                             phase_fn=lambda: "harvest",
                             frames_fn=lambda: {1: chain("m", "f")},
                             now_fn=clock)
    p.sample_once()
    ctype, body = p.handle_pprof({})
    assert ctype.startswith("text/plain")
    assert "phase:harvest;fake.m;fake.f 1" in body
    ctype, body = p.handle_pprof({"format": ["json"],
                                  "seconds": ["60"]})
    assert ctype == "application/json"
    import json
    doc = json.loads(body)
    assert doc["schema"] == obs.PROFILE_SCHEMA
    assert doc["seconds"] == 60.0
    for bad in ({"seconds": ["0"]}, {"seconds": ["601"]},
                {"format": ["flamegraph"]}):
        with pytest.raises(ValueError):
            p.handle_pprof(bad)


def test_constructor_validation():
    for kw in ({"hz": 0}, {"window_s": 0.5}, {"max_stacks": 0}):
        with pytest.raises(ValueError):
            obs.SamplingProfiler(**kw)


# -- overhead ---------------------------------------------------------------

def test_overhead_under_3_percent_at_default_hz():
    """The acceptance bound: the real sampling thread at the default
    19 hz, against a busy main thread plus a handful of parked worker
    threads, measures under 3% of wall time."""
    p = obs.SamplingProfiler(hz=prof_mod.DEFAULT_HZ)
    stop = threading.Event()
    workers = [threading.Thread(target=stop.wait, daemon=True)
               for _ in range(4)]
    for w in workers:
        w.start()
    p.start()
    try:
        deadline = time.perf_counter() + 1.0
        x = 0
        while time.perf_counter() < deadline:  # busy loop under test
            x += 1
    finally:
        p.stop()
        stop.set()
    doc = p.as_json()
    assert doc["samples"] > 0  # it actually profiled the busy loop
    assert p.overhead_ratio() < 0.03
    assert x > 0
