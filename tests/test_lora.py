"""Multi-LoRA serving: per-request adapters over one compiled step.

Oracles: a zero-B adapter is bit-exactly the base model; a trained
(random-B) adapter matches a base model whose weights were explicitly
merged (W + scale * A @ B); mixed-adapter batches match each request's
solo run bit-for-bit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.inference import (
    attach_lora,
    greedy_generate,
    init_cache,
    make_decoder,
    quantize_lm_params,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128)
DT = jnp.float32
N_ADAPT = 3
RANK = 4


@pytest.fixture(scope="module")
def setup():
    base = make_decoder(**CFG, max_len=64, dtype=DT)
    lora = make_decoder(**CFG, max_len=64, dtype=DT,
                        n_adapters=N_ADAPT, lora_rank=RANK)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    base_params = base.init(rng, tokens, pos)["params"]
    return base, lora, base_params


def _solo(model, params, prompt, n, **admit_kw):
    eng = ServingEngine(model, params, n_slots=1, max_new_tokens=n)
    s = eng.admit(prompt, **admit_kw)
    eng.run(n + 2)
    return eng.output(s)


def test_fresh_adapter_is_exact_noop(setup):
    base, lora, base_params = setup
    lp = attach_lora(base_params, lora, jax.random.PRNGKey(1))
    prompt = [5, 17, 3, 70]
    want, _ = greedy_generate(
        base, base_params, jnp.asarray(prompt, jnp.int32)[None, :], 6)
    got = _solo(lora, lp, prompt, 6, adapter=1)
    assert got == np.asarray(want)[0].tolist()


def _random_b(lp, rng):
    """Fill every lora_B with random values (a 'trained' adapter)."""
    out = jax.tree_util.tree_map(lambda x: x, lp)
    for bname, block in out.items():
        if not bname.startswith("block_"):
            continue
        for name in list(block):
            if name.endswith("_lora_B"):
                rng, k = jax.random.split(rng)
                block[name] = jax.random.normal(
                    k, block[name].shape, jnp.float32) * 0.05
    return out


def _merged(base_params, lp, adapter, scale=1.0):
    """Base tree with adapter folded in: W + scale * A_k @ B_k."""
    out = jax.tree_util.tree_map(lambda x: x, base_params)
    for bname, block in out.items():
        if not bname.startswith("block_"):
            continue
        for name in list(block):
            if isinstance(block[name], dict) and "kernel" in block[name]:
                a = lp[bname].get(f"{name}_lora_A")
                b = lp[bname].get(f"{name}_lora_B")
                if a is None:
                    continue
                delta = (a[adapter] @ b[adapter]) * scale
                block[name] = {
                    "kernel": (block[name]["kernel"].astype(jnp.float32)
                               + delta).astype(block[name]["kernel"].dtype)
                }
    return out


def test_trained_adapter_matches_merged_weights(setup):
    base, lora, base_params = setup
    lp = _random_b(attach_lora(base_params, lora, jax.random.PRNGKey(1)),
                   jax.random.PRNGKey(2))
    prompt = jnp.asarray([[5, 17, 3, 70, 2]], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(5, dtype=jnp.int32), (1, 5))
    for adapter in range(N_ADAPT):
        merged = _merged(base_params, lp, adapter)
        ref, _ = base.apply(
            {"params": merged, "cache": init_cache(base, 1)},
            prompt, pos, decode=False, mutable=["cache"])
        got, _ = lora.apply(
            {"params": lp, "cache": init_cache(lora, 1)},
            prompt, pos, decode=True,
            adapter_ids=jnp.asarray([adapter], jnp.int32),
            mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(got), rtol=2e-4, atol=2e-4)


def test_mixed_adapters_match_solo_runs(setup):
    base, lora, base_params = setup
    lp = _random_b(attach_lora(base_params, lora, jax.random.PRNGKey(1)),
                   jax.random.PRNGKey(2))
    prompts = {0: [5, 17, 3], 1: [9, 9, 8, 7], None: [2, 71]}
    eng = ServingEngine(lora, lp, n_slots=4, max_new_tokens=6)
    slots = {a: eng.admit(p, adapter=a) for a, p in prompts.items()}
    eng.run(8)
    for a, p in prompts.items():
        assert eng.output(slots[a]) == _solo(lora, lp, p, 6, adapter=a), a


def test_prefix_bound_to_adapter(setup):
    _, lora, base_params = setup
    lp = _random_b(attach_lora(base_params, lora, jax.random.PRNGKey(1)),
                   jax.random.PRNGKey(2))
    system = [7, 7, 12]
    eng = ServingEngine(lora, lp, n_slots=2, max_new_tokens=5)
    h = eng.register_prefix(system, adapter=0)
    with pytest.raises(ValueError, match="adapter"):
        eng.admit(system + [1], prefix=h, adapter=1)
    with pytest.raises(ValueError, match="adapter"):
        eng.admit(system + [1], prefix=h)  # base vs adapter-0 prefix
    s = eng.admit(system + [1], prefix=h, adapter=0)
    eng.run(7)
    assert eng.output(s) == _solo(lora, lp, system + [1], 5, adapter=0)


def test_adapter_validation(setup):
    base, lora, base_params = setup
    lp = attach_lora(base_params, lora, jax.random.PRNGKey(1))
    eng = ServingEngine(lora, lp, n_slots=1)
    with pytest.raises(ValueError, match="adapter"):
        eng.admit([1, 2], adapter=N_ADAPT)
    base_eng = ServingEngine(base, base_params, n_slots=1)
    with pytest.raises(ValueError, match="n_adapters"):
        base_eng.admit([1, 2], adapter=0)


def test_lora_composes_with_int8(setup):
    base, _, base_params = setup
    qlora = make_decoder(**CFG, max_len=64, dtype=DT, quantized=True,
                         n_adapters=N_ADAPT, lora_rank=RANK)
    qp = attach_lora(quantize_lm_params(base_params), qlora,
                     jax.random.PRNGKey(1))
    prompt = [5, 17, 3]
    got = _solo(qlora, qp, prompt, 4, adapter=2)
    # zero-B adapters over the int8 base == plain int8 decode
    qbase = make_decoder(**CFG, max_len=64, dtype=DT, quantized=True)
    want = _solo(qbase, quantize_lm_params(base_params), prompt, 4)
    assert got == want


def test_lora_composes_with_int4(setup):
    base, _, base_params = setup
    from tpu_k8s_device_plugin.workloads.inference import (
        quantize_lm_params_int4)

    q4lora = make_decoder(**CFG, max_len=64, dtype=DT, quantized="int4",
                          n_adapters=N_ADAPT, lora_rank=RANK)
    qp = attach_lora(quantize_lm_params_int4(base_params), q4lora,
                     jax.random.PRNGKey(1))
    # lora_B must carry the FULL output dim, not the packed width
    f = base_params["block_0"]["mlp_up"]["kernel"].shape[1]
    assert qp["block_0"]["mlp_up_lora_B"].shape == (N_ADAPT, RANK, f)
    got = _solo(q4lora, qp, [5, 17, 3], 4, adapter=1)
    # zero-B adapters over the int4 base == plain int4 decode
    q4base = make_decoder(**CFG, max_len=64, dtype=DT, quantized="int4")
    want = _solo(q4base, quantize_lm_params_int4(base_params),
                 [5, 17, 3], 4)
    assert got == want
