"""tpulint self-test: every rule must catch its seeded fixture
violation AND pass its clean twin, the pragma contract must hold, and
— the teeth — the repo itself must lint clean under --strict, which is
exactly what the CI ``code-lint`` job asserts.  Mirrors how promlint
is tested by test_metrics_lint.py; wired into the same race-stress
loop so the analysis stays deterministic under thread preemption.
"""

import ast
import importlib
import json
import os
import sys
import unittest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.tpulint import (  # noqa: E402
    RULES,
    Finding,
    lint_paths,
    render_json,
)
from tools.tpulint.cli import DEFAULT_TARGETS, main  # noqa: E402

FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")


def lint_fixture(*names, strict=False):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return lint_paths(paths, strict=strict, root=REPO_ROOT,
                      excludes=("__pycache__",))


def rules_of(findings):
    return sorted({f.rule for f in findings})


class RuleCatalogTest(unittest.TestCase):
    def test_all_eight_rules_registered(self):
        self.assertEqual(
            sorted(RULES),
            ["C1", "C2", "C3", "D1", "O1", "O2", "R1", "R2"])

    def test_every_rule_documents_itself(self):
        for rule in RULES.values():
            self.assertTrue(rule.doc, f"{rule.id} has no doc line")
            self.assertTrue(rule.name, f"{rule.id} has no name")


class FixtureCorpusTest(unittest.TestCase):
    """The promlint discipline: each rule demonstrably catches its
    seeded violation and stays quiet on the clean twin."""

    PAIRS = {
        "C1": ("c1_violation.py", "c1_clean.py"),
        "C2": ("c2_violation.py", "c2_clean.py"),
        "C3": ("c3_violation.py", "c3_clean.py"),
        "R1": ("r1_violation.py", "r1_clean.py"),
        "R2": ("r2_violation.py", "r2_clean.py"),
        "O1": ("o1_violation.py", "o1_clean.py"),
        "O2": ("o2_violation.py", "o2_clean.py"),
        "D1": ("d1_violation.py", "d1_clean.py"),
    }

    def test_violations_caught(self):
        for rule_id, (violation, _) in self.PAIRS.items():
            findings = lint_fixture(violation)
            self.assertIn(rule_id, rules_of(findings),
                          f"{violation} did not trip {rule_id}: "
                          f"{findings}")

    def test_violations_trip_only_their_rule(self):
        for rule_id, (violation, _) in self.PAIRS.items():
            findings = lint_fixture(violation)
            self.assertEqual(rules_of(findings), [rule_id],
                             f"{violation} tripped extra rules")

    def test_clean_twins_pass(self):
        for rule_id, (_, clean) in self.PAIRS.items():
            findings = lint_fixture(clean)
            self.assertEqual(findings, [],
                             f"{clean} should be {rule_id}-clean: "
                             f"{findings}")

    def test_c1_cycle_crosses_modules(self):
        """The inter-module half of C1: each file alone is acyclic,
        together they close the cycle through project-local calls."""
        self.assertEqual(rules_of(lint_fixture("c1_xmod_a.py")), [])
        self.assertEqual(rules_of(lint_fixture("c1_xmod_b.py")), [])
        both = lint_fixture("c1_xmod_a.py", "c1_xmod_b.py")
        self.assertEqual(rules_of(both), ["C1"])
        self.assertIn("cycle", both[0].message)

    def test_c2_reports_the_lock_held(self):
        findings = lint_fixture("c2_violation.py")
        self.assertTrue(
            any("Stall._lock" in f.message for f in findings),
            f"C2 messages should name the held lock: {findings}")

    def test_d1_requires_the_deterministic_marker(self):
        """The same nondeterministic source WITHOUT the marker (and
        outside the known suffixes) is not D1's business."""
        src_path = os.path.join(FIXTURES, "d1_violation.py")
        with open(src_path) as f:
            body = f.read().replace(
                "# tpulint: deterministic-path\n", "")
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            unmarked = os.path.join(td, "unmarked.py")
            with open(unmarked, "w") as f:
                f.write(body)
            findings = lint_paths([unmarked], root=td,
                                  excludes=("__pycache__",))
        self.assertEqual(findings, [])


class PragmaContractTest(unittest.TestCase):
    def test_justified_pragma_suppresses(self):
        self.assertEqual(lint_fixture("pragma_suppressed.py"), [])

    def test_missing_justification_is_p1_and_does_not_suppress(self):
        findings = lint_fixture("pragma_missing_justification.py")
        self.assertEqual(rules_of(findings), ["C2", "P1"],
                         f"unjustified pragma must leave the original "
                         f"finding standing: {findings}")

    def test_unused_pragma_flagged_only_under_strict(self):
        self.assertEqual(lint_fixture("pragma_unused.py"), [])
        strict = lint_fixture("pragma_unused.py", strict=True)
        self.assertEqual(rules_of(strict), ["P2"])

    def test_unknown_rule_in_pragma_is_p1(self):
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "bogus.py")
            with open(path, "w") as f:
                f.write("# tpulint: disable=Z9 -- no such rule\n"
                        "x = 1\n")
            findings = lint_paths([path], root=td,
                                  excludes=("__pycache__",))
        self.assertEqual(rules_of(findings), ["P1"])

    def test_docstring_pragma_examples_are_inert(self):
        """A pragma QUOTED in a docstring must not register: only real
        COMMENT tokens count (core.py documents its own grammar)."""
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "quoted.py")
            with open(path, "w") as f:
                f.write('"""Example: # tpulint: disable=C2 -- how"""\n'
                        "x = 1\n")
            findings = lint_paths([path], root=td, strict=True,
                                  excludes=("__pycache__",))
        self.assertEqual(findings, [])


class OutputTest(unittest.TestCase):
    def test_json_shape(self):
        findings = lint_fixture("r2_violation.py")
        doc = json.loads(render_json(findings))
        self.assertEqual(doc["count"], 1)
        self.assertEqual(doc["findings"][0]["rule"], "R2")
        self.assertIn("line", doc["findings"][0])
        self.assertIn("path", doc["findings"][0])

    def test_cli_exit_codes(self):
        """The CLI's default excludes drop lint_fixtures (deliberate
        violations must not fail repo runs), so drive it with temp
        copies instead."""
        import shutil
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            clean = os.path.join(td, "clean.py")
            violation = os.path.join(td, "violation.py")
            shutil.copy(os.path.join(FIXTURES, "c1_clean.py"), clean)
            shutil.copy(os.path.join(FIXTURES, "r2_violation.py"),
                        violation)
            self.assertEqual(main([clean]), 0)
            self.assertEqual(main(["--json", violation]), 1)

    def test_cli_excludes_fixture_corpus(self):
        self.assertEqual(
            main([os.path.join(FIXTURES, "r2_violation.py")]), 0)

    def test_findings_sorted_and_formatted(self):
        findings = lint_fixture("c2_violation.py")
        self.assertEqual([f.line for f in findings],
                         sorted(f.line for f in findings))
        line = findings[0].format()
        self.assertRegex(line, r"^tests/lint_fixtures/c2_violation"
                               r"\.py:\d+: C2 ")


class RepoGateTest(unittest.TestCase):
    """The acceptance criterion itself: the shipped package and tools
    lint clean under --strict — every surviving pragma justified, no
    unused pragmas.  This is the same invocation CI's code-lint runs."""

    def test_repo_is_strict_clean(self):
        targets = [os.path.join(REPO_ROOT, t) for t in DEFAULT_TARGETS]
        findings = lint_paths(targets, strict=True, root=REPO_ROOT)
        self.assertEqual(
            findings, [],
            "repo must lint clean under tpulint --strict:\n"
            + "\n".join(f.format() for f in findings))

    def test_every_repo_pragma_is_justified(self):
        """Redundant with strict-clean, but stated directly: grep every
        live pragma in the lint targets and demand the `--` text."""
        from tools.tpulint.core import (
            DEFAULT_EXCLUDES, FileContext, iter_python_files)
        targets = [os.path.join(REPO_ROOT, t) for t in DEFAULT_TARGETS]
        for path in iter_python_files(targets, DEFAULT_EXCLUDES):
            with open(path, encoding="utf-8") as f:
                ctx = FileContext(path, os.path.relpath(path, REPO_ROOT),
                                  f.read())
            for pragma in ctx.pragmas:
                self.assertTrue(
                    pragma.justification,
                    f"{ctx.relpath}:{pragma.line} pragma lacks "
                    "justification text")


class SweepRegressionTest(unittest.TestCase):
    """The genuine defect the repo sweep surfaced (R2): the slice
    coordinator swallowed RPC-metadata failures with a bare ``pass`` —
    a malformed-metadata flood would have been invisible forever.  The
    fixed path must still degrade to a fresh root trace AND account
    the swallow in tpu_suppressed_errors_total{site}."""

    def test_trace_metadata_failure_is_accounted(self):
        from tpu_k8s_device_plugin import obs, resilience
        from tpu_k8s_device_plugin.slice import server as slice_server

        reg = obs.Registry()
        metrics = resilience.ResilienceMetrics(reg)
        resilience.set_suppressed_metrics(metrics)
        try:
            class _BadContext:
                def invocation_metadata(self):
                    raise RuntimeError("metadata exploded")

            trace = slice_server._trace_from_context(_BadContext())
            # degrades, never raises: the RPC still gets a root trace
            self.assertEqual(len(trace.trace_id), 32)
            body = reg.render()
            self.assertIn('tpu_suppressed_errors_total'
                          '{site="slice.trace_metadata"} 1', body)
        finally:
            resilience.set_suppressed_metrics(None)


class MeasureR3HousekeepingTest(unittest.TestCase):
    """ROADMAP housekeeping rider: the queued on-chip A/B phases must
    keep parsing and importing so they can run the day the TPU tunnel
    returns."""

    def test_measure_r3_parses_and_imports(self):
        path = os.path.join(REPO_ROOT, "tools", "measure_r3.py")
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        phases = [n.name for n in ast.walk(tree)
                  if isinstance(n, ast.FunctionDef)
                  and n.name.startswith("phase_")]
        self.assertGreaterEqual(len(phases), 10,
                                f"queued phases vanished: {phases}")
        mod = importlib.import_module("tools.measure_r3")
        for name in phases:
            self.assertTrue(callable(getattr(mod, name)),
                            f"{name} not importable")


if __name__ == "__main__":
    unittest.main()
