"""Subprocess worker for the cross-process checkpoint tests.

Run as ``python tests/ckpt_worker.py <mode> <base_dir> <out_json>``:

  train-crash   run 2 train steps, save step_2, print "saved", then
                SIGKILL itself — a hard crash with no atexit/orbax
                cleanup, the way a preempted pod actually dies
  resume        restore the latest checkpoint into a FRESH process,
                run 3 more steps, write the losses to <out_json>

The training setup is bit-identical to test_checkpoint._setup (same
seeds, same config, same backend), so the parent test can compare the
resumed trajectory against an uninterrupted in-process run exactly.
"""

import functools
import json
import os
import sys

# same backend forcing as conftest.py: the host image's sitecustomize
# pins JAX_PLATFORMS to the TPU tunnel, and jax.config beats env —
# set both BEFORE any backend initialization
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except (AttributeError, KeyError):  # pragma: no cover
    pass

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from tpu_k8s_device_plugin.workloads import llama  # noqa: E402
from tpu_k8s_device_plugin.workloads.checkpoint import (  # noqa: E402
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from tpu_k8s_device_plugin.workloads.transformer import (  # noqa: E402
    lm_train_step,
    synthetic_lm_batch,
)

CFG = llama.TINY_LLAMA


def build():
    model = llama.train_model(CFG, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens, labels, positions = synthetic_lm_batch(rng, 4, 16, CFG.vocab)
    params = model.init(rng, tokens, positions)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    step = jax.jit(functools.partial(lm_train_step, model, tx))
    return step, params, opt_state, (tokens, labels, positions)


def main() -> None:
    mode, base, out = sys.argv[1], sys.argv[2], sys.argv[3]
    step, params, opt_state, batch = build()
    if mode == "train-crash":
        p, o = params, opt_state
        for _ in range(2):
            p, o, _ = step(p, o, *batch)
        save_checkpoint(base, 2, {"params": p, "opt_state": o})
        print("saved", flush=True)
        os.kill(os.getpid(), 9)  # no clean shutdown of any kind
    elif mode == "resume":
        template = {"params": params, "opt_state": opt_state}
        start = latest_step(base)
        restored = restore_checkpoint(base, template=template)
        p, o = restored["params"], restored["opt_state"]
        losses = []
        for _ in range(3):
            p, o, loss = step(p, o, *batch)
            losses.append(float(loss))
        with open(out, "w") as f:
            json.dump({"start_step": start, "losses": losses}, f)
    else:  # pragma: no cover
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
