"""Replay harness coverage, three layers:

1. Pure units (no sockets, no jax): shared-loadclient frame parsing,
   SLO judging (abandonment excluded from the denominator), the
   span-bucket attribution math, report assembly, and a promlint pass
   over the ``tpu_replay_*`` families in both exposition modes.
2. Live-wire integration against an in-process tiny engine (jax):
   THE determinism proof — the same seeded trace replayed twice
   against the same server yields identical per-request outcome
   sets — plus the abandonment loop closed end to end: the client
   reports ``abandoned``, the SERVER journals the matching
   ``tpu_serve_client_abandon`` event and counts it in /stats.
3. Report plumbing: ``--assert-goodput`` gate exit codes and the
   ``tools/obs_query.py --replay-report`` post-mortem rendering.
"""

import json
import threading
import time

import pytest

from tpu_k8s_device_plugin import obs
from tpu_k8s_device_plugin.workloads import loadclient, replay
from tpu_k8s_device_plugin.workloads.trafficgen import (
    TraceConfig,
    TraceRequest,
    generate,
    write_trace,
)
from tools.promlint import lint

# ---------------------------------------------------------------------------
# layer 1: pure units


def test_parse_frame_fast_path_counts_tokens():
    n, ev = loadclient.parse_frame(b'{"tokens":[1,2,3]}')
    assert (n, ev) == (3, None)  # fast path: no parsed event
    n, ev = loadclient.parse_frame(b'{"tokens":[7]}')
    assert (n, ev) == (1, None)
    # off the fast path (whitespace), a tokens list parses fully
    n, ev = loadclient.parse_frame(b'{"tokens": [4, 5]}')
    assert n == 2 and ev is not None


def test_parse_frame_terminal_and_error():
    # terminal frames count 0 streamed tokens — the full list rides
    # in the parsed event for done_tokens accounting
    n, ev = loadclient.parse_frame(b'{"done":true,"tokens":[1,2]}')
    assert n == 0 and ev is not None and ev.get("done") is True
    assert ev.get("tokens") == [1, 2]
    n, ev = loadclient.parse_frame(b'{"error":"boom","code":500}')
    assert n == 0 and ev is not None and ev.get("error") == "boom"
    n, ev = loadclient.parse_frame(b'{"token":42}')  # legacy frame
    assert n == 1 and ev is not None
    with pytest.raises(ValueError):
        loadclient.parse_frame(b"[1,2,3]")


def test_sse_data_extraction():
    assert loadclient.sse_data(b"data: {\"x\":1}") == b'{"x":1}'
    assert loadclient.sse_data(b"data:[DONE]") is None  # sentinel
    assert loadclient.sse_data(b": keepalive") is None
    assert loadclient.sse_data(b"") is None


def _req(slo_class="interactive", stream=True, rid="r0", t_ms=0.0,
         tenant="default"):
    return TraceRequest(
        rid=rid, t_ms=t_ms, tenant=tenant, slo_class=slo_class,
        priority=0 if stream else 1, prefix_id=0, tokens=[1, 2, 3],
        max_new_tokens=4,
        behavior=loadclient.ClientBehavior(stream=stream))


def _out(outcome=loadclient.OUTCOME_OK, ttft_s=0.01, total_s=0.05):
    return loadclient.StreamOutcome(
        status=200, outcome=outcome, total_s=total_s, ttft_s=ttft_s)


def test_judge_semantics():
    pol = obs.default_slo_policies()
    assert replay.judge(_req(), _out(), pol) is True
    # abandonment is the CLIENT's own doing: excluded, not a miss
    assert replay.judge(
        _req(), _out(outcome=loadclient.OUTCOME_ABANDONED), pol) \
        is None
    assert replay.judge(
        _req(), _out(outcome=loadclient.OUTCOME_SHED), pol) is False
    # a blown TTFT target misses even though the stream finished ok
    assert replay.judge(_req(), _out(ttft_s=10.0), pol) is False
    # unknown class falls back on request shape (stream=interactive)
    assert replay.judge(
        _req(slo_class="mystery"), _out(), pol) is True


def _ev(name, trace, parent, dur_s, **attrs):
    span = obs.new_trace()
    return {"name": name, "trace_id": trace, "span_id": span.span_id,
            "parent_id": parent, "t_wall": 0.0,
            "attrs": dict(attrs, duration_s=dur_s)}


def test_attribution_buckets_and_router_hop():
    tid = "t" * 32
    events = [
        _ev("tpu_serve_queue_wait", tid, None, 0.010),
        _ev("tpu_serve_admit", tid, None, 0.020),
        _ev("tpu_serve_window", tid, None, 0.015),
        _ev("tpu_serve_window", tid, None, 0.015),
        _ev("tpu_serve_stream_write", tid, None, 0.005),
        _ev("tpu_serve_request", tid, None, 0.070, outcome="ok"),
        _ev("tpu_router_proxy", tid, None, 0.090, outcome="ok"),
    ]
    attr = replay.attribute(events, client_total_s=0.100)
    assert attr["queue_wait_ms"] == pytest.approx(10.0)
    assert attr["prefill_ms"] == pytest.approx(20.0)
    assert attr["decode_ms"] == pytest.approx(30.0)  # windows summed
    assert attr["stream_write_ms"] == pytest.approx(5.0)
    # router hop = proxy span minus the server's own span
    assert attr["router_hop_ms"] == pytest.approx(20.0)
    # whatever the spans can't explain stays visible, never hidden
    assert attr["unattributed_ms"] == pytest.approx(
        100.0 - 10 - 20 - 30 - 5 - 20)
    assert set(attr) == set(replay.ATTRIBUTION_KEYS)


def test_attribution_without_router_span():
    tid = "u" * 32
    events = [_ev("tpu_serve_queue_wait", tid, None, 0.004)]
    attr = replay.attribute(events, client_total_s=0.010)
    assert attr["router_hop_ms"] == 0.0
    assert attr["unattributed_ms"] == pytest.approx(6.0)


def test_replay_metrics_promlint_clean_both_modes():
    reg = obs.Registry()
    m = replay.ReplayMetrics(reg, obs.default_slo_policies())
    res = replay.RequestResult(req=_req(), outcome=_out(),
                               lag_s=0.2, late=True, slo_met=True)
    m.observe(res)
    m.observe(replay.RequestResult(
        req=_req(slo_class="batch", stream=False),
        outcome=_out(outcome=loadclient.OUTCOME_ERROR, ttft_s=None),
        lag_s=0.0, late=False, slo_met=False))
    m.set_attainment({"interactive": 1.0, "batch": 0.0})
    for mode in (False, True):
        text = reg.render(openmetrics=mode)
        assert lint(text) == []
    samples = obs.parse_exposition(reg.render())
    by = {}
    for name, labels, value in samples:
        by.setdefault(name, []).append((labels, value))
    assert ("tpu_replay_requests_total" in by
            and "tpu_replay_late_dispatches_total" in by
            and "tpu_replay_slo_attainment_ratio" in by)
    got = {(l["class"], l["outcome"]): v
           for l, v in by["tpu_replay_requests_total"]}
    assert got[("interactive", "ok")] == 1.0
    assert got[("batch", "error")] == 1.0
    assert by["tpu_replay_late_dispatches_total"][0][1] == 1.0


def test_build_report_shape_and_missed_ranking():
    pol = obs.default_slo_policies()
    results = [
        replay.RequestResult(req=_req(rid="fast"), outcome=_out(),
                             lag_s=0.0, late=False, slo_met=True),
        replay.RequestResult(
            req=_req(rid="slowest"),
            outcome=_out(ttft_s=9.0, total_s=9.5),
            lag_s=0.0, late=False, slo_met=False),
        replay.RequestResult(
            req=_req(rid="slower"),
            outcome=_out(ttft_s=5.0, total_s=5.5),
            lag_s=0.0, late=False, slo_met=False),
        replay.RequestResult(
            req=_req(rid="gone"),
            outcome=_out(outcome=loadclient.OUTCOME_ABANDONED),
            lag_s=0.0, late=False, slo_met=None),
    ]
    rep = replay.build_report(
        results, pol, trace_header={"seed": 1}, target="x:1",
        time_scale=1.0, late_ms=100.0)
    assert rep["schema"] == replay.REPORT_SCHEMA
    cls = rep["classes"]["interactive"]
    assert cls["total"] == 4
    assert cls["eligible"] == 3      # abandoned excluded
    assert cls["met"] == 1
    assert cls["attainment"] == pytest.approx(1 / 3, abs=1e-3)
    missed = [r["rid"] for r in rep["slo_missed"]]
    assert missed == ["slowest", "slower"]  # worst first
    assert rep["abandoned"] == 1
    assert all(k in rep["slo_missed"][0]["attribution"]
               for k in replay.ATTRIBUTION_KEYS)


def test_report_per_tenant_attainment():
    pol = obs.default_slo_policies()
    results = [
        replay.RequestResult(req=_req(rid="p0", tenant="prio"),
                             outcome=_out(), lag_s=0.0, late=False,
                             slo_met=True),
        replay.RequestResult(req=_req(rid="p1", tenant="prio"),
                             outcome=_out(), lag_s=0.0, late=False,
                             slo_met=True),
        replay.RequestResult(
            req=_req(rid="b0", tenant="batchfarm"),
            outcome=_out(ttft_s=9.0, total_s=9.5),
            lag_s=0.0, late=False, slo_met=False),
        replay.RequestResult(
            req=_req(rid="b1", tenant="batchfarm"),
            outcome=_out(outcome=loadclient.OUTCOME_ABANDONED),
            lag_s=0.0, late=False, slo_met=None),
    ]
    rep = replay.build_report(
        results, pol, trace_header={"seed": 1}, target="x:1",
        time_scale=1.0, late_ms=100.0)
    t = rep["tenants"]
    assert set(t) == {"prio", "batchfarm"}
    assert t["prio"]["attainment"] == pytest.approx(1.0)
    assert t["prio"]["eligible"] == 2
    # abandonment excluded per-tenant exactly like per-class
    assert t["batchfarm"]["total"] == 2
    assert t["batchfarm"]["eligible"] == 1
    assert t["batchfarm"]["attainment"] == pytest.approx(0.0)
    # the gate's spec grammar reaches the tenant rows
    specs = replay._parse_goodput_specs(
        ["tenant:prio=0.7", "interactive=0.5"])
    assert specs == {"tenant:prio": 0.7, "interactive": 0.5}


def test_goodput_spec_parsing():
    assert replay._parse_goodput_specs(["interactive=0.9"]) \
        == {"interactive": 0.9}
    with pytest.raises(ValueError):
        replay._parse_goodput_specs(["nope"])
    with pytest.raises(ValueError):
        replay._parse_goodput_specs(["c=1.5"])


# ---------------------------------------------------------------------------
# layer 2: live-wire integration (jax, in-process tiny engine)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpu_k8s_device_plugin.workloads.inference import make_decoder  # noqa: E402
from tpu_k8s_device_plugin.workloads.server import EngineServer  # noqa: E402
from tpu_k8s_device_plugin.workloads.serving import ServingEngine  # noqa: E402

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)

# the trace the determinism proof replays: both classes, shared
# prefixes, fast virtual arrivals so the whole replay stays sub-second
TRACE_CFG = TraceConfig(
    n_requests=14, base_rate_rps=60.0, burst_rate_rps=200.0,
    p_enter_burst=0.2, p_exit_burst=0.2, prefix_chunk=8,
    n_prefixes=4, max_prefix_chunks=2, prompt_median=8.0,
    prompt_max=16, output_median=6.0, output_max=8, vocab=128,
    unary_frac=0.3, slow_reader_frac=0.0, abandon_frac=0.0)


@pytest.fixture(scope="module")
def replay_server():
    model = make_decoder(**CFG, max_len=96, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    eng = ServingEngine(model, params, n_slots=4)
    srv = EngineServer(eng, max_new_tokens=64, window=4)
    srv.start(host="127.0.0.1", port=0)
    # warm the compile caches so replayed latencies are steady-state
    loadclient.stream_request(
        "127.0.0.1", srv.port,
        {"tokens": [1, 2, 3], "max_new_tokens": 4}, timeout_s=120)
    yield srv
    srv.stop()


def _replay_once(srv, requests, policies):
    metrics = replay.ReplayMetrics(obs.Registry(), policies)
    return replay.replay_trace(
        requests, "127.0.0.1", srv.port, policies=policies,
        metrics=metrics, time_scale=1.0, late_ms=100.0,
        timeout_s=60.0)


def _outcome_map(results):
    return {r.req.rid: (r.outcome.status, r.outcome.outcome,
                        r.outcome.done_tokens) for r in results}


def test_deterministic_replay_same_trace_same_outcomes(replay_server):
    requests = generate(TRACE_CFG, 42)
    policies = obs.default_slo_policies()
    first = _outcome_map(_replay_once(replay_server, requests,
                                      policies))
    second = _outcome_map(_replay_once(replay_server, requests,
                                       policies))
    assert first == second
    assert set(first) == {r.rid for r in requests}
    assert all(st == 200 and oc == "ok"
               for st, oc, _ in first.values())
    # open loop honored: ignore_eos'd streams produce exactly the
    # trace's requested token counts, so the counts replay too
    by_rid = {r.rid: r.max_new_tokens for r in requests}
    assert all(first[rid][2] == by_rid[rid] for rid in first)


def test_abandonment_round_trip_client_and_server(replay_server):
    srv = replay_server
    stats0 = loadclient.fetch_json(srv.port, "/stats")
    journal0 = len(srv.recorder.events(
        name="tpu_serve_client_abandon"))
    # a stream long enough (64 tokens, windowed flushes) that a
    # 40 ms abandonment deadline fires mid-stream, reliably
    req = TraceRequest(
        rid="quitter", t_ms=0.0, tenant="default",
        slo_class="interactive", priority=0, prefix_id=0,
        tokens=[3, 5, 7, 9], max_new_tokens=64,
        behavior=loadclient.ClientBehavior(stream=True,
                                           abandon_after_ms=40.0))
    policies = obs.default_slo_policies()
    results = _replay_once(srv, [req], policies)
    assert results[0].outcome.outcome == loadclient.OUTCOME_ABANDONED
    assert results[0].slo_met is None  # not in the SLO denominator
    # the server's side of the story: the handler saw the disconnect,
    # journaled the abandon event, and counted it in /stats
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        stats = loadclient.fetch_json(srv.port, "/stats")
        events = srv.recorder.events(name="tpu_serve_client_abandon")
        if int(stats.get("client_abandons", 0)) \
                > int(stats0.get("client_abandons", 0)) \
                and len(events) > journal0:
            break
        time.sleep(0.1)
    assert int(stats.get("client_abandons", 0)) \
        > int(stats0.get("client_abandons", 0))
    assert len(events) > journal0
    assert "tpu_serve_client_abandons_total" in srv.registry.render()


def test_late_dispatches_counted_never_rescheduled(replay_server):
    requests = [_req(rid=f"l{i}", t_ms=float(i)) for i in range(3)]
    policies = obs.default_slo_policies()
    metrics = replay.ReplayMetrics(obs.Registry(), policies)
    results = replay.replay_trace(
        requests, "127.0.0.1", replay_server.port,
        policies=policies, metrics=metrics, time_scale=1.0,
        late_ms=0.0, timeout_s=60.0)  # every real dispatch lags >0ms
    assert all(r.late for r in results)
    assert len(results) == 3  # late ones still ran, exactly once
    samples = obs.parse_exposition(metrics.registry.render())
    late = [v for name, labels, v in samples
            if name == "tpu_replay_late_dispatches_total"]
    assert late == [3.0]


def test_replay_cli_report_gate_and_obs_query(replay_server, tmp_path,
                                              capsys):
    trace = tmp_path / "trace.jsonl"
    write_trace(str(trace), TRACE_CFG, 8, generate(TRACE_CFG, 8))
    report = tmp_path / "report.json"
    metrics_out = tmp_path / "metrics.prom"
    # an impossible TTFT target forces SLO misses so the report's
    # attribution + embedded spans paths are exercised
    rc = replay.main([
        "--trace", str(trace),
        "--target", f"127.0.0.1:{replay_server.port}",
        "--slo", "interactive=0.001", "--slo", "batch=0:0.001",
        "--report", str(report), "--metrics-out", str(metrics_out),
        "--top-missed", "2", "--timeout-s", "60",
        "--assert-goodput", "interactive=0.99"])
    assert rc == 1  # the gate trips: nothing meets a 1ms TTFT
    captured = capsys.readouterr()
    assert "GOODPUT GATE FAIL" in captured.err
    rep = json.loads(report.read_text())
    assert rep["schema"] == replay.REPORT_SCHEMA
    assert rep["classes"]["interactive"]["attainment"] == 0.0
    missed = rep["slo_missed"]
    assert missed and all("attribution" in r for r in missed)
    # the slowest rows embed raw spans for offline stitching
    assert any(r.get("events") for r in missed[:2])
    assert lint(metrics_out.read_text()) == []

    from tools import obs_query
    rc = obs_query.main(["--replay-report", str(report), "--top", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "class interactive: attainment" in out
    assert "where it went:" in out
    assert "tpu_serve_request" in out  # the re-stitched span tree

    # and the gate passes (rc 0) under the generous default policies
    rc = replay.main([
        "--trace", str(trace),
        "--target", f"127.0.0.1:{replay_server.port}",
        "--report", str(report), "--timeout-s", "60",
        "--assert-goodput", "interactive=0.9",
        "--assert-goodput", "batch=0.9"])
    assert rc == 0
    assert "goodput gate ok" in capsys.readouterr().out


def test_obs_query_rejects_foreign_report(tmp_path, capsys):
    from tools import obs_query
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": "something/else"}))
    assert obs_query.main(["--replay-report", str(bad)]) == 2
