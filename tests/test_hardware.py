"""Hardware-gated tier: cross-check the discovery/health sysfs ABI
against a LIVE host tree when one exists (VERDICT r4 #3 — the analog of
the reference's ``hasAMDGPU`` guard,
/root/reference/internal/pkg/amdgpu/amdgpu_test.go:30-37, which
cross-checks its parsers against the machine under the tests).

Everything here skips cleanly on accel-less boxes (CI, dev laptops);
on a TPU VM it pins the fixture ABI (testdata/README.md) to reality:
the accel class enumerates, PCI links resolve, the metadata file (when
present) parses, and the granular-health attrs' presence/absence is
consistent with what the exporter reports.
"""

import os

import pytest

from tpu_k8s_device_plugin.tpu import discovery
from tpu_k8s_device_plugin.types import constants

_ACCEL = "/sys/class/accel"


def _has_tpu() -> bool:
    try:
        return any(e.startswith("accel") for e in os.listdir(_ACCEL))
    except OSError:
        return False


pytestmark = pytest.mark.skipif(
    not _has_tpu(), reason="no /sys/class/accel entries on this host")


def test_live_accel_class_enumerates():
    nodes = discovery.list_accel_nodes("/sys")
    assert nodes, "accel class present but enumerated empty"
    for idx, pci in nodes:
        assert idx >= 0
        # the device symlink must resolve into the PCI tree with a
        # parseable DBDF — the id every downstream map keys on
        assert os.path.isdir(f"/sys/bus/pci/devices/{pci}"), pci


def test_live_discovery_matches_tree():
    chips, topo = discovery.get_tpu_chips(
        "/sys", "/dev", constants.TPU_ENV_FILE)
    nodes = dict(discovery.list_accel_nodes("/sys"))
    accel_chips = {c.accel_index: c for c in chips.values()
                   if c.accel_index >= 0}
    # every accel node became a chip, and every chip's vendor is Google
    assert set(accel_chips) == set(nodes)
    for chip in accel_chips.values():
        vendor = open(
            f"/sys/bus/pci/devices/{chip.pci_address}/vendor"
        ).read().strip()
        assert vendor == constants.GOOGLE_VENDOR_ID, chip.pci_address
        assert os.path.exists(chip.dev_path), chip.dev_path
    # topology, when the metadata file exists, must carry a coordinate
    # per local chip (the allocator's whole basis)
    if topo is not None:
        for chip in accel_chips.values():
            assert chip.coords is not None, chip.id


def test_live_granular_health_attrs_consistent():
    """Whatever the real driver exposes, the exporter's availability
    signal must agree with the tree: if no chip has chip_state or
    uncorrectable_errors, granular_health_available is False (and the
    scrape says so); if any does, the probe consumes it without
    error."""
    from tpu_k8s_device_plugin.health.metrics import render_metrics
    from tpu_k8s_device_plugin.health.server import (
        granular_health_available,
        probe_chip_states,
    )

    chips, _ = discovery.get_tpu_chips("/sys", "/dev", "/nonexistent")
    avail = granular_health_available("/sys", chips)
    states = probe_chip_states("/sys", "/dev", chips=chips)
    assert set(states) <= set(chips)
    body = render_metrics("/sys", "/dev")
    assert f"tpu_exporter_granular_health {1 if avail else 0}" in body


def test_live_tpu_env_parses_if_present():
    if not os.path.exists(constants.TPU_ENV_FILE):
        pytest.skip(f"{constants.TPU_ENV_FILE} absent on this host")
    from tpu_k8s_device_plugin.tpu.topology import (
        read_tpu_env,
        topology_from_env,
    )

    env = read_tpu_env(constants.TPU_ENV_FILE)
    assert env, "tpu-env exists but parsed empty"
    topo = topology_from_env(env)
    assert topo is not None and topo.accelerator_type
