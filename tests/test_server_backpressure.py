"""Admission backpressure e2e: the HTTP front door must degrade
GRACEFULLY under load — a fixed worker pool (thread count flat
whatever the burst), a bounded admission heap answering 429 +
Retry-After, and a bounded per-request event queue that disconnects a
client who stops draining instead of buffering its tokens forever —
while the engine keeps decoding for every admitted request."""

import http.client
import json
import queue
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.server import EngineServer
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=512, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    return model, params


def _post_full(port, payload, timeout=120):
    """POST /generate returning (status, headers, events)."""
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        events = [json.loads(line) for line in resp if line.strip()]
        return resp.status, dict(resp.getheaders()), events
    finally:
        conn.close()


def _serve_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("serve-http")]


def test_fixed_pool_sheds_burst_with_429(setup):
    """12 simultaneous clients against a 2-worker pool + 2-deep heap:
    every response is a clean 200 or 429 (never a hang, never an
    unbounded thread), the pool's thread count is identical before and
    after, and the 200s prove the engine kept decoding for admitted
    requests throughout the burst."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=4, window=2,
                       max_connections=2, max_queue=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        # warm the compile so burst timing exercises scheduling, not jit
        _post_full(srv.port, {"tokens": [1, 2], "stream": False})
        before = _serve_threads()
        assert len(before) == 3  # 1 accept thread + 2 pool workers
        results = [None] * 12
        lock = threading.Lock()

        def one(i):
            try:
                status, headers, _ = _post_full(
                    srv.port, {"tokens": [3 + i, 5], "stream": False})
            except OSError:
                status, headers = -1, {}
            with lock:
                results[i] = (status, headers)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        statuses = [r[0] for r in results]
        assert set(statuses) <= {200, 429}, statuses
        assert 200 in statuses and 429 in statuses, statuses
        # every 429 names its retry contract
        for status, headers in results:
            if status == 429:
                assert headers.get("Retry-After"), headers
        # thread count is FLAT: same accept thread + workers, no
        # thread-per-connection growth
        assert _serve_threads() == before
        st = srv.stats()
        assert st["http_workers"] == 2
        assert (st["connections_rejected"] + st["requests_throttled"]
                >= statuses.count(429))
    finally:
        srv.stop()


def test_queue_overflow_429_retry_after(setup):
    """max_queue=1 on a 1-slot engine: with the slot busy and one
    request pending, the next admission answers 429 + Retry-After;
    the pending request still completes once the slot frees."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=400, window=4,
                       max_queue=1)
    srv.start(host="127.0.0.1", port=0)
    try:
        results = {}

        def runner(name, budget):
            results[name] = _post_full(
                srv.port, {"tokens": [7, 8, 9],
                           "max_new_tokens": budget, "stream": False})

        a = threading.Thread(target=runner, args=("a", 400))
        a.start()
        deadline = time.monotonic() + 60
        while srv.stats()["running_copies"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        b = threading.Thread(target=runner, args=("b", 2))
        b.start()
        while srv.stats()["pending_requests"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        status, headers, events = _post_full(
            srv.port, {"tokens": [1, 2], "stream": False})
        assert status == 429
        assert headers.get("Retry-After")
        assert "error" in events[0]
        a.join(timeout=120)
        b.join(timeout=120)
        assert results["a"][0] == 200
        assert results["b"][0] == 200
        assert srv.stats()["requests_throttled"] == 1
    finally:
        srv.stop()


def test_slow_client_drop_policy(setup):
    """The documented slow-client policy at the unit level: a full
    bounded event queue cancels the request, drops the oldest
    undelivered event for a terminal 503, and counts the drop — the
    scheduler never blocks and never buffers past the bound."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(eng, max_new_tokens=4, max_events=8)
    req = srv._parse_request({"tokens": [1, 2]})
    for i in range(8):
        assert srv._push(req, {"seq": i})
    assert not srv._push(req, {"seq": 8})  # overflow: drop fires
    assert req.cancelled and req.dropped
    assert srv._requests_dropped == 1
    # a second overflow does not double-count or re-fire
    assert not srv._push(req, {"seq": 9})
    assert srv._requests_dropped == 1
    drained = []
    while True:
        try:
            drained.append(req.events.get_nowait())
        except queue.Empty:
            break
    # oldest event was dropped to make room for the terminal error
    assert drained[0] == {"seq": 1}
    assert drained[-1].get("code") == 503
    assert len(drained) == 8  # never past the bound


def test_stalled_reader_does_not_starve_other_clients(setup):
    """A streaming client that connects and never reads its body must
    not stall other traffic: admitted requests keep completing, and
    the stalled request's events stay bounded."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=300, window=4,
                       max_connections=4, max_events=16)
    srv.start(host="127.0.0.1", port=0)
    stalled = http.client.HTTPConnection("127.0.0.1", srv.port,
                                         timeout=120)
    try:
        stalled.request(
            "POST", "/generate",
            json.dumps({"tokens": [9, 9, 8], "max_new_tokens": 300}),
            {"Content-Type": "application/json"})
        # deliberately never call getresponse(): the peer stops
        # draining while the scheduler keeps producing windows
        for i in range(3):
            status, _, events = _post_full(
                srv.port, {"tokens": [i + 1, 2, 3],
                           "max_new_tokens": 4, "stream": False})
            assert status == 200
            assert len(events[-1]["tokens"]) == 4
        assert srv.stats()["requests_served"] >= 3
    finally:
        stalled.close()
        srv.stop()


def test_concurrent_scrape_under_load(setup):
    """PR 3 observability satellite: hammer /metrics while a burst of
    streaming requests (some shed with 429) is in flight.  Every
    scrape must succeed, parse as promlint-clean exposition, and the
    monotonic counters must never go backwards between scrapes."""
    import urllib.error
    import urllib.request

    from tools.promlint import lint
    from tpu_k8s_device_plugin import obs

    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=8, window=2,
                       max_connections=4, max_queue=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        # warm the compile so the load phase is scheduling, not jit
        _post_full(srv.port, {"tokens": [1, 2], "stream": False})

        stop = threading.Event()
        scrape_errors = []
        monotone = [
            "tpu_serve_request_seconds_count",
            "tpu_serving_requests_served_total",
            "tpu_serve_shed_total",
            "tpu_serve_ttft_seconds_count",
        ]

        def scraper():
            last = {}
            while not stop.is_set():
                try:
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{srv.port}/metrics",
                            timeout=30,
                        ) as resp:
                            body = resp.read().decode()
                    except urllib.error.HTTPError as e:
                        if e.code == 429:
                            # the bounded pool sheds scrapes too under
                            # the flood — admission control working as
                            # documented, not a metrics bug; retry
                            time.sleep(0.01)
                            continue
                        raise
                    errs = lint(body)
                    if errs:
                        scrape_errors.append(f"promlint: {errs[:3]}")
                        return
                    totals = {}
                    for n, _ls, v in obs.parse_exposition(body):
                        if n in monotone:
                            totals[n] = totals.get(n, 0.0) + v
                    for k, v in totals.items():
                        if v < last.get(k, 0.0):
                            scrape_errors.append(
                                f"{k} went backwards: "
                                f"{last[k]} -> {v}")
                            return
                    last.update(totals)
                except Exception as e:  # any scrape failure is a bug
                    if not stop.is_set():
                        scrape_errors.append(f"{type(e).__name__}: {e}")
                        return

        scrapers = [threading.Thread(target=scraper) for _ in range(3)]
        for t in scrapers:
            t.start()

        results = [None] * 10
        lock = threading.Lock()

        def one(i):
            try:
                status, _, _ = _post_full(
                    srv.port,
                    {"tokens": [3 + i, 5], "max_new_tokens": 8})
            except OSError:
                status = -1
            with lock:
                results[i] = status

        load = [threading.Thread(target=one, args=(i,))
                for i in range(10)]
        for t in load:
            t.start()
        for t in load:
            t.join(timeout=120)
        stop.set()
        for t in scrapers:
            t.join(timeout=30)
        assert not scrape_errors, scrape_errors
        assert all(s in (200, 429) for s in results), results
        assert any(s == 200 for s in results)
        # the final body reflects the traffic it raced
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30
        ) as resp:
            body = resp.read().decode()
        samples = obs.parse_exposition(body)
        served = [v for n, _ls, v in samples
                  if n == "tpu_serving_requests_served_total"]
        assert served and served[0] >= sum(s == 200 for s in results)
    finally:
        srv.stop()
