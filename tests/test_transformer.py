"""Transformer LM tests on the virtual 8-device mesh: single-device
training, the sharded data×seq×model step, and exact agreement between
ring-attention (both layouts) and local-attention forward passes."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.transformer import (
    TransformerLM,
    local_causal_attention,
    lm_loss,
    lm_train_step,
    make_lm_mesh,
    make_lm_train_step,
    synthetic_lm_batch,
)

TINY = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64)


def build(attn_fn=local_causal_attention, batch=2, seq_len=32):
    rng = jax.random.PRNGKey(1)
    model = TransformerLM(attn_fn=attn_fn, **TINY)
    tokens, labels, positions = synthetic_lm_batch(
        rng, batch, seq_len, TINY["vocab"]
    )
    params = model.init(rng, tokens, positions)["params"]
    return model, params, (tokens, labels, positions)


def test_forward_shapes_and_finite():
    model, params, (tokens, _, positions) = build()
    logits = model.apply({"params": params}, tokens, positions)
    assert logits.shape == (2, 32, TINY["vocab"])
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_single_device_training_reduces_loss():
    import optax

    model, params, batch = build()
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    step = jax.jit(functools.partial(lm_train_step, model, tx))
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, *batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_causality_is_position_driven():
    """Permuting tokens+positions together must not change per-token
    logits — the property that makes the zig-zag layout legal end-to-end."""
    model, params, (tokens, _, positions) = build(batch=1, seq_len=16)
    logits = model.apply({"params": params}, tokens, positions)
    perm = np.random.RandomState(0).permutation(16)
    logits_p = model.apply(
        {"params": params}, tokens[:, perm], positions[:, perm]
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, perm]), np.asarray(logits_p), atol=2e-2,
        rtol=2e-2,
    )


class TestShardedLM:
    @pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
    def test_sharded_loss_matches_local_oracle(self, layout):
        mesh = make_lm_mesh(jax.devices(), seq=2, model=2)
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, attn_layout=layout, **TINY
        )
        tokens, labels, positions = state["batch"]
        placed = place(tokens, labels, positions)
        # oracle: same params, local attention, natural order
        local_model = TransformerLM(attn_fn=local_causal_attention, **TINY)
        host_params = jax.device_get(state["params"])
        want = float(lm_loss(
            local_model, host_params, tokens, labels, positions
        ))
        params, opt_state, loss = step(
            state["params"], state["opt_state"], *placed
        )
        assert np.isclose(float(loss), want, rtol=2e-2), (float(loss), want)

    def test_sharded_training_reduces_loss_and_keeps_layout(self):
        mesh = make_lm_mesh(jax.devices(), seq=2, model=2)
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, **TINY
        )
        placed = place(*state["batch"])
        params, opt_state = state["params"], state["opt_state"]
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, *placed)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # tensor-parallel layout survives the update loop
        qkv = params["block_0"]["qkv"]["kernel"]
        assert tuple(qkv.sharding.spec) == (None, "model")
        assert (
            qkv.addressable_shards[0].data.shape[1]
            == qkv.shape[1] // mesh.shape["model"]
        )

    def test_pure_data_parallel_fallback(self):
        """seq_axis=None: plain DP+TP without sequence parallelism."""
        mesh = make_lm_mesh(jax.devices(), seq=1, model=2)
        step, state, place = make_lm_train_step(
            mesh, seq_len=32, batch=4, seq_axis=None, **TINY
        )
        placed = place(*state["batch"])
        _, _, loss = step(state["params"], state["opt_state"], *placed)
        assert np.isfinite(float(loss))
