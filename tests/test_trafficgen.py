"""Trace generator invariants: byte-identical determinism, schema
round-trip, and the reader's rejection of damaged traces.

The generator is the CI goodput gate's foundation: if two runs of the
same seed can differ by one byte, "replay the same trace twice" proves
nothing.  So the first tests compare WHOLE FILE BYTES, not summaries.
"""

import dataclasses
import json

import pytest

from tpu_k8s_device_plugin.workloads.trafficgen import (
    SCHEMA,
    TraceConfig,
    TraceError,
    TraceRequest,
    _prefix_block,
    dumps_trace,
    generate,
    load_trace,
    loads_trace,
    main,
    parse_session_revisit,
    parse_tenant_mix,
    summarize,
    write_trace,
)

# small but non-trivial: both classes, slow readers, abandoners
CFG = TraceConfig(n_requests=80, base_rate_rps=20.0,
                  burst_rate_rps=120.0, p_enter_burst=0.1,
                  p_exit_burst=0.2, prefix_chunk=8, n_prefixes=4,
                  max_prefix_chunks=2, prompt_median=10.0,
                  prompt_max=24, output_median=6.0, output_max=8,
                  vocab=128, unary_frac=0.3, slow_reader_frac=0.2,
                  abandon_frac=0.2)


# -- determinism -----------------------------------------------------------


def test_same_seed_is_byte_identical(tmp_path):
    a = dumps_trace(CFG, 7, generate(CFG, 7))
    b = dumps_trace(CFG, 7, generate(CFG, 7))
    assert a == b
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    write_trace(str(pa), CFG, 7, generate(CFG, 7))
    write_trace(str(pb), CFG, 7, generate(CFG, 7))
    assert pa.read_bytes() == pb.read_bytes()


def test_different_seed_differs():
    assert dumps_trace(CFG, 1, generate(CFG, 1)) \
        != dumps_trace(CFG, 2, generate(CFG, 2))


def test_timestamps_monotonic_and_virtual():
    reqs = generate(CFG, 3)
    ts = [r.t_ms for r in reqs]
    assert ts == sorted(ts)
    assert ts[0] > 0.0


# -- schema round-trip -----------------------------------------------------


def test_round_trip_preserves_every_record(tmp_path):
    reqs = generate(CFG, 11)
    path = tmp_path / "t.jsonl"
    write_trace(str(path), CFG, 11, reqs)
    header, loaded = load_trace(str(path))
    assert header["schema"] == SCHEMA
    assert header["seed"] == 11
    assert header["requests"] == len(reqs) == len(loaded)
    assert [r.to_record() for r in loaded] \
        == [r.to_record() for r in reqs]
    # behaviors survive the round trip typed, not as dicts
    assert all(type(r.behavior) is type(reqs[0].behavior)
               for r in loaded)


def test_reload_of_dumped_trace_redumps_identically():
    reqs = generate(CFG, 5)
    text = dumps_trace(CFG, 5, reqs)
    header, loaded = loads_trace(text)
    assert dumps_trace(CFG, 5, loaded) == text


# -- reader rejection ------------------------------------------------------


def _trace_lines(seed=9):
    return dumps_trace(CFG, seed, generate(CFG, seed)).splitlines()


def test_truncated_trace_rejected():
    lines = _trace_lines()
    with pytest.raises(TraceError, match="truncated or padded"):
        loads_trace("\n".join(lines[:-3]) + "\n")


def test_padded_trace_rejected():
    lines = _trace_lines()
    with pytest.raises(TraceError, match="truncated or padded"):
        loads_trace("\n".join(lines + [lines[-1]]) + "\n")


def test_unknown_schema_version_rejected():
    lines = _trace_lines()
    header = json.loads(lines[0])
    header["schema"] = "tpu-trace/v999"
    bad = "\n".join([json.dumps(header)] + lines[1:])
    with pytest.raises(TraceError, match="unsupported trace schema"):
        loads_trace(bad)


def test_malformed_record_line_rejected():
    lines = _trace_lines()
    lines[3] = lines[3][: len(lines[3]) // 2]  # chopped mid-JSON
    with pytest.raises(TraceError, match="malformed record"):
        loads_trace("\n".join(lines) + "\n")


def test_wrong_field_type_rejected():
    lines = _trace_lines()
    rec = json.loads(lines[2])
    rec["tokens"] = "not-a-list"
    lines[2] = json.dumps(rec)
    with pytest.raises(TraceError):
        loads_trace("\n".join(lines) + "\n")


def test_backwards_time_rejected():
    lines = _trace_lines()
    a, b = json.loads(lines[1]), json.loads(lines[2])
    a["t_ms"], b["t_ms"] = b["t_ms"], a["t_ms"]
    a["rid"], b["rid"] = b["rid"], a["rid"]
    lines[1], lines[2] = json.dumps(a), json.dumps(b)
    with pytest.raises(TraceError, match="goes backwards"):
        loads_trace("\n".join(lines) + "\n")


def test_empty_and_non_object_header_rejected():
    with pytest.raises(TraceError):
        loads_trace("")
    with pytest.raises(TraceError):
        loads_trace("[1,2,3]\n")


# -- shape invariants ------------------------------------------------------


def test_shared_prefixes_chunk_aligned_and_exact():
    reqs = generate(CFG, 21)
    blocks = {pid: _prefix_block(21, CFG, pid)
              for pid in range(CFG.n_prefixes)}
    for r in reqs:
        block = blocks[r.prefix_id]
        assert len(block) % CFG.prefix_chunk == 0
        # the request's prompt STARTS with its prefix block exactly —
        # what the APC cache and the router's affinity key hash over
        assert r.tokens[: len(block)] == block
        assert len(r.tokens) > len(block)  # always a unique suffix
        assert all(0 < t < CFG.vocab for t in r.tokens)
        assert CFG.output_min <= r.max_new_tokens <= CFG.output_max


def test_zipf_head_dominates():
    counts = {}
    for r in generate(CFG, 13):
        counts[r.prefix_id] = counts.get(r.prefix_id, 0) + 1
    assert counts.get(0, 0) == max(counts.values())


def test_mix_covers_both_classes_and_behaviors():
    reqs = generate(CFG, 17)
    s = summarize(reqs)
    assert set(s["classes"]) == {"interactive", "batch"}
    assert s["unary"] > 0 and s["slow_readers"] > 0 \
        and s["abandoners"] > 0
    # behavior coupling: unary requests are batch-class, never
    # slow-read or abandoned (those are streaming-client behaviors)
    for r in reqs:
        if not r.behavior.stream:
            assert r.slo_class == "batch" and r.priority == 1
            assert r.behavior.read_bytes_per_s == 0
            assert r.behavior.abandon_after_ms == 0.0
        else:
            assert r.slo_class == "interactive" and r.priority == 0


def test_weighted_tenants_skew_and_determinism():
    cfg = dataclasses.replace(CFG, tenants=("prio", "batchfarm"),
                              tenant_weights=(9.0, 1.0))
    reqs = generate(cfg, 23)
    counts = summarize(reqs)["tenants"]
    # 9:1 over 80 draws: the heavy tenant must dominate, the light one
    # must still appear (weights partition, they don't exclude)
    assert counts["prio"] > counts.get("batchfarm", 0) * 3
    assert counts.get("batchfarm", 0) > 0
    # weighted draws are part of the same determinism contract
    assert dumps_trace(cfg, 23, generate(cfg, 23)) \
        == dumps_trace(cfg, 23, reqs)


def test_unweighted_tenants_unchanged_by_weights_field():
    # tenant_weights=None must take the historical randrange arm:
    # a pre-existing trace config regenerates byte-identically
    base = dataclasses.replace(CFG, tenants=("a", "b", "c"))
    explicit = dataclasses.replace(CFG, tenants=("a", "b", "c"),
                                   tenant_weights=None)
    assert [r.tenant for r in generate(base, 7)] \
        == [r.tenant for r in generate(explicit, 7)]


def test_parse_tenant_mix():
    names, weights = parse_tenant_mix("prio:3,batchfarm:1")
    assert names == ("prio", "batchfarm")
    assert weights == (3.0, 1.0)
    # weightless mix keeps the unweighted (historical) draw arm
    names, weights = parse_tenant_mix("a,b")
    assert names == ("a", "b") and weights is None
    # partial weights: unannotated entries default to 1.0
    names, weights = parse_tenant_mix("a:2,b")
    assert weights == (2.0, 1.0)
    assert parse_tenant_mix(None) == (("default",), None)
    assert parse_tenant_mix("", ("x",)) == (("x",), None)
    with pytest.raises(ValueError):
        parse_tenant_mix("a:nope")
    with pytest.raises(ValueError):
        parse_tenant_mix(":3")


def test_session_revisit_deterministic_and_consistent():
    cfg = dataclasses.replace(CFG, session_revisit=(0.5, 1000.0))
    reqs = generate(cfg, 11)
    assert all(r.session for r in reqs)
    seen = set()
    for r in reqs:
        if r.cont:
            assert r.session in seen  # revisits target earlier sessions
        seen.add(r.session)
    s = summarize(reqs)
    assert s["revisits"] > 0
    assert s["sessions"] == len(seen)
    assert s["sessions"] + s["revisits"] == len(reqs)
    # revisit gaps advance the clock, never rewind it
    ts = [r.t_ms for r in reqs]
    assert ts == sorted(ts)
    # the session dimension is part of the determinism contract
    assert dumps_trace(cfg, 11, generate(cfg, 11)) \
        == dumps_trace(cfg, 11, reqs)


def test_unsessioned_trace_unchanged_by_revisit_field():
    # session_revisit=None must add ZERO rng draws and ZERO record
    # keys: a pre-existing trace config regenerates byte-identically
    explicit = dataclasses.replace(CFG, session_revisit=None)
    a = [json.dumps(r.to_record()) for r in generate(CFG, 7)]
    b = [json.dumps(r.to_record()) for r in generate(explicit, 7)]
    assert a == b
    assert all('"session"' not in line for line in a)


def test_session_fields_round_trip(tmp_path):
    cfg = dataclasses.replace(CFG, session_revisit=(0.4, 500.0))
    path = tmp_path / "sess.jsonl"
    write_trace(str(path), cfg, 9, generate(cfg, 9))
    _, back = load_trace(str(path))
    orig = generate(cfg, 9)
    assert [(r.session, r.cont) for r in back] \
        == [(r.session, r.cont) for r in orig]


def test_parse_session_revisit():
    assert parse_session_revisit(None) is None
    assert parse_session_revisit("") is None
    assert parse_session_revisit("0.3") == (0.3, 1000.0)
    assert parse_session_revisit("0.3:500") == (0.3, 500.0)
    assert parse_session_revisit("0:0") == (0.0, 0.0)
    with pytest.raises(ValueError):
        parse_session_revisit("1.5")
    with pytest.raises(ValueError):
        parse_session_revisit("0.3:-1")
    with pytest.raises(ValueError):
        parse_session_revisit("nope")


def test_config_validation():
    with pytest.raises(ValueError):
        TraceConfig(n_requests=0)
    with pytest.raises(ValueError):
        TraceConfig(vocab=2)
    with pytest.raises(ValueError):
        TraceConfig(unary_frac=1.5)
    with pytest.raises(ValueError):
        TraceConfig(tenants=())
    with pytest.raises(ValueError):
        TraceConfig(tenants=("a", "b"), tenant_weights=(1.0,))
    with pytest.raises(ValueError):
        TraceConfig(tenants=("a",), tenant_weights=(0.0,))
    with pytest.raises(ValueError):
        TraceConfig(session_revisit=(1.5, 0.0))
    with pytest.raises(ValueError):
        TraceConfig(session_revisit=(0.5, -1.0))


def test_cli_writes_loadable_trace(tmp_path, capsys):
    out = tmp_path / "cli.jsonl"
    rc = main(["--out", str(out), "--seed", "4", "--requests", "30",
               "--prefix-chunk", "8", "--n-prefixes", "4",
               "--prompt-max", "32", "--output-max", "8",
               "--vocab", "128", "--tenant", "acme",
               "--tenant", "globex"])
    assert rc == 0
    header, reqs = load_trace(str(out))
    assert len(reqs) == 30
    assert {r.tenant for r in reqs} <= {"acme", "globex"}
    printed = json.loads(capsys.readouterr().out)
    assert printed["summary"]["requests"] == 30
