"""Pallas max-pool: exactness vs flax nn.max_pool / XLA autodiff.

The kernel's contract is bit-exactness — forward values AND gradients,
including select_and_scatter's first-match tie-break — so every check
here is equality, not tolerance.  Runs in interpreter mode on the CPU
test mesh (same code path as the compiled TPU kernel; the compiled
path is additionally exercised on real hardware by bench.py).
"""

import jax
import jax.numpy as jnp
import flax.linen as nn
import numpy as np
import pytest

from tpu_k8s_device_plugin.workloads.pool import max_pool


def _ref(x, window, stride):
    return nn.max_pool(x, (window, window), (stride, stride))


def _grads(fn, x):
    return jax.grad(lambda a: jnp.sum(fn(a).astype(jnp.float32) ** 2))(x)


CASES = [
    ((2, 56, 56, 64), 3, 2),   # AlexNet seg1
    ((2, 27, 27, 192), 3, 2),  # AlexNet seg2 (odd spatial)
    ((2, 13, 13, 256), 3, 2),  # AlexNet seg5
    ((3, 10, 10, 16), 2, 2),   # non-overlapping window
    ((1, 9, 9, 8), 3, 3),      # stride == window
    ((2, 8, 12, 4), 3, 1),     # stride 1 (fully overlapping)
]


@pytest.mark.parametrize("shape,window,stride", CASES)
def test_forward_exact(shape, window, stride):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    got = max_pool(x, window, stride, interpret=True)
    ref = _ref(x, window, stride)
    assert got.shape == ref.shape
    assert jnp.array_equal(got, ref)


@pytest.mark.parametrize("shape,window,stride", CASES)
def test_gradient_exact(shape, window, stride):
    x = jax.random.normal(jax.random.PRNGKey(1), shape, jnp.float32)
    g_got = _grads(lambda a: max_pool(a, window, stride, interpret=True), x)
    g_ref = _grads(lambda a: _ref(a, window, stride), x)
    assert jnp.array_equal(g_got, g_ref)


def test_gradient_tie_break_matches_select_and_scatter():
    # quantized values force many exact ties inside windows; the
    # gradient must still route every dp element to the same winner
    # XLA's select_and_scatter picks (first max in row-major order)
    x = jnp.round(
        jax.random.normal(jax.random.PRNGKey(2), (4, 20, 20, 8)) * 2
    ).astype(jnp.float32)
    g_got = _grads(lambda a: max_pool(a, 3, 2, interpret=True), x)
    g_ref = _grads(lambda a: _ref(a, 3, 2), x)
    assert jnp.array_equal(g_got, g_ref)


def test_constant_plateau_routes_to_first_offset():
    # all-equal input: every window is one big tie; the whole pooled
    # gradient must land on each window's (0, 0) corner
    x = jnp.ones((1, 5, 5, 4), jnp.float32)
    g = _grads(lambda a: max_pool(a, 3, 2, interpret=True), x)
    g_ref = _grads(lambda a: _ref(a, 3, 2), x)
    assert jnp.array_equal(g, g_ref)


def test_bfloat16_exact():
    x = jax.random.normal(
        jax.random.PRNGKey(3), (2, 27, 27, 64)).astype(jnp.bfloat16)
    got = max_pool(x, 3, 2, interpret=True)
    ref = _ref(x, 3, 2)
    assert got.dtype == jnp.bfloat16
    assert jnp.array_equal(
        got.astype(jnp.float32), ref.astype(jnp.float32))
    g_got = _grads(lambda a: max_pool(a, 3, 2, interpret=True), x)
    g_ref = _grads(lambda a: _ref(a, 3, 2), x)
    assert jnp.array_equal(
        g_got.astype(jnp.float32), g_ref.astype(jnp.float32))


def test_neg_inf_data_survives_padding():
    # the kernel pads parity planes with -inf; real -inf data must
    # still pool to -inf and not corrupt neighbours
    x = jnp.full((1, 7, 7, 8), -jnp.inf, jnp.float32)
    got = max_pool(x, 3, 2, interpret=True)
    assert jnp.array_equal(got, _ref(x, 3, 2))


def test_jit_and_vmap_compose():
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 13, 13, 8))
    jitted = jax.jit(lambda a: max_pool(a, 3, 2, interpret=True))
    assert jnp.array_equal(jitted(x), _ref(x, 3, 2))


def test_batch_not_multiple_of_128_exact():
    # awkward batch sizes stay exact: on TPU the lane dim pads to 128
    # and slices back; in interpret mode (this test) the batch is used
    # as the lane block directly (_batch_tiling) — either way the
    # result must match the oracle losslessly
    x = jax.random.normal(jax.random.PRNGKey(5), (5, 12, 12, 8))
    assert jnp.array_equal(
        max_pool(x, 3, 2, interpret=True), _ref(x, 3, 2))


def test_alexnet_pallas_pool_matches_xla_pool():
    # the model-level knob: same params, both pool impls, identical
    # logits and gradients (interpret mode on CPU)
    import functools

    from tpu_k8s_device_plugin.workloads.alexnet import (
        AlexNet,
        loss_fn,
        space_to_depth,
    )

    rng = jax.random.PRNGKey(0)
    x = space_to_depth(
        jax.random.normal(rng, (2, 224, 224, 3), jnp.float32))
    labels = jnp.asarray([3, 7])
    a_xla = AlexNet(num_classes=10, dtype=jnp.float32, s2d=True,
                    pool="xla")
    a_pl = AlexNet(num_classes=10, dtype=jnp.float32, s2d=True,
                   pool="pallas")
    params = a_xla.init(rng, x, train=False)["params"]
    lx = a_xla.apply({"params": params}, x, train=False)
    lp = a_pl.apply({"params": params}, x, train=False)
    assert jnp.array_equal(lx, lp)
    gx = jax.grad(functools.partial(loss_fn, a_xla))(params, x, labels)
    gp = jax.grad(functools.partial(loss_fn, a_pl))(params, x, labels)
    # the pool op itself is bit-exact (tests above); through the whole
    # model, XLA fuses differently around the custom-call boundary so
    # OTHER ops' accumulation order shifts at float epsilon
    for a, b in zip(jax.tree_util.tree_leaves(gx),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
