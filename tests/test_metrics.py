"""Prometheus /metrics surfaces (VERDICT r3 #5): the exporter daemon's
per-chip health gauges must transition when a fixture chip wedges, and
the plugin debug endpoint must re-render its RPC/impl counters in
exposition format."""

import os
import shutil
import urllib.request

import pytest

from tpu_k8s_device_plugin.health.metrics import (
    MetricsHTTPServer,
    render_metrics,
)
from tpu_k8s_device_plugin.types import constants


@pytest.fixture
def v5e8_copy(testdata, tmp_path):
    dst = str(tmp_path / "v5e-8")
    shutil.copytree(os.path.join(testdata, "v5e-8"), dst, symlinks=True)
    return dst


def _roots(copy):
    return os.path.join(copy, "sys"), os.path.join(copy, "dev")


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


def _series(body):
    """{name{labels}: value} for every non-comment sample line."""
    out = {}
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        out[key] = float(val)
    return out


def test_render_all_healthy(v5e8_copy):
    sys_root, dev_root = _roots(v5e8_copy)
    s = _series(render_metrics(sys_root, dev_root, scrapes=1))
    gauges = {k: v for k, v in s.items()
              if k.startswith("tpu_device_health{")}
    assert len(gauges) == 8 and all(v == 1 for v in gauges.values())
    assert s["tpu_exporter_chips"] == 8
    assert s["tpu_exporter_unhealthy_chips"] == 0
    assert s["tpu_exporter_scrapes_total"] == 1


def test_gauge_transitions_when_chip_wedges(v5e8_copy):
    """The VERDICT done-criterion: curl /metrics, wedge a fixture chip,
    curl again — the gauge must flip 1 -> 0 and the UE counter appear."""
    sys_root, dev_root = _roots(v5e8_copy)
    srv = MetricsHTTPServer(port=0, host="127.0.0.1",
                            sysfs_root=sys_root,
                            dev_root=dev_root).start()
    try:
        status, body = _get(srv.port, "/metrics")
        assert status == 200
        before = _series(body)
        key = next(k for k in before
                   if k.startswith('tpu_device_health{chip="0000:00:06.0"'))
        assert before[key] == 1

        pci_dir = os.path.join(
            sys_root, "devices", "pci0000:00", "0000:00:06.0")
        with open(os.path.join(pci_dir, constants.SYSFS_CHIP_STATE),
                  "w") as f:
            f.write("dead\n")
        with open(os.path.join(pci_dir, constants.SYSFS_UE_COUNT),
                  "w") as f:
            f.write("5\n")

        status, body = _get(srv.port, "/metrics")
        after = _series(body)
        assert after[key] == 0
        assert after["tpu_exporter_unhealthy_chips"] == 1
        assert after[
            'tpu_device_uncorrectable_errors_total{chip="0000:00:06.0"}'] == 5
        assert after["tpu_exporter_scrapes_total"] == 2
    finally:
        srv.stop()


def test_healthz_and_404(v5e8_copy):
    sys_root, dev_root = _roots(v5e8_copy)
    srv = MetricsHTTPServer(port=0, host="127.0.0.1",
                            sysfs_root=sys_root,
                            dev_root=dev_root).start()
    try:
        assert _get(srv.port, "/healthz") == (200, "ok\n")
        try:
            _get(srv.port, "/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_exporter_cli_serves_metrics_port(v5e8_copy, tmp_path):
    """The CLI flag wires the HTTP listener next to the gRPC socket,
    and SIGTERM tears both down (no leaked listeners — a thread-driven
    main() would outlive the test)."""
    import signal
    import socket
    import subprocess
    import sys
    import time

    sys_root, dev_root = _roots(v5e8_copy)
    sock = str(tmp_path / "hm.sock")
    # grab an ephemeral port for the CLI (it has no port-0 report path)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_k8s_device_plugin.cmd.metrics_exporter",
         "--socket", sock, "--metrics-port", str(port),
         "--sysfs-root", sys_root, "--dev-root", dev_root],
        cwd=repo,
    )
    try:
        body = None
        for _ in range(100):
            try:
                _, body = _get(port, "/metrics")
                break
            except OSError:
                time.sleep(0.1)
        assert body is not None, "CLI never served /metrics"
        assert "tpu_device_health" in body
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=10) == 143
        assert not os.path.exists(sock), "SIGTERM left a stale socket"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_plugin_debug_metrics_route(testdata, tmp_path):
    """The plugin's debug server re-renders Allocate/ListAndWatch
    counters and the degraded-bounds count as Prometheus text."""
    from fake_kubelet import FakeKubelet
    from tpu_k8s_device_plugin.manager import PluginManager
    from tpu_k8s_device_plugin.observability import DebugServer
    from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
    from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl

    root = os.path.join(testdata, "v5e-8")
    impl = TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
    )
    kubelet = FakeKubelet(str(tmp_path / "device-plugins")).start()
    manager = PluginManager(impl, kubelet_dir=kubelet.dir,
                            kubelet_watch_interval_s=0.1)
    manager.run(block=False)
    debug = DebugServer(manager, port=0).start()
    try:
        assert kubelet.wait_for_registration()
        stub = kubelet.plugin_stub("google.com_tpu")
        # one contiguous, one fragmented Allocate
        stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[pluginapi.ContainerAllocateRequest(
                devices_ids=["0000:00:04.0", "0000:00:05.0"])]))
        stub.Allocate(pluginapi.AllocateRequest(
            container_requests=[pluginapi.ContainerAllocateRequest(
                devices_ids=["0000:00:04.0", "0000:00:07.0"])]))
        status, body = _get(debug.port, "/metrics")
        assert status == 200
        s = _series(body)
        assert s['tpu_plugin_rpc_total{resource="tpu",rpc="allocate"}'] == 2
        assert s['tpu_plugin_devices_healthy{resource="tpu"}'] == 8
        assert s['tpu_plugin_devices_unhealthy{resource="tpu"}'] == 0
        # renamed in PR 3 (promlint: counters end in _total)
        assert s["tpu_plugin_degraded_bounds_allocations_total"] == 1
        # Allocate latency histogram moved with the RPCs
        assert s['tpu_plugin_allocate_seconds_count{resource="tpu"}'] == 2
    finally:
        debug.stop()
        manager.stop()
        kubelet.stop()


def test_granular_health_gauge_and_degrade(v5e8_copy, caplog):
    """The fixture ABI's risky attrs (chip_state / uncorrectable_errors
    — modelled, not driver-cited; testdata/README.md) must degrade
    VISIBLY when a real driver omits them (VERDICT r4 #3): the scrape
    flips tpu_exporter_granular_health to 0 and the probe logs
    'granular health unavailable' once per tree."""
    import glob
    import logging

    from tpu_k8s_device_plugin.health.server import probe_chip_states

    sys_root, dev_root = _roots(v5e8_copy)
    s = _series(render_metrics(sys_root, dev_root))
    assert s["tpu_exporter_granular_health"] == 1
    # strip every granular attr, as an older/differently-spelled
    # driver's tree would look
    for pat in ("chip_state", "uncorrectable_errors"):
        for f in glob.glob(os.path.join(
                sys_root, "bus", "pci", "devices", "*", pat)):
            os.remove(f)
    with caplog.at_level(logging.WARNING):
        states = probe_chip_states(sys_root, dev_root)
    # per-chip verdicts stay absence-is-healthy ...
    assert all(st.health == "Healthy" for st in states.values())
    # ... but the degradation is operator-visible, exactly once
    hits = [r for r in caplog.records
            if "granular health unavailable" in r.message]
    assert len(hits) == 1
    with caplog.at_level(logging.WARNING):
        probe_chip_states(sys_root, dev_root)
    assert len([r for r in caplog.records
                if "granular health unavailable" in r.message]) == 1
    s = _series(render_metrics(sys_root, dev_root))
    assert s["tpu_exporter_granular_health"] == 0
