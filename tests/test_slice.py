"""Multi-host slice coordination: rendezvous, ranks, health propagation.

Two full plugin managers — one per v5e-16 fixture host, each with its own
fake kubelet — form a 2-host slice over real gRPC sockets and must hand
every container a consistent env contract; a chip wedged on host A (the
sysfs ``chip_state`` watch) must flip host B's devices Unhealthy in its
next ListAndWatch frame, and recovery must propagate back; a restarted
coordinator or worker must recover membership from the crash-safe state
file without re-forming the slice.
"""

import concurrent.futures
import os
import shutil
import time

import grpc
import pytest

from tpu_k8s_device_plugin.health.server import probe_chip_states
from tpu_k8s_device_plugin.manager import PluginManager
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.slice import (
    SliceClient,
    SliceCoordinator,
    SliceState,
    load_membership,
)
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
from tpu_k8s_device_plugin.tpu.topology import derive_worker_identity
from tpu_k8s_device_plugin.types import constants

from fake_kubelet import FakeKubelet, ListAndWatchConsumer

_JAX_PORT = 8476


class SliceHost:
    """One member: mutable fixture tree, device impl (sysfs-fed granular
    health), slice client, fake kubelet, and a pulsing plugin manager."""

    def __init__(self, name, fixture, testdata, tmp_path, rendezvous):
        self.name = name
        root = tmp_path / name
        shutil.copytree(os.path.join(testdata, fixture), root, symlinks=True)
        self.sys_root = str(root / "sys")
        self.dev_root = str(root / "dev")
        self.impl = TpuContainerImpl(
            sysfs_root=self.sys_root,
            dev_root=self.dev_root,
            tpu_env_path=str(root / "run" / "tpu" / "tpu-env"),
            health_fn=self._granular,
        )
        self.client = SliceClient(
            rendezvous_address=rendezvous,
            hostname=name,
            coords=(self.impl.topology.worker_id,),
            chip_count=len(self.impl.chips),
            state_path=str(tmp_path / f"{name}-membership.json"),
            local_health_fn=self.impl.local_health,
        )
        self.impl.set_slice_client(self.client)
        self.kubelet = FakeKubelet(str(tmp_path / f"{name}-dp")).start()
        self.manager = PluginManager(
            self.impl,
            pulse_seconds=0,
            kubelet_dir=self.kubelet.dir,
            kubelet_watch_interval_s=0.1,
            slice_client=self.client,
        )

    def _granular(self):
        states = probe_chip_states(self.sys_root, self.dev_root)
        return {cid: st.health for cid, st in states.items()}

    def pulse(self):
        """One manual pulse round, exactly the manager loop's order:
        slice heartbeat first, then beat every plugin."""
        self.client.heartbeat_now()
        with self.manager._plugins_lock:
            plugins = list(self.manager._plugins.values())
        for sp in plugins:
            sp.plugin.beat()

    def wedge_chip(self, pci_address, state="dead"):
        attr = os.path.join(
            self.sys_root, "devices", "pci0000:00", pci_address,
            constants.SYSFS_CHIP_STATE,
        )
        with open(attr, "w") as f:
            f.write(f"{state}\n")

    def stop(self):
        self.manager.stop()
        self.client.stop()
        self.kubelet.stop()


@pytest.fixture
def coordinator(tmp_path):
    c = SliceCoordinator(
        expected_workers=2,
        bind_address="127.0.0.1:0",
        jax_port=_JAX_PORT,
        state_path=str(tmp_path / "coordinator-membership.json"),
        heartbeat_timeout_s=0.0,  # tests drive heartbeats explicitly
    ).start()
    yield c
    c.stop()


@pytest.fixture
def hosts(coordinator, testdata, tmp_path):
    rendezvous = f"127.0.0.1:{coordinator.port}"
    pair = [
        SliceHost("host-a", "v5e-16-host0", testdata, tmp_path, rendezvous),
        SliceHost("host-b", "v5e-16-host1", testdata, tmp_path, rendezvous),
    ]
    yield pair
    for h in pair:
        h.stop()


def _form(hosts):
    """Concurrent joins, as in real deployments (each plugin process polls
    until the slice forms).  host-b is submitted first: ranks must come
    from ICI coordinates, not from who knocked first."""
    with concurrent.futures.ThreadPoolExecutor(len(hosts)) as pool:
        futures = [
            pool.submit(h.client.join, timeout_s=15.0)
            for h in reversed(hosts)
        ]
        for f in futures:
            f.result(timeout=20.0)


def _allocate_all(host):
    """Drive Allocate exactly as the kubelet would, over the wire."""
    assert host.kubelet.wait_for_registration()
    stub = host.kubelet.plugin_stub("google.com_tpu")
    consumer = ListAndWatchConsumer(stub)
    frame = consumer.next_frame()
    ids = [d.ID for d in frame.devices]
    resp = stub.Allocate(
        pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(devices_ids=ids)
            ]
        )
    )
    [car] = resp.container_responses
    return consumer, dict(car.envs)


def test_two_hosts_form_slice_with_consistent_env(hosts):
    """Acceptance: two coordinated managers, consistent rank/hostname env
    in both Allocate responses over real gRPC."""
    _form(hosts)
    a, b = hosts
    # deterministic ranks from ICI coordinates (host-a is worker 0 in the
    # fixture metadata) even though host-b joined first
    assert a.client.rank == 0 and b.client.rank == 1
    m = a.client.membership
    assert m.hostnames == ("host-a", "host-b")
    assert m.coordinator_address == f"host-a:{_JAX_PORT}"
    assert b.client.membership == m

    a.manager.run(block=False)
    b.manager.run(block=False)
    _, env_a = _allocate_all(a)
    _, env_b = _allocate_all(b)

    # the rendezvous contract, identical on both members modulo rank
    assert env_a[constants.ENV_TPU_WORKER_ID] == "0"
    assert env_b[constants.ENV_TPU_WORKER_ID] == "1"
    for env in (env_a, env_b):
        assert env[constants.ENV_TPU_WORKER_HOSTNAMES] == "host-a,host-b"
        assert (env[constants.ENV_JAX_COORDINATOR_ADDRESS]
                == f"host-a:{_JAX_PORT}")
        assert env[constants.ENV_JAX_NUM_PROCESSES] == "2"
        # the per-host topology env still rides along
        assert env[constants.ENV_TPU_PROCESS_BOUNDS] == "2,1,1"
    assert env_a[constants.ENV_JAX_PROCESS_ID] == "0"
    assert env_b[constants.ENV_JAX_PROCESS_ID] == "1"

    # the slice is operator-visible on the debug surface
    from tpu_k8s_device_plugin.observability import manager_status
    st = manager_status(b.manager)["slice"]
    assert st["formed"] and st["rank"] == 1
    assert st["hostnames"] == ["host-a", "host-b"]


def test_allocate_before_formation_falls_back_to_metadata(
    coordinator, testdata, tmp_path
):
    """A pod admitted while the slice is still forming gets the per-host
    metadata view (no rendezvous contract yet) — the plugin serves its
    kubelet without blocking on peers."""
    h = SliceHost("host-b", "v5e-16-host1", testdata, tmp_path,
                  f"127.0.0.1:{coordinator.port}")
    try:
        h.manager.run(block=False)
        _, env = _allocate_all(h)
        # tpu-env metadata WORKER_ID, not a rendezvous rank
        assert env[constants.ENV_TPU_WORKER_ID] == "1"
        assert constants.ENV_TPU_WORKER_HOSTNAMES not in env
        assert constants.ENV_JAX_COORDINATOR_ADDRESS not in env
    finally:
        h.stop()


def test_wedged_chip_propagates_slice_wide_and_recovers(hosts):
    """Acceptance: a single-chip failure on host A reaches host B's
    kubelet as all-Unhealthy within one heartbeat period, and recovery
    propagates the same way."""
    _form(hosts)
    a, b = hosts
    a.manager.run(block=False)
    b.manager.run(block=False)
    consumer_a, _ = _allocate_all(a)
    consumer_b, _ = _allocate_all(b)

    # settle: both members report healthy, both streams render it
    a.pulse()
    b.pulse()
    frame = consumer_a.next_frame()
    assert all(d.health == constants.HEALTHY for d in frame.devices)
    frame = consumer_b.next_frame()
    assert all(d.health == constants.HEALTHY for d in frame.devices)

    # wedge one chip on A (driver-reported state, the chardev still opens)
    a.wedge_chip("0000:00:06.0")
    a.pulse()   # A probes the fault and ships it in its heartbeat
    b.pulse()   # B learns the slice verdict, then beats its streams
    frame = consumer_b.next_frame()
    assert all(d.health == constants.UNHEALTHY for d in frame.devices), (
        "host B must demote ALL its devices when host A has a wedged chip"
    )
    # A's own frame is demoted too (its chip is the faulty one)
    frame = consumer_a.next_frame()
    assert all(d.health == constants.UNHEALTHY for d in frame.devices)

    # recovery: chip back alive -> whole slice healthy again
    a.wedge_chip("0000:00:06.0", state=constants.CHIP_STATE_ALIVE)
    a.pulse()
    b.pulse()
    frame = consumer_b.next_frame()
    assert all(d.health == constants.HEALTHY for d in frame.devices)


def test_coordinator_restart_recovers_membership(coordinator, hosts, tmp_path):
    """Acceptance: a restarted coordinator serves the SAME membership
    (ranks, slice id, generation) from its crash-safe state file, without
    waiting for the full slice to re-join."""
    _form(hosts)
    before = hosts[0].client.membership
    coordinator.stop()

    revived = SliceCoordinator(
        expected_workers=2,
        bind_address=f"127.0.0.1:{coordinator.port}",
        jax_port=_JAX_PORT,
        state_path=coordinator.state.state_path,
        heartbeat_timeout_s=0.0,
    ).start()
    try:
        # ONE member rejoining suffices — no re-formation quorum
        after = hosts[0].client.join(timeout_s=10.0)
        assert after == before
        assert revived.state.membership.generation == before.generation
    finally:
        revived.stop()


def test_worker_restart_recovers_rank_from_state_file(hosts, tmp_path):
    """A restarted worker knows its rank before any RPC (local state
    file), and re-polling the coordinator confirms it without changing
    the membership."""
    _form(hosts)
    b = hosts[1]
    reborn = SliceClient(
        rendezvous_address=b.client._address,
        hostname=b.name,
        state_path=b.client._state_path,
    )
    try:
        assert reborn.rank == 1          # before any RPC
        m = reborn.join(timeout_s=10.0)  # coordinator agrees, no re-form
        assert m == b.client.membership
    finally:
        reborn.stop()


def test_unknown_host_rejected_after_formation(hosts):
    _form(hosts)
    stranger = SliceClient(
        rendezvous_address=hosts[0].client._address,
        hostname="host-z",
        state_path=None,
    )
    try:
        with pytest.raises(RuntimeError, match="not a member"):
            stranger.join(timeout_s=5.0)
    finally:
        stranger.stop()


def test_join_times_out_without_coordinator(tmp_path):
    lonely = SliceClient(
        rendezvous_address="127.0.0.1:1",  # nothing listens there
        hostname="host-a",
        state_path=None,
    )
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="did not form"):
            lonely.join(timeout_s=1.0)
        assert time.monotonic() - t0 < 10.0
    finally:
        lonely.stop()


def test_stale_member_drags_slice_unhealthy():
    """Coordinator-side staleness: a member that stops heartbeating past
    the timeout poisons the slice, exactly like a reported fault."""
    s = SliceState(expected_workers=2, jax_port=_JAX_PORT,
                   heartbeat_timeout_s=5.0)
    s.join("host-a", coords=(0,), now=0.0)
    s.join("host-b", coords=(1,), now=0.0)
    v = s.heartbeat("host-a", healthy=True, now=1.0)
    assert v.slice_healthy
    # host-b silent for > timeout
    v = s.heartbeat("host-a", healthy=True, now=7.0)
    assert not v.slice_healthy and v.unhealthy_hostnames == ["host-b"]
    # it comes back
    s.heartbeat("host-b", healthy=True, now=8.0)
    v = s.heartbeat("host-a", healthy=True, now=8.5)
    assert v.slice_healthy


def test_single_host_identity_unchanged():
    """Satellite guard: without a slice client, both Allocate paths derive
    the same worker identity as before (sub-host grants are worker 0 of a
    standalone slice; full-host grants follow the metadata)."""
    assert derive_worker_identity(None, full_host=False) == (0, 1)
    assert derive_worker_identity(None, full_host=True) == (0, 1)


def test_membership_file_round_trip(tmp_path, coordinator, hosts):
    _form(hosts)
    for h in hosts:
        m = load_membership(h.client._state_path)
        assert m == h.client.membership
    # coordinator's own copy matches too
    assert load_membership(coordinator.state.state_path) == \
        hosts[0].client.membership


def test_slice_metrics_move_across_member_death():
    """PR 3 observability satellite: across a simulated member death
    (heartbeats stop, the staleness timeout demotes it), the rendered
    heartbeat-age gauge must GROW for the dead member and the
    membership-transition counters must record the demotion — and
    recovery must move them again the other way."""
    from tools.promlint import lint
    from tpu_k8s_device_plugin import obs
    from tpu_k8s_device_plugin.slice import SliceMetrics

    metrics = SliceMetrics()
    reg = metrics.registry
    s = SliceState(expected_workers=2, jax_port=_JAX_PORT,
                   heartbeat_timeout_s=5.0, metrics=metrics)
    s.join("host-a", coords=(0,), session="a1", now=0.0)
    s.join("host-b", coords=(1,), session="b1", now=0.0)
    s.heartbeat("host-a", healthy=True, now=1.0)
    s.heartbeat("host-b", healthy=True, now=1.0)

    def series(now):
        s.refresh_ages(now)
        samples = obs.parse_exposition(reg.render())
        return {(n, tuple(sorted(ls.items()))): v
                for n, ls, v in samples}

    before = series(now=2.0)
    assert before[("tpu_slice_membership_transitions_total",
                   (("kind", "formed"),))] == 1
    age_key = ("tpu_slice_heartbeat_age_seconds",
               (("hostname", "host-b"),))
    assert before[age_key] == 1.0  # last heard at t=1

    # host-b dies: only host-a keeps beating; past the 5s timeout the
    # verdict flips and host-a's next heartbeat DELIVERS the demotion
    v = s.heartbeat("host-a", healthy=True, now=9.0)
    assert not v.slice_healthy and v.unhealthy_hostnames == ["host-b"]
    dead = series(now=9.0)
    assert dead[age_key] == 8.0  # age grew with the silence
    assert dead[("tpu_slice_membership_transitions_total",
                 (("kind", "slice_demoted"),))] == 1
    # propagation observed for host-a (its heartbeat after the flip)
    assert dead[("tpu_slice_demotion_propagation_seconds_count",
                 ())] >= 1

    # host-b comes back: age snaps down, recovery transition recorded
    v = s.heartbeat("host-b", healthy=True, now=10.0)
    assert v.slice_healthy
    back = series(now=10.5)
    assert back[age_key] == 0.5
    assert back[("tpu_slice_membership_transitions_total",
                 (("kind", "slice_recovered"),))] == 1
    # the slice surface stays promlint-clean while it moves
    assert lint(reg.render()) == []


def test_corrupt_membership_file_variants_load_as_none(tmp_path):
    """A corrupt/truncated/alien state file means re-forming, never
    crashing (PR 5 satellite): every breakage mode loads as None."""
    p = str(tmp_path / "membership.json")
    for payload in (
        b"",                                  # empty
        b"\x00\xff\xfe binary garbage",       # not JSON at all
        b'{"version": 99, "hostnames": []}',  # unknown version
        b'{"version": 1}',                    # missing fields
        b'{"version": 1, "slice_id": "s", "generation": "NaNope", '
        b'"hostnames": ["a"]}',               # wrong field type
        b'{"version": 1, "slice_id": "s", "gen',  # truncated mid-write
    ):
        with open(p, "wb") as f:
            f.write(payload)
        assert load_membership(p) is None, payload


def test_truncated_membership_file_recovery_over_grpc(hosts):
    """A worker restarting onto a TRUNCATED state file (power loss
    mid-disk-flush) must silently re-join and re-persist a clean file
    with the same rank — the crash-safe contract end to end."""
    _form(hosts)
    a = hosts[0]
    path = a.client._state_path
    content = open(path).read()
    with open(path, "w") as f:
        f.write(content[: len(content) // 2])
    assert load_membership(path) is None
    restarted = SliceClient(
        rendezvous_address=a.client._address,
        hostname=a.name,
        coords=(0,),
        chip_count=len(a.impl.chips),
        state_path=path,
        join_backoff_initial_s=0.05,
        join_backoff_max_s=0.2,
    )
    try:
        # the corrupt file must not seed a membership
        assert restarted.membership is None
        m = restarted.join(timeout_s=10.0)
        assert m.rank_of(a.name) == 0           # same rank, no re-form
        assert m.generation == \
            hosts[1].client.membership.generation
        # and the state file is whole again
        assert load_membership(path) == m
    finally:
        restarted.stop()
