"""DeviceImpl tests: container impl and VFIO passthrough impls against
fixture trees (≈ reference amdgpu_test.go + the VF/PF coverage it lacks)."""

import os

import pytest

from tpu_k8s_device_plugin.allocator import BestEffortPolicy
from tpu_k8s_device_plugin.proto import deviceplugin_pb2 as pluginapi
from tpu_k8s_device_plugin.types import DevicePluginContext, constants
from tpu_k8s_device_plugin.tpu.device_impl import TpuContainerImpl
from tpu_k8s_device_plugin.tpu.device_impl_vfio import TpuPfImpl, TpuVfImpl
from tpu_k8s_device_plugin.tpu.vfio import (
    get_pf_mapping,
    get_tpu_vf_module_versions,
    get_vf_mapping,
)


def make_impl(testdata, name, **kwargs):
    root = os.path.join(testdata, name)
    return TpuContainerImpl(
        sysfs_root=os.path.join(root, "sys"),
        dev_root=os.path.join(root, "dev"),
        tpu_env_path=os.path.join(root, "run", "tpu", "tpu-env"),
        **kwargs,
    )


def ctx_for(impl, resource=None):
    resource = resource or impl.get_resource_names()[0]
    ctx = DevicePluginContext(resource, BestEffortPolicy())
    impl.start(ctx)
    return ctx


def addr(i):
    return f"0000:00:{4 + i:02x}.0"


class TestContainerImpl:
    def test_resource_names_single(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        assert impl.get_resource_names() == ["tpu"]

    def test_enumerate_with_numa_topology(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        devs = impl.enumerate(ctx)
        assert len(devs) == 8
        assert all(d.health == constants.HEALTHY for d in devs)
        by_id = {d.ID: d for d in devs}
        assert by_id[addr(0)].topology.nodes[0].ID == 0
        assert by_id[addr(7)].topology.nodes[0].ID == 1

    def test_allocate_mounts_and_env(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[addr(0), addr(1)]
                )
            ]
        )
        resp = impl.allocate(ctx, req)
        car = resp.container_responses[0]
        assert [os.path.basename(d.host_path) for d in car.devices] == [
            "accel0", "accel1"
        ]
        assert all(d.permissions == "rw" for d in car.devices)
        assert car.envs[constants.ENV_TPU_VISIBLE_CHIPS] == "0,1"
        assert car.envs[constants.ENV_TPU_SKIP_MDS_QUERY] == "true"
        # sub-host allocation: the slice-wide accelerator type is omitted
        # (it would imply a chip count the container is not granted)
        assert constants.ENV_TPU_ACCELERATOR_TYPE not in car.envs
        # 2 adjacent chips on the x axis -> 2x1x1 bounding box
        assert car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] == "2,1,1"
        assert car.envs[constants.ENV_TPU_PROCESS_BOUNDS] == "1,1,1"

    def test_allocate_full_host_propagates_slice_identity(self, testdata):
        """A whole-host allocation on a multi-host slice must carry the
        slice-level identity so JAX/libtpu can initialise distributed
        training (worker 0 of the 2-host v5e-16 fixture)."""
        impl = make_impl(testdata, "v5e-16-host0")
        ctx = ctx_for(impl)
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[addr(i) for i in range(8)]
                )
            ]
        )
        car = impl.allocate(ctx, req).container_responses[0]
        assert car.envs[constants.ENV_TPU_ACCELERATOR_TYPE] == "v5litepod-16"
        assert car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] == "2,4,1"
        assert car.envs[constants.ENV_TPU_PROCESS_BOUNDS] == "2,1,1"
        assert car.envs[constants.ENV_TPU_WORKER_ID] == "0"
        assert car.envs[constants.ENV_TPU_TOPOLOGY] == "4x4"

    def test_allocate_full_host_propagates_worker1_identity(self, testdata):
        """The second worker's full-host grant must carry TPU_WORKER_ID=1
        and the same slice-global identity as worker 0 — libtpu derives
        each process's slice offset from exactly this pair."""
        impl = make_impl(testdata, "v5e-16-host1")
        ctx = ctx_for(impl)
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[addr(i) for i in range(8)]
                )
            ]
        )
        car = impl.allocate(ctx, req).container_responses[0]
        assert car.envs[constants.ENV_TPU_ACCELERATOR_TYPE] == "v5litepod-16"
        assert car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] == "2,4,1"
        assert car.envs[constants.ENV_TPU_PROCESS_BOUNDS] == "2,1,1"
        assert car.envs[constants.ENV_TPU_WORKER_ID] == "1"
        assert car.envs[constants.ENV_TPU_TOPOLOGY] == "4x4"

    def test_allocate_noncontiguous_bounds_degrade_linear(self, testdata, caplog):
        """Fragmented kubelet-default sets must not claim a bounding box
        whose volume exceeds the chip count — and the lossy degrade must
        be operator-visible (warning + counter), not silent (VERDICT r3
        #8: a pod with linear bounds has slow ICI collectives and the
        operator needs to see why)."""
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        assert impl.counters()["degraded_bounds_allocations"] == 0
        req = pluginapi.AllocateRequest(
            container_requests=[
                # coords (0,0) and (1,1): box volume 4 != 2 chips
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[addr(0), addr(3)]
                )
            ]
        )
        with caplog.at_level("WARNING",
                             logger="tpu_k8s_device_plugin.tpu.device_impl"):
            car = impl.allocate(ctx, req).container_responses[0]
        assert car.envs[constants.ENV_TPU_CHIPS_PER_HOST_BOUNDS] == "2,1,1"
        assert impl.counters()["degraded_bounds_allocations"] == 1
        assert any("non-contiguous" in r.message for r in caplog.records)

    def test_allocate_contiguous_does_not_count_degraded(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[addr(0), addr(1)]
                )
            ]
        )
        impl.allocate(ctx, req)
        assert impl.counters()["degraded_bounds_allocations"] == 0

    def test_allocate_unknown_device(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(devices_ids=["bogus"])
            ]
        )
        with pytest.raises(RuntimeError, match="unknown device"):
            impl.allocate(ctx, req)

    def test_preferred_allocation_uses_policy(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        req = pluginapi.PreferredAllocationRequest(
            container_requests=[
                pluginapi.ContainerPreferredAllocationRequest(
                    available_deviceIDs=[addr(i) for i in range(8)],
                    allocation_size=4,
                )
            ]
        )
        resp = impl.get_preferred_allocation(ctx, req)
        assert list(resp.container_responses[0].deviceIDs) == [
            addr(0), addr(1), addr(2), addr(3)
        ]

    def test_options_reflect_allocator_state(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        assert impl.get_options(ctx).get_preferred_allocation_available
        ctx.set_allocator_error(True)
        assert not impl.get_options(ctx).get_preferred_allocation_available

    def test_update_health_simple_check(self, testdata):
        impl = make_impl(testdata, "v5e-8")
        ctx = ctx_for(impl)
        devs = impl.update_health(ctx)
        # fixture sysfs still enumerates all chips -> healthy
        assert all(d.health == constants.HEALTHY for d in devs)

    def test_update_health_exporter_overlay(self, testdata):
        impl = make_impl(
            testdata, "v5e-8",
            health_fn=lambda: {addr(3): constants.UNHEALTHY},
        )
        ctx = ctx_for(impl)
        health = {d.ID: d.health for d in impl.update_health(ctx)}
        assert health[addr(3)] == constants.UNHEALTHY
        assert health[addr(0)] == constants.HEALTHY

    def test_update_health_exporter_failure_degrades(self, testdata):
        def boom():
            raise RuntimeError("exporter down")
        impl = make_impl(testdata, "v5e-8", health_fn=boom)
        ctx = ctx_for(impl)
        devs = impl.update_health(ctx)
        assert all(d.health == constants.HEALTHY for d in devs)

    def test_heterogeneous_requires_mixed(self, testdata):
        with pytest.raises(RuntimeError, match="mixed"):
            make_impl(testdata, "v5p-8-hetero")

    def test_heterogeneous_mixed_resources(self, testdata):
        impl = make_impl(
            testdata, "v5p-8-hetero",
            resource_naming_strategy=constants.RESOURCE_NAMING_STRATEGY_MIXED,
        )
        assert impl.get_resource_names() == ["tpu", "tpucore"]
        ctx_tpu = ctx_for(impl, "tpu")
        ctx_core = ctx_for(impl, "tpucore")
        assert len(impl.enumerate(ctx_tpu)) == 2
        core_devs = impl.enumerate(ctx_core)
        assert sorted(d.ID for d in core_devs) == [
            f"{addr(2)}#core0", f"{addr(2)}#core1",
            f"{addr(3)}#core0", f"{addr(3)}#core1",
        ]

    def test_core_partition_allocate(self, testdata):
        impl = make_impl(
            testdata, "v5p-8-core",
            resource_naming_strategy=constants.RESOURCE_NAMING_STRATEGY_MIXED,
        )
        assert impl.get_resource_names() == ["tpucore"]
        ctx = ctx_for(impl, "tpucore")
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(
                    devices_ids=[f"{addr(0)}#core0", f"{addr(0)}#core1"]
                )
            ]
        )
        car = impl.allocate(ctx, req).container_responses[0]
        # both cores live on one chip: one device node, not two
        assert [os.path.basename(d.host_path) for d in car.devices] == ["accel0"]
        assert car.envs["TPU_VISIBLE_CORES"] == "0,1"

    def test_no_accel_class_raises(self, testdata):
        with pytest.raises(RuntimeError, match="accel"):
            make_impl(testdata, "vfio-pf")


class TestVfioImpls:
    def test_pf_mapping(self, testdata):
        m = get_pf_mapping(os.path.join(testdata, "vfio-pf", "sys"))
        assert len(m) == 4
        assert m["8"].pci_address == addr(0)

    def test_vf_mapping(self, testdata):
        m = get_vf_mapping(os.path.join(testdata, "vfio-vf", "sys"))
        assert len(m) == 4  # 2 PFs x 2 VFs
        groups = sorted(m, key=int)
        assert m[groups[0]].pf_pci_address == addr(0)
        assert m[groups[0]].pci_address.startswith("0000:01:")

    def test_vf_module_versions(self, testdata):
        v = get_tpu_vf_module_versions(os.path.join(testdata, "vfio-vf", "sys"))
        assert v["version"] == "1.8.0"

    def test_pf_impl_enumerate_allocate(self, testdata):
        impl = TpuPfImpl(sysfs_root=os.path.join(testdata, "vfio-pf", "sys"))
        ctx = DevicePluginContext(impl.get_resource_names()[0])
        impl.start(ctx)
        assert ctx.get_allocator_error()  # no topology policy for passthrough
        devs = impl.enumerate(ctx)
        assert [d.ID for d in devs] == ["8", "9", "10", "11"]
        req = pluginapi.AllocateRequest(
            container_requests=[
                pluginapi.ContainerAllocateRequest(devices_ids=["8", "9"])
            ]
        )
        car = impl.allocate(ctx, req).container_responses[0]
        assert [d.host_path for d in car.devices] == [
            "/dev/vfio/8", "/dev/vfio/9", "/dev/vfio/vfio"
        ]
        assert car.envs["PCI_RESOURCE_GOOGLE_COM_TPU"] == f"{addr(0)},{addr(1)}"

    def test_pf_impl_health(self, testdata):
        impl = TpuPfImpl(sysfs_root=os.path.join(testdata, "vfio-pf", "sys"))
        ctx = DevicePluginContext("tpu")
        devs = impl.update_health(ctx)
        assert all(d.health == constants.HEALTHY for d in devs)

    def test_vf_impl_health_maps_pf(self, testdata):
        sys_root = os.path.join(testdata, "vfio-vf", "sys")
        impl = TpuVfImpl(
            sysfs_root=sys_root,
            resource_naming_strategy=constants.RESOURCE_NAMING_STRATEGY_MIXED,
            health_fn=lambda: {addr(0): constants.UNHEALTHY},
        )
        assert impl.get_resource_names() == ["tpu_vf"]
        ctx = DevicePluginContext("tpu_vf")
        health = {d.ID: d.health for d in impl.update_health(ctx)}
        # both VFs of PF0 inherit its unhealthiness; PF1's VFs stay healthy
        unhealthy = [g for g, h in health.items() if h == constants.UNHEALTHY]
        assert len(unhealthy) == 2

    def test_vf_impl_missing_driver_raises(self, testdata):
        with pytest.raises(RuntimeError):
            TpuVfImpl(sysfs_root=os.path.join(testdata, "vfio-pf", "sys"))
