"""Multi-tenant QoS over the serving front door: token-rate quotas
(429 as per-tenant policy), weighted fair queueing in the admission
heap, and preemption-by-page-eviction when the paged KV pool runs dry
— the preempted request COMPLETES after re-admission, proven on its
response and in the journal/metrics.
"""

import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_k8s_device_plugin.workloads.inference import make_decoder
from tpu_k8s_device_plugin.workloads.server import (
    EngineServer,
    TenantQuota,
    parse_tenant_quotas,
)
from tpu_k8s_device_plugin.workloads.serving import ServingEngine

CFG = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)
MAX_LEN = 64


@pytest.fixture(scope="module")
def setup():
    model = make_decoder(**CFG, max_len=MAX_LEN, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (1, 8))
    params = model.init(rng, tokens, pos)["params"]
    return model, params


def _post(port, payload, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        events = [json.loads(line) for line in resp if line.strip()]
        return resp.status, events
    finally:
        conn.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def test_parse_tenant_quotas():
    q = parse_tenant_quotas(["a=100", "b=50:200:2", "*=10:10"])
    assert q["a"].rate == 100 and q["a"].weight == 1.0
    assert q["b"].burst == 200 and q["b"].weight == 2.0
    assert q["*"].rate == 10
    with pytest.raises(ValueError):
        parse_tenant_quotas(["nope"])
    with pytest.raises(ValueError):
        parse_tenant_quotas(["a=1:2:3:4"])
    with pytest.raises(ValueError):
        parse_tenant_quotas(["a=1:1:0"])


def test_token_bucket_charges_and_refills():
    q = TenantQuota(rate=1000.0, burst=100.0)
    assert q.try_charge(80)
    assert not q.try_charge(80)      # bucket nearly empty
    time.sleep(0.1)                  # ~100 tokens refill
    assert q.try_charge(80)
    unlimited = TenantQuota(rate=0.0)
    for _ in range(100):
        assert unlimited.try_charge(1e9)


def test_quota_429_is_per_tenant(setup):
    """A bursting tenant exhausts ITS bucket and 429s; the quiet
    tenant keeps admitting — 429 as policy, not a global constant."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(
        eng, max_new_tokens=4, window=4,
        tenant_quotas=parse_tenant_quotas(
            ["burst=1:30", "quiet=1000:100000"]))
    srv.start(host="127.0.0.1", port=0)
    try:
        # each request estimates 4 prompt + 4 budget = 8 tokens
        codes = [
            _post(srv.port, {"tokens": [1, 2, 3, 4],
                             "max_new_tokens": 4,
                             "tenant": "burst"})[0]
            for _ in range(6)
        ]
        assert 429 in codes, codes          # the burst got throttled
        assert codes[0] == 200              # but not before its burst
        st, _ = _post(srv.port, {"tokens": [1, 2, 3, 4],
                                 "max_new_tokens": 4,
                                 "tenant": "quiet"})
        assert st == 200                    # quiet tenant unaffected
        _, metrics = _get(srv.port, "/metrics")
        assert 'tpu_serve_shed_total{reason="quota"}' in metrics
    finally:
        srv.stop()


def _heap_order(srv):
    """Drain the admission heap in pop order (no scheduler thread:
    pure, deterministic WFQ inspection)."""
    import heapq

    heap = list(srv._pending)
    out = []
    while heap:
        out.append(heapq.heappop(heap)[-1].tenant)
    return out


def test_wfq_interleaves_tenants_fairly(setup):
    """Six queued requests from a bursting tenant, then one from a
    quiet tenant: WFQ places the quiet arrival right behind the
    burst's HEAD (its virtual finish time sits at the clock), not
    behind the whole backlog — FIFO would serve it seventh."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(
        eng, max_new_tokens=4, window=4,
        tenant_quotas=parse_tenant_quotas(["*=0:0:1"]))
    body = {"tokens": [1, 2, 3, 4, 5, 6], "max_new_tokens": 4}
    for _ in range(6):
        srv._enqueue(srv._parse_request(dict(body, tenant="burst")))
    srv._enqueue(srv._parse_request(dict(body, tenant="quiet")))
    order = _heap_order(srv)
    assert order.index("quiet") == 1, order
    # priority still dominates vft: a high-priority burst request
    # jumps the whole level
    srv._enqueue(srv._parse_request(
        dict(body, tenant="burst", priority=3)))
    assert _heap_order(srv)[0] == "burst"


def test_wfq_weights_scale_the_share(setup):
    """A weight-4 tenant's requests cost 1/4 the virtual time: with
    both backlogs queued together, the heavy tenant gets ~4 of every
    5 pops instead of strict interleave."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=1)
    srv = EngineServer(
        eng, max_new_tokens=4, window=4,
        tenant_quotas=parse_tenant_quotas(
            ["gold=0:0:4", "bronze=0:0:1"]))
    body = {"tokens": [1, 2, 3, 4, 5, 6], "max_new_tokens": 4}
    for _ in range(8):
        srv._enqueue(srv._parse_request(dict(body, tenant="gold")))
        srv._enqueue(srv._parse_request(dict(body, tenant="bronze")))
    first8 = _heap_order(srv)[:8]
    assert first8.count("gold") >= 6, first8


def test_preemption_by_page_eviction_completes_both(setup):
    """Page pressure + a higher-priority arrival: the low-priority
    running request is preempted (pages checkpointed + freed), the
    high-priority one admits, and the preempted one RESUMES and
    completes with full output — preemption + journal + metric all
    observable."""
    model, params = setup
    # pool of 8 pages (page=8 rows): one 30-token prompt + growth
    # fills ~5 pages, so two can't run cold together
    eng = ServingEngine(model, params, n_slots=2, chunk=8,
                        kv_paging=True, kv_pages=8)
    srv = EngineServer(eng, max_new_tokens=8, window=2)
    srv.start(host="127.0.0.1", port=0)
    try:
        results = {}

        def fire(key, payload):
            results[key] = _post(srv.port, payload)

        lo = threading.Thread(target=fire, args=("lo", {
            "tokens": list(range(1, 31)), "max_new_tokens": 8,
            "priority": 0, "tenant": "batch"}))
        lo.start()
        time.sleep(0.5)   # lo is decoding and holds most of the pool
        hi = threading.Thread(target=fire, args=("hi", {
            "tokens": list(range(40, 70)), "max_new_tokens": 8,
            "priority": 5, "tenant": "interactive"}))
        hi.start()
        lo.join(timeout=120)
        hi.join(timeout=120)
        assert results["hi"][0] == 200
        assert results["lo"][0] == 200
        lo_done = [e for e in results["lo"][1] if e.get("done")]
        hi_done = [e for e in results["hi"][1] if e.get("done")]
        assert lo_done and len(lo_done[0]["tokens"]) == 8
        assert hi_done and len(hi_done[0]["tokens"]) == 8
        st = json.loads(_get(srv.port, "/stats")[1])
        assert st["kv_preemptions"] >= 1
        _, metrics = _get(srv.port, "/metrics")
        assert "tpu_serve_kv_preemptions_total" in metrics
        # journal evidence: eviction AND resume events
        _, traces = _get(srv.port, "/debug/events?since=0")
        ev = json.loads(traces)
        names = [e.get("name") for e in ev.get("events", [])]
        assert "tpu_serve_kv_preempt" in names
        assert "tpu_serve_kv_resume" in names
        eng._pool.check()
    finally:
        srv.stop()


def test_kv_families_render_on_contiguous_engines(setup):
    """The KV/QoS metric families render (as zeros) even without
    paging, so scrapes see one schema."""
    model, params = setup
    eng = ServingEngine(model, params, n_slots=2)
    srv = EngineServer(eng, max_new_tokens=4)
    body = srv.render_metrics()
    for fam in ("tpu_serve_kv_pages_free",
                "tpu_serve_kv_pages_shared",
                "tpu_serve_kv_preemptions_total",
                "tpu_serve_kv_cow_copies_total",
                "tpu_serve_prefix_evictions_total"):
        assert fam in body, fam
